#!/usr/bin/env python
"""MoE training step cost on the real chip — the dispatch verdict, as a
GRID, not a point (VERDICT r4 #7).

Three dispatch formulations of the SAME training step (identical
routing, capacity drops, GShard choice-major priority, aux loss,
hand-VJP expert FFNs — differential-pinned leaf-for-leaf in
tests/test_moe.py):

- ``dense``: GShard's one-hot einsum movement. The [T, E, C] dispatch
  tensor is O(k*T^2*cf) ELEMENTS at fixed capacity factor (T=8192,
  cf=2, k=2: ~134M floats, ~0.5 GB in HBM) and its einsums are
  O(k*T^2*cf*d) MXU FLOPs — quadratic in tokens, independent of E.
- ``scatter``: O(T*d) scatter-add of token rows into the expert-slot
  buffer. On TPU a scatter lowers to a serialized per-row loop, and the
  autodiff TRANSPOSE of the combine's gather is a second scatter in the
  backward — r04 measured it at 0.59x dense (one point, E8/cf2).
- ``gather``: the round-5 formulation. The kept (token, choice) -> slot
  map is a bijection, so dispatch AND combine can be permutation
  GATHERS in both directions (custom VJPs route the backward through
  the inverse maps); the only scatters left are O(k*T) int32 slot
  bookkeeping. Gathers vectorize on TPU where scatters serialize.

The sweep varies E in {8, 32, 64} x capacity_factor in {1.0, 2.0} at
fixed token count and k — the expert-FFN FLOPs are E-invariant at fixed
tokens (each kept token runs k FFN passes), so every grid point does
the same useful work and the ratios isolate the movement cost. The
headline value stays the best dispatch at the r04 comparison shape
(d768/L6/E8/cf2), plus the MoE-LM EP family number with its measured
head-policy grid.

Emits one JSON line; written to ``MOE_r05.json`` when ``MOE_ARTIFACT``
is set. Timing: scan over steps in one program, best-of-REPS, scalar
readback (bench.py methodology).

Run: ``python bench_moe.py`` (real TPU). Smoke: ``BENCH_PLATFORM=cpu
MOE_TOKENS=256 MOE_D=64 MOE_STEPS=4 python bench_moe.py``.
"""

import json
import os
import sys

import jax

if os.environ.get("BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

D = int(os.environ.get("MOE_D", 768))
L = int(os.environ.get("MOE_LAYERS", 6))
E = int(os.environ.get("MOE_EXPERTS", 8))
TOKENS = int(os.environ.get("MOE_TOKENS", 8 * 1024))
K = int(os.environ.get("MOE_K", 2))
STEPS = int(os.environ.get("MOE_STEPS", 16))
REPS = int(os.environ.get("MOE_REPS", 3))
# MoE-LM family shape
SEQ = int(os.environ.get("MOE_SEQ", 512))
VOCAB = int(os.environ.get("MOE_VOCAB", 50304))
# sweep grid (VERDICT r4 #7): E x capacity_factor x dispatch at fixed
# FLOPs; fewer layers + steps than the headline — the grid buys its
# breadth with per-point cost, and movement cost per layer is what the
# ratios measure
SWEEP_E = [int(e) for e in
           os.environ.get("MOE_SWEEP_E", "8,32,64").split(",") if e]
SWEEP_CF = [float(c) for c in
            os.environ.get("MOE_SWEEP_CF", "1.0,2.0").split(",") if c]
SWEEP_L = int(os.environ.get("MOE_SWEEP_LAYERS", 2))
SWEEP_STEPS = int(os.environ.get("MOE_SWEEP_STEPS", 8))
SWEEP_REPS = int(os.environ.get("MOE_SWEEP_REPS", 2))

DISPATCHES = ("dense", "scatter", "gather")


def main() -> int:
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.models import init_moe_stack
    from distributed_llm_code_samples_tpu.parallel import train_moe_dense
    from distributed_llm_code_samples_tpu.utils.benchtime import (
        steps_per_sec)

    params = init_moe_stack(jax.random.PRNGKey(0), D, L, E)
    warm = make_seed_schedule(STEPS, random_seed=1)
    timed = make_seed_schedule(STEPS, random_seed=2)

    def measure(run_fn, p0=None, reps=REPS, n_steps=None):
        if n_steps is None:
            w, t = warm, timed
        else:
            w = make_seed_schedule(n_steps, random_seed=1)
            t = make_seed_schedule(n_steps, random_seed=2)
        return steps_per_sec(run_fn, params if p0 is None else p0,
                             w, t, reps, n_steps or STEPS)

    payload = {"metric": "moe_steps_per_sec",
               "unit": "steps/s",
               "shape": f"d{D}_L{L}_E{E}_k{K}_tok{TOKENS}",
               "device_kind": jax.devices()[0].device_kind}
    results = {}
    for dispatch in DISPATCHES:
        try:
            results[dispatch] = round(measure(
                lambda p, s, _disp=dispatch: train_moe_dense(
                    p, s, TOKENS, D, lr=0.1, k=K, aux_coef=0.01,
                    dispatch=_disp)), 4)
        except Exception as exc:  # noqa: BLE001
            results[dispatch] = (
                f"error: {type(exc).__name__}: {str(exc)[:160]}")
    for dispatch in DISPATCHES:
        payload[f"{dispatch}_steps_per_sec"] = results[dispatch]
    numeric = {k2: v for k2, v in results.items()
               if isinstance(v, float)}
    if numeric:
        win = max(numeric, key=numeric.get)
        payload["value"] = numeric[win]
        payload["dispatch"] = win
        if isinstance(results["dense"], float):
            for other in ("scatter", "gather"):
                if isinstance(results[other], float):
                    payload[f"{other}_vs_dense"] = round(
                        results[other] / results["dense"], 4)
        # a win must clear the measurement-noise band (run-to-run
        # jitter is ~±1.5%; best-of-REPS narrows but does not remove
        # it) or the verdict honestly reports a tie
        runner_up = max((v for k2, v in numeric.items() if k2 != win),
                        default=0.0)
        if runner_up and numeric[win] / runner_up > 1.05:
            payload["verdict"] = (
                f"{win} dispatch wins at the headline shape "
                f"({numeric[win] / runner_up:.2f}x the runner-up); see "
                "sweep for where each formulation holds")
        else:
            payload["verdict"] = (
                "throughput-equal at the headline shape (lead within "
                "the 5% noise band); see sweep")
    else:
        payload["value"] = 0.0

    # the E x capacity_factor x dispatch grid at fixed FLOPs
    if os.environ.get("MOE_SWEEP", "1") != "0":
        sweep = {}
        for e_n in SWEEP_E:
            sp = init_moe_stack(jax.random.PRNGKey(2), D, SWEEP_L, e_n)
            for cf in SWEEP_CF:
                point = {}
                for dispatch in DISPATCHES:
                    try:
                        point[dispatch] = round(measure(
                            lambda p, s, _d=dispatch, _c=cf:
                            train_moe_dense(
                                p, s, TOKENS, D, lr=0.1, k=K,
                                aux_coef=0.01, capacity_factor=_c,
                                dispatch=_d),
                            p0=sp, reps=SWEEP_REPS,
                            n_steps=SWEEP_STEPS), 4)
                    except Exception as exc:  # noqa: BLE001
                        point[dispatch] = (f"error: {type(exc).__name__}:"
                                           f" {str(exc)[:120]}")
                nums = {k2: v for k2, v in point.items()
                        if isinstance(v, float)}
                if nums:
                    point["best"] = max(nums, key=nums.get)
                sweep[f"E{e_n}_cf{cf}"] = point
        payload["sweep"] = sweep
        payload["sweep_shape"] = (f"d{D}_L{SWEEP_L}_k{K}_tok{TOKENS}_"
                                  f"steps{SWEEP_STEPS}")

    # MoE-LM family step (EP over the single available chip: same
    # sharded program, collectives degenerate)
    if os.environ.get("MOE_LM", "1") != "0":
        try:
            from distributed_llm_code_samples_tpu.models import init_moe_lm
            from distributed_llm_code_samples_tpu.parallel import (
                EXPERT_AXIS, make_mesh, train_moe_lm_ep)
            b = max(TOKENS // SEQ, 1)
            lm = init_moe_lm(jax.random.PRNGKey(1), VOCAB, D, L, E, SEQ)
            mesh = make_mesh({EXPERT_AXIS: jax.device_count()})
            # head policy measured (bench.py families convention):
            # oracle materializes [N, V] logits + softmax residual,
            # fused keeps logit tiles in VMEM (ops/pallas_xent.py)
            by_head = {}
            for h_impl in (None, "fused"):
                by_head[h_impl or "oracle"] = measure(
                    lambda p, s, _h=h_impl: train_moe_lm_ep(
                        p, s, b * SEQ, D, mesh, lr=0.1, seq_len=SEQ,
                        n_heads=max(D // 64, 1), k=K, aux_coef=0.01,
                        head_impl=_h), lm)
            win = max(by_head, key=by_head.get)
            payload["moe_lm_steps_per_sec"] = round(by_head[win], 4)
            payload["moe_lm_head"] = win
            payload["moe_lm_by_head"] = {k2: round(v, 4)
                                         for k2, v in by_head.items()}
            payload["moe_lm_shape"] = (f"d{D}_L{L}_E{E}_k{K}_T{SEQ}"
                                       f"_B{b}_V{VOCAB}")
        except Exception as exc:  # noqa: BLE001
            payload["moe_lm_steps_per_sec"] = (
                f"error: {type(exc).__name__}: {str(exc)[:160]}")

    print(json.dumps(payload))
    artifact = os.environ.get("MOE_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(payload, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
