#!/usr/bin/env python
"""MoE training step cost on the real chip — the dispatch verdict.

VERDICT r2 #8: the dense one-hot dispatch is GShard-faithful and
static-shaped, but its token movement is O(T*E*C*d) MXU work
(``T*E*C = k*T^2*capacity_factor`` — quadratic in tokens), while the
expert FFN itself is linear in T. This bench times the SAME training
step (``train_moe_dense``: top-2 routing, residual stack, aux loss,
hand-VJP expert FFNs) under both dispatch implementations at a
bench-scale shape, plus the MoE-LM EP step for the family number, and
records which dispatch the numbers defend.

Emits one JSON line; written to ``MOE_r03.json`` when ``MOE_ARTIFACT``
is set. Timing: scan over steps in one program, best-of-REPS, scalar
readback (bench.py methodology).

Run: ``python bench_moe.py`` (real TPU). Smoke: ``BENCH_PLATFORM=cpu
MOE_TOKENS=256 MOE_D=64 MOE_STEPS=4 python bench_moe.py``.
"""

import json
import os
import sys

import jax

if os.environ.get("BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

D = int(os.environ.get("MOE_D", 768))
L = int(os.environ.get("MOE_LAYERS", 6))
E = int(os.environ.get("MOE_EXPERTS", 8))
TOKENS = int(os.environ.get("MOE_TOKENS", 8 * 1024))
K = int(os.environ.get("MOE_K", 2))
STEPS = int(os.environ.get("MOE_STEPS", 16))
REPS = int(os.environ.get("MOE_REPS", 3))
# MoE-LM family shape
SEQ = int(os.environ.get("MOE_SEQ", 512))
VOCAB = int(os.environ.get("MOE_VOCAB", 50304))


def main() -> int:
    from distributed_llm_code_samples_tpu.data import make_seed_schedule
    from distributed_llm_code_samples_tpu.models import init_moe_stack
    from distributed_llm_code_samples_tpu.parallel import train_moe_dense
    from distributed_llm_code_samples_tpu.utils.benchtime import (
        steps_per_sec)

    params = init_moe_stack(jax.random.PRNGKey(0), D, L, E)
    warm = make_seed_schedule(STEPS, random_seed=1)
    timed = make_seed_schedule(STEPS, random_seed=2)

    def measure(run_fn, p0=None):
        return steps_per_sec(run_fn, params if p0 is None else p0,
                             warm, timed, REPS, STEPS)

    payload = {"metric": "moe_steps_per_sec",
               "unit": "steps/s",
               "shape": f"d{D}_L{L}_E{E}_k{K}_tok{TOKENS}",
               "device_kind": jax.devices()[0].device_kind}
    results = {}
    for dispatch in ("dense", "scatter"):
        try:
            results[dispatch] = round(measure(
                lambda p, s, _disp=dispatch: train_moe_dense(
                    p, s, TOKENS, D, lr=0.1, k=K, aux_coef=0.01,
                    dispatch=_disp)), 4)
        except Exception as exc:  # noqa: BLE001
            results[dispatch] = (
                f"error: {type(exc).__name__}: {str(exc)[:160]}")
    payload["dense_steps_per_sec"] = results["dense"]
    payload["scatter_steps_per_sec"] = results["scatter"]
    numeric = [v for v in results.values() if isinstance(v, float)]
    if len(numeric) == 2:
        ratio = results["scatter"] / results["dense"]
        payload["scatter_vs_dense"] = round(ratio, 4)
        payload["verdict"] = (
            "scatter dispatch wins: the dense one-hot einsums' "
            "O(k*T^2*cf*d) movement dominates at this scale"
            if ratio > 1.05 else
            "dense dispatch defended: XLA's einsum lowering beats the "
            "scatter/gather path at this scale"
            if ratio < 0.95 else "throughput-equal at this scale")
        payload["value"] = max(numeric)
        payload["dispatch"] = ("scatter" if results["scatter"]
                               >= results["dense"] else "dense")
    else:
        payload["value"] = numeric[0] if numeric else 0.0

    # MoE-LM family step (EP over the single available chip: same
    # sharded program, collectives degenerate)
    if os.environ.get("MOE_LM", "1") != "0":
        try:
            from distributed_llm_code_samples_tpu.models import init_moe_lm
            from distributed_llm_code_samples_tpu.parallel import (
                EXPERT_AXIS, make_mesh, train_moe_lm_ep)
            b = max(TOKENS // SEQ, 1)
            lm = init_moe_lm(jax.random.PRNGKey(1), VOCAB, D, L, E, SEQ)
            mesh = make_mesh({EXPERT_AXIS: jax.device_count()})
            # head policy measured (bench.py families convention):
            # oracle materializes [N, V] logits + softmax residual,
            # fused keeps logit tiles in VMEM (ops/pallas_xent.py)
            by_head = {}
            for h_impl in (None, "fused"):
                by_head[h_impl or "oracle"] = measure(
                    lambda p, s, _h=h_impl: train_moe_lm_ep(
                        p, s, b * SEQ, D, mesh, lr=0.1, seq_len=SEQ,
                        n_heads=max(D // 64, 1), k=K, aux_coef=0.01,
                        head_impl=_h), lm)
            win = max(by_head, key=by_head.get)
            payload["moe_lm_steps_per_sec"] = round(by_head[win], 4)
            payload["moe_lm_head"] = win
            payload["moe_lm_by_head"] = {k2: round(v, 4)
                                         for k2, v in by_head.items()}
            payload["moe_lm_shape"] = (f"d{D}_L{L}_E{E}_k{K}_T{SEQ}"
                                       f"_B{b}_V{VOCAB}")
        except Exception as exc:  # noqa: BLE001
            payload["moe_lm_steps_per_sec"] = (
                f"error: {type(exc).__name__}: {str(exc)[:160]}")

    print(json.dumps(payload))
    artifact = os.environ.get("MOE_ARTIFACT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump(payload, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
