"""Checkpoint / resume subsystem.

The reference has **no serialization anywhere** — final params are only
returned in-memory and printed (``train_ffns.py:383-384``); its only
"resume" story is seed-schedule reproducibility via ``--random_seed``
(``:350, :356-360``). This framework makes checkpoint/resume a first-class
subsystem (SURVEY.md section 5), built on the same deterministic
seeds-as-dataset design: a checkpoint is ``(params, step, seed schedule)``,
and restoring it mid-run continues the *exact* run — same data, same
gradients, same final params as an uninterrupted run.

Format (first-principles, like the rest of the framework): one directory per
step, ``step_{N}/`` containing ``arrays.npz`` (every pytree leaf, keyed by
its tree path) and ``meta.json`` (step, schedule, user metadata). Writes are
atomic: staged into ``step_{N}.tmp`` and ``os.rename``d, so ``latest_step``
never sees a torn checkpoint (a crash mid-write leaves only a ``.tmp``
directory, which restore ignores and the next save overwrites).

The publish path is crash-safe beyond rename atomicity (the CheckFreq
posture): array payloads are fsync'd and carry per-file CRC-32 checksums
in ``meta.json`` (npz: the container file; native: each leaf's ``.raw``
bytes), the staging dir and parent are fsync'd around the rename, and a
restore with ``step=None`` falls back to the NEWEST checkpoint that
*verifies* (``latest_verified_step``) — a truncated or bit-rotted latest
step costs one segment of recompute, never the run. ``keep_last`` bounds
the directory to the most recent k published steps.

Sharding-aware: ``save_checkpoint`` accepts arrays living on any
single-process sharding (``np.asarray`` assembles fully-addressable shards);
``restore_checkpoint`` takes an optional ``shardings`` pytree and
``device_put``s each leaf straight onto its mesh placement, so an FSDP run
restores to sharded buffers without ever materializing a replicated copy per
device. An optional orbax backend (``backend="orbax"``) delegates the array
I/O to ``orbax.checkpoint`` for multi-host/async use, same directory layout
one level down.

Multi-host (``jax.process_count() > 1``): filesystem mutations (staging,
npz write, atomic renames, restart cleanup) happen on process 0 only,
bracketed by ``sync_global_devices`` barriers so no process observes a
half-published step; the orbax save is collective (every process writes
its addressable shards), with host-local leaves lifted to
globally-replicated arrays first. The npz backend handles replicated
params (DDP) across processes; process-spanning *sharded* params (FSDP)
require ``backend="orbax"`` and say so in the error. Proven end-to-end by
``tests/test_multiprocess.py::test_two_process_checkpoint_resume``:
2-process kill-at-step-4 + resume equals the uninterrupted run, both
backends.
"""

from __future__ import annotations

import functools
import json
import os
import re
import shutil
import sys
import zlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")


class CorruptCheckpointError(RuntimeError):
    """An explicitly-requested checkpoint failed integrity verification."""


class NonFiniteParamsError(RuntimeError):
    """A training segment produced non-finite params (poisoned step)."""


class LossSpikeError(RuntimeError):
    """A training segment's param update jumped far beyond the previous
    segment's — the loss-spike signature (PaLM's rewind-on-spike
    scenario). Recoverable: the supervisor's rollback rung rewinds to
    the last verified checkpoint in-process (``runtime/failure.py``).
    Carries ``baseline`` (the pre-spike update norm) so the retry keeps
    the reference scale — a PERSISTENT spike re-fires on the retrained
    segment instead of slipping past a reset baseline."""

    def __init__(self, msg: str, baseline: float | None = None):
        super().__init__(msg)
        self.baseline = baseline

_ASYNC_WRITER = None
_ERRORS_SEEN = 0  # errors already reported by a previous wait_pending
_TMP_SEQ = 0      # unique tmp-dir suffixes for async staging


def _writer():
    """Process-wide native async checkpoint writer (lazy; 2 I/O threads)."""
    global _ASYNC_WRITER
    if _ASYNC_WRITER is None:
        from .runtime.native import AsyncCheckpointWriter
        _ASYNC_WRITER = AsyncCheckpointWriter(n_threads=2)
    return _ASYNC_WRITER


def wait_pending() -> None:
    """Block until every ``backend="native"`` checkpoint submitted by this
    process is published; raises if any write failed *since the last
    wait* — an old failure must not mask later successful saves or block
    an in-process restore of a still-good checkpoint."""
    global _ERRORS_SEEN
    if _ASYNC_WRITER is None:
        return
    _ASYNC_WRITER.wait()
    errs = _ASYNC_WRITER.errors()
    new = errs - _ERRORS_SEEN
    _ERRORS_SEEN = errs
    if new:
        raise RuntimeError(
            f"{new} async checkpoint write(s) failed "
            "(their step_*.tmp dirs are left behind for inspection)")


def _primary() -> bool:
    """Exactly one process owns filesystem mutations (dir staging, npz
    write, atomic renames) — the multi-host analogue of the reference
    writing results from rank 0 only (``train_ffns.py:193``)."""
    return jax.process_index() == 0


def _sync(tag: str) -> None:
    """Cross-process barrier (no-op single-process): keeps every process's
    view of the checkpoint directory consistent around primary-only
    mutations and collective orbax writes. ``ckpt_dir`` must be a shared
    filesystem — every process reads the steps the primary publishes."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt:{tag}")


def _agreed_latest_step(ckpt_dir: str) -> int | None:
    """Latest *verified* step as decided by the primary and broadcast, so
    every process takes the same resume-vs-restart branch. A divergent
    local view (per-host disk, NFS attribute-cache lag) would otherwise
    send processes to mismatched ``_sync`` barriers — a hang, not an
    error. Verification on the primary keeps the agreement anchored on a
    checkpoint everyone can actually restore."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        # only the primary pays the CRC scan; broadcast discards every
        # other process's answer anyway, so peers contribute a placeholder
        step = latest_verified_step(ckpt_dir) if _primary() else None
        step = int(multihost_utils.broadcast_one_to_all(
            np.int32(-1 if step is None else step)))
        return None if step < 0 else step
    return latest_verified_step(ckpt_dir)


# _np_dtype / _crc_file / _fsync_file / _fsync_dir: lifted to
# ``runtime/wire.py`` in round 16 (the serving wire transport shares the
# exact same CRC and fsync posture) and re-bound under their historical
# names at the END of this module — see the note there.


def _to_numpy(leaf) -> np.ndarray:
    """Host copy in an npz-safe dtype: extended dtypes (bfloat16, ...) are
    byte-views as unsigned ints — np.savez would otherwise write them as raw
    void and the restore would be unloadable. The true dtype travels in
    meta.json."""
    arr = np.asarray(leaf)
    if arr.dtype.kind == "V":  # ml_dtypes extension type
        arr = arr.view(f"u{arr.dtype.itemsize}")
    return arr


def _ensure_global_fn():
    """Multi-host orbax can only serialize *global* arrays. Returns a
    per-leaf converter (one shared all-devices mesh per save, not one per
    leaf): leaves that are still host-local (fresh params before the first
    training segment, or a replicated result pulled to one device) are
    identical on every process by the framework's determinism, so lift
    them to a globally-replicated array over all devices; process-spanning
    arrays pass through."""
    if jax.process_count() == 1:
        return lambda leaf: leaf
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("_ckpt",))
    sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    def ensure(leaf):
        if hasattr(leaf, "sharding") and not leaf.is_fully_addressable:
            return leaf  # already global
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx])

    return ensure


def _flatten(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = [jax.tree_util.keystr(p) for p, _ in flat]
    leaves = [leaf for _, leaf in flat]
    return names, leaves, treedef


def verify_checkpoint(path: str) -> tuple[bool, str]:
    """Integrity-check one published ``step_{N}`` dir: ``meta.json``
    parses and every checksummed payload file matches its recorded
    CRC-32. Checkpoints written before checksums existed (no
    ``checksums`` key) verify by file presence alone."""
    try:
        with open(os.path.join(path, "meta.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"meta.json unreadable: {type(e).__name__}: {e}"
    for fname, want in doc.get("checksums", {}).items():
        fpath = os.path.join(path, fname)
        if not os.path.exists(fpath):
            return False, f"{fname} missing"
        got = _crc_file(fpath)
        if got != want:
            return False, (f"{fname} checksum mismatch "
                           f"(crc32 {got:#010x} != recorded {want:#010x})")
    if doc.get("backend", "npz") == "npz" and "checksums" not in doc \
            and not os.path.exists(os.path.join(path, "arrays.npz")):
        return False, "arrays.npz missing"
    return True, "ok"


def latest_verified_step(ckpt_dir: str) -> int | None:
    """Highest published step that passes ``verify_checkpoint`` — the
    resume anchor. Corrupt steps are skipped (with a stderr note naming
    the damage) instead of failing the restore: recovery falls back to
    the newest checkpoint that still verifies."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = sorted((int(m.group(1)) for name in os.listdir(ckpt_dir)
                    if (m := _STEP_RE.match(name))), reverse=True)
    for step in steps:
        ok, reason = verify_checkpoint(os.path.join(ckpt_dir, f"step_{step}"))
        if ok:
            return step
        print(f"checkpoint: step_{step} failed verification ({reason}); "
              "falling back to an earlier step", file=sys.stderr)
    return None


def save_checkpoint(ckpt_dir: str, params: Any, step: int, seeds=None,
                    meta: dict | None = None, backend: str = "npz") -> str:
    """Write ``step_{step}/`` atomically; returns the final path.

    ``params`` is any pytree of arrays (sharded arrays are gathered via
    their addressable shards — single-process; multi-host goes through the
    orbax backend). ``seeds`` is the full seed schedule, saved so a resumed
    run replays the identical data stream.
    """
    names, leaves, _ = _flatten(params)
    if backend == "native" and any("/" in n for n in names):
        raise ValueError("native backend writes one file per leaf; tree "
                         f"paths may not contain '/': {names}")
    if jax.process_count() > 1 and backend != "orbax":
        # npz gathers through np.asarray, which only works when every
        # process holds the full value; process-spanning shards need the
        # collective orbax path
        for n, leaf in zip(names, leaves):
            if (hasattr(leaf, "is_fully_replicated")
                    and not leaf.is_fully_replicated
                    and not getattr(leaf, "is_fully_addressable", True)):
                raise ValueError(
                    f"leaf {n} spans processes and is not replicated; "
                    "the npz backend cannot gather it — use "
                    "backend='orbax' for multi-host sharded checkpoints")

    final = os.path.join(ckpt_dir, f"step_{step}")
    tmp = final + ".tmp"
    if backend == "native":
        # unique staging dir per submit: a re-save of the same step must
        # not race an in-flight worker on the same tmp path (the _STEP_RE
        # filter hides any crash-leftover .tmp.* dirs from latest_step)
        global _TMP_SEQ
        _TMP_SEQ += 1
        tmp = f"{final}.tmp.{os.getpid()}.{_TMP_SEQ}"
    if _primary():
        os.makedirs(ckpt_dir, exist_ok=True)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
    _sync(f"staged-{step}")  # tmp dir visible to all before collective I/O

    checksums = None  # per-file CRC-32 (primary-only; orbax opts out —
    #                   its own format carries internal integrity state)
    host_bufs = None
    if backend == "orbax":
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        # collective: every process writes its addressable shards
        ckptr.save(os.path.join(os.path.abspath(tmp), "arrays"),
                   jax.tree_util.tree_map(_ensure_global_fn(), params))
    elif backend != "native" and _primary():
        npz_path = os.path.join(tmp, "arrays.npz")
        np.savez(npz_path, **{n: _to_numpy(l)
                              for n, l in zip(names, leaves)})
        _fsync_file(npz_path)  # durable BEFORE the publishing rename
        checksums = {"arrays.npz": _crc_file(npz_path)}
    elif backend == "native" and _primary():
        # checksum the buffers the async worker will write: the bytes on
        # disk must equal these or the restore-side verify rejects them
        host_bufs = [np.ascontiguousarray(_to_numpy(l)) for l in leaves]
        checksums = {n + ".raw": zlib.crc32(b.tobytes())
                     for n, b in zip(names, host_bufs)}
    # metadata from array attributes only — no host fetch (multi-host arrays
    # are not fully addressable; orbax handles their device I/O above)
    doc = {"step": int(step), "backend": backend, "leaf_names": names,
           "leaf_shapes": [list(np.shape(l)) for l in leaves],
           "leaf_dtypes": [np.dtype(getattr(l, "dtype", type(l))).name
                           for l in leaves]}
    if checksums is not None:
        doc["checksums"] = checksums
    if seeds is not None:
        doc["seeds"] = np.asarray(seeds).tolist()
    if meta:
        doc["meta"] = meta
    if _primary():
        meta_path = os.path.join(tmp, "meta.json")
        with open(meta_path, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        if backend == "native":
            # async: the native worker pool copies the buffers now, writes
            # the .raw leaves and atomically renames tmp -> final off this
            # thread (native/ckpt_writer.cpp) — training overlaps the I/O.
            # Re-publishing the SAME step drops the old version first
            # (brief no-version window; distinct steps are unaffected).
            if os.path.exists(final):
                shutil.rmtree(final)
            _writer().submit(tmp, final, names, host_bufs)
            if jax.process_count() > 1:
                # peers read the step right after the barrier; asynchrony
                # is a single-host feature
                wait_pending()
        else:
            _fsync_dir(tmp)  # entries durable before they become visible
            old = None
            if os.path.exists(final):
                # keep the previous version valid until the new one is
                # published: move it aside (its .tmp suffix hides it from
                # latest_step), swap in the new dir, then drop it
                old = final + ".old.tmp"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(final, old)
            os.rename(tmp, final)  # atomic publish
            _fsync_dir(ckpt_dir)   # the rename itself survives a crash
            if old is not None:
                shutil.rmtree(old)
    _sync(f"published-{step}")  # no process proceeds past an unpublished step
    return final


def latest_step(ckpt_dir: str) -> int | None:
    """Highest completed (published, non-``.tmp``) step, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [int(m.group(1)) for name in os.listdir(ckpt_dir)
             if (m := _STEP_RE.match(name))]
    return max(steps) if steps else None


def read_meta(ckpt_dir: str, step: int) -> dict:
    """The user ``meta`` dict saved with ``step_{step}`` (empty when the
    checkpoint predates it or carries none) — the elastic-resume path
    reads the save-time ``data_shards`` from here."""
    try:
        with open(os.path.join(ckpt_dir, f"step_{step}",
                               "meta.json")) as f:
            return json.load(f).get("meta", {}) or {}
    except (OSError, ValueError):
        return {}


def restore_checkpoint(ckpt_dir: str, target: Any, step: int | None = None,
                       shardings: Any = None, verify: bool = True):
    """Restore ``(params, step, seeds)``.

    ``target`` is an example pytree (same structure/dtypes as saved — e.g.
    the freshly-initialized params) used to rebuild the tree. ``shardings``,
    if given, is a matching pytree (or single sharding) of placements; each
    leaf is ``device_put`` directly onto it. ``verify=False`` skips the
    CRC pass for a step the caller has ALREADY verified (the resume path:
    ``latest_verified_step`` just read every payload byte — re-reading a
    multi-GB checkpoint to re-checksum it doubles the restore I/O, and on
    multi-host it would re-run per-host verification of a step the
    primary's broadcast already anchored).
    """
    wait_pending()  # a native-backend save from this process may be in flight
    if step is None:
        # fall back to the newest checkpoint that VERIFIES: a torn or
        # bit-rotted latest step must cost a segment, not the run
        step = latest_verified_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(
                f"no verified checkpoint under {ckpt_dir}")
        verify = False  # just verified, byte for byte
    path = os.path.join(ckpt_dir, f"step_{step}")
    if verify:
        ok, reason = verify_checkpoint(path)
        if not ok:
            # an explicitly-requested step never falls back silently
            raise CorruptCheckpointError(f"{path}: {reason}")
    with open(os.path.join(path, "meta.json")) as f:
        doc = json.load(f)

    names, leaves, treedef = _flatten(target)
    if doc.get("leaf_names") != names:
        raise ValueError(
            f"checkpoint tree {doc.get('leaf_names')} != target tree {names}")
    saved_shapes = [tuple(s) for s in doc.get("leaf_shapes", [])]
    target_shapes = [tuple(np.shape(l)) for l in leaves]
    if saved_shapes and saved_shapes != target_shapes:
        raise ValueError(
            f"checkpoint shapes {saved_shapes} != target shapes "
            f"{target_shapes} — the checkpoint is from a different model "
            "config (layers/model_size)")
    saved_dtypes = doc.get("leaf_dtypes", [])
    target_dtypes = [np.dtype(getattr(l, "dtype", type(l))).name
                     for l in leaves]
    if saved_dtypes and saved_dtypes != target_dtypes:
        raise ValueError(
            f"checkpoint dtypes {saved_dtypes} != target dtypes "
            f"{target_dtypes} — resuming would silently continue in the "
            "saved dtype; re-init the run or match --dtype")
    if doc.get("backend") == "orbax":
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        # restore WITH the target: an untargeted restore returns a plain
        # dict whose tree_leaves come out in dict-key-sorted order, not the
        # target NamedTuple's field order — for MoEStackParams that silently
        # permuted (wg, w1, w2) into (w1, w2, wg)
        params = ckptr.restore(os.path.join(os.path.abspath(path), "arrays"),
                               item=target)
        new_leaves = jax.tree_util.tree_leaves(params)
    elif doc.get("backend") == "native":
        new_leaves = []
        for n, dt_name, shape in zip(names, doc["leaf_dtypes"],
                                     doc["leaf_shapes"]):
            dt = _np_dtype(dt_name)
            raw = np.fromfile(os.path.join(path, n + ".raw"), np.uint8)
            new_leaves.append(raw.view(dt).reshape(shape))
    else:
        dtypes = [_np_dtype(n) for n in doc.get("leaf_dtypes", [])] \
            or [None] * len(names)
        with np.load(os.path.join(path, "arrays.npz")) as z:
            new_leaves = [z[n] if dt is None or z[n].dtype == dt
                          else z[n].view(dt)
                          for n, dt in zip(names, dtypes)]

    if shardings is not None:
        sh_leaves = jax.tree_util.tree_leaves(shardings)
        if len(sh_leaves) == 1:
            sh_leaves = sh_leaves * len(new_leaves)
        if len(sh_leaves) != len(new_leaves):
            raise ValueError(
                f"shardings tree has {len(sh_leaves)} leaves but params "
                f"tree has {len(new_leaves)} — pass one sharding per leaf "
                "(or a single sharding for all)")
        new_leaves = [_owned_leaf(l, s)
                      for l, s in zip(new_leaves, sh_leaves)]
    else:
        new_leaves = [_owned_leaf(np.asarray(l)) for l in new_leaves]
    params = jax.tree_util.tree_unflatten(treedef, new_leaves)
    seeds = np.asarray(doc["seeds"], np.int32) if "seeds" in doc else None
    return params, int(doc["step"]), seeds


def _owned_leaf(arr, sharding=None):
    """Place a restored host array on device as FRESH, exclusively-owned
    buffers — a jitted copy, never a bare ``device_put``.

    ``device_put`` of a host array may zero-copy alias the numpy buffer
    on CPU, and a replicating sharding can back several device views
    with shared memory. Trainers DONATE restored leaves into their step
    programs (``run_with_checkpointing`` threads ``(params, opt_state)``
    straight into ``launch(donate_argnums=...)``), and donating a
    shared/aliased buffer lets XLA reuse memory that another view still
    reads — the rare wrong-resume race this exact test pinned:
    ``tests/test_checkpoint.py::test_stateful_fsdp_checkpoint_resume_is_
    exact`` flaked under non-alphabetical orderings with 100%-divergent
    resumes. jit outputs never alias non-donated inputs (the
    ``models.ffn_stack.clone_params`` guarantee), so the copy below is
    the same ownership contract every launcher already applies to params
    — extended to everything a restore produces."""
    if sharding is not None:
        # no host round-trip: multi-host (orbax) restores hand over
        # global arrays that are not fully addressable — the jitted copy
        # reshards them on device, numpy inputs upload as before
        return _sharded_copy_fn(sharding)(arr)
    return _owned_copy_fn()(np.asarray(arr))


@functools.lru_cache(maxsize=256)
def _sharded_copy_fn(sharding):
    """One cached jit per target sharding: a per-leaf fresh ``jax.jit``
    would re-trace (and re-compile) every leaf of every restore."""
    return jax.jit(jnp.copy, out_shardings=sharding)


@functools.lru_cache(maxsize=1)
def _owned_copy_fn():
    return jax.jit(jnp.copy)


def _leaf_finite(leaf) -> bool:
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        # multi-host shard: np.asarray would raise — each process checks
        # the shards it can see; the guard still catches the poison
        # wherever it lives (non-finite grads replicate through psums)
        return all(_leaf_finite(s.data) for s in leaf.addressable_shards)
    arr = np.asarray(leaf)
    if arr.dtype.kind in "iub":
        return True  # integer state (Adam counts, seeds) is always finite
    if arr.dtype.kind not in "fc":  # ml_dtypes extension types (bf16, fp8)
        arr = np.asarray(leaf, np.float32)
    return bool(np.all(np.isfinite(arr)))


def tree_finite(tree) -> bool:
    """True iff every floating leaf of the pytree is free of NaN/Inf."""
    return all(_leaf_finite(l) for l in jax.tree_util.tree_leaves(tree))


def _emit_event(on_event, payload: dict) -> None:
    if on_event is not None:
        try:
            on_event(payload)
        except Exception:  # noqa: BLE001 — observability never kills a run
            pass


def _prune_old_steps(ckpt_dir: str, keep_last: int) -> None:
    """Drop all but the newest ``keep_last`` published steps (primary
    only; callers barrier afterwards in multi-host runs)."""
    steps = sorted(int(m.group(1)) for name in os.listdir(ckpt_dir)
                   if (m := _STEP_RE.match(name)))
    for step in steps[:-keep_last] if keep_last > 0 else []:
        shutil.rmtree(os.path.join(ckpt_dir, f"step_{step}"),
                      ignore_errors=True)


def run_with_checkpointing(train_fn, params, seeds, *args,
                           ckpt_dir: str, every: int = 0, resume: bool = True,
                           backend: str = "npz", seeds_divisor: int = 1,
                           stateful: bool = False, optimizer=None,
                           thread_state: bool | None = None,
                           restore_shardings=None, chaos=None,
                           nonfinite: str | None = None, keep_last: int = 0,
                           on_event=None, guard=None, guard_state=None,
                           spike_factor: float = 0.0,
                           spike_baseline: float | None = None,
                           elastic: bool = True,
                           in_graph_chaos: bool = False,
                           **kwargs):
    """Drive any strategy launcher (uniform L4 signature,
    ``fn(params, seeds, batch, d, **kw)``) with periodic checkpointing.

    The schedule is chunked into ``every``-step segments (0 = one segment);
    after each segment the params and the *full* schedule are saved under
    ``step_{completed}``. On ``resume``, the latest checkpoint's params and
    schedule are authoritative — a run killed between segments continues
    exactly where it stopped and lands on the same final params as an
    uninterrupted run (allclose-verified in tests/test_checkpoint.py).
    Passing a *longer* schedule than the saved one extends the run: the
    completed prefix keeps its saved data, the extra steps train on the new
    schedule's tail. ``resume=False`` clears existing ``step_*`` dirs first,
    so a later resume can't pick up a stale higher step from a previous run.

    For data-parallel strategies, ``every`` must be divisible by the
    data-axis size (the strided seed split asserts divisibility,
    ``train_ffns.py:182`` semantics) — pass it as ``seeds_divisor`` so a
    bad value fails *here*, up front, instead of as a divisibility assert
    deep inside the strategy (possibly after a restore mid-run).

    Resilience hooks (``runtime/chaos.py`` + ``runtime/failure.py``):
    ``chaos`` is a ``FaultPlan`` whose in-segment faults wrap ``train_fn``
    and whose publish faults fire after each ``save_checkpoint``;
    ``nonfinite`` arms the poisoned-step guard — ``"skip"`` reverts to the
    pre-segment state and advances past the segment WITHOUT checkpointing
    the non-finite params (a later restart may legitimately retrain those
    steps from the last checkpoint — if the poison was transient they
    then apply cleanly), ``"raise"`` raises ``NonFiniteParamsError`` for a
    supervisor to turn into a restart; ``keep_last`` keeps only the
    newest k published steps (0 = keep all); ``on_event`` receives one
    dict per noteworthy recovery event (structured logging).

    Self-healing surface (round 8, DESIGN.md section 14):

    - ``guard`` (a ``runtime.guardrails.GuardrailConfig``) threads the
      in-graph guardrail through every segment: the trainer is called
      with ``guard``/``guard_state``/``return_guard=True`` (the
      single/ddp/fsdp/lm surface), the returned ``GuardState``
      (skip/overflow counters, live loss scale) carries across segments,
      and each segment whose counters advanced emits one ``anomaly``
      event — the per-chunk counter flow the telemetry stream records.
      With ``in_graph_chaos=True`` (an explicit opt-in for data
      families whose seeds carry the poison into a float gradient —
      the FFN family; cli passes it), chaos nan/inf faults are injected
      IN-GRAPH via seed poisoning
      (``FaultPlan.poison_segment_seeds``) so they exercise the
      guardrail, not the segment-level ``nonfinite`` readback.
    - ``spike_factor > 0`` arms the segment-delta spike guard: after
      each finite segment, the global L2 norm of the params update is
      compared against the previous segment's; a jump beyond
      ``spike_factor``x raises ``LossSpikeError`` BEFORE the segment is
      checkpointed — the supervisor's rollback rung rewinds to the last
      verified step and retrains (transient spikes retrain cleanly).
    - ``elastic`` (default on): a resume whose checkpoint was saved
      under a different data-shard count N than the current
      ``seeds_divisor`` M re-strides the remaining schedule to preserve
      the save-time global batch — scale-DOWN (M | N) passes
      ``seed_accum = N/M`` to the trainer (each survivor
      gradient-accumulates the lost ranks' seeds; the update sequence,
      and hence the loss trajectory, matches the uninterrupted N-device
      run), scale-UP (N | M) continues with the new M-seed global batch
      (deterministic batch order, new math — logged, not hidden). Any
      other N/M pair fails loudly.
    """
    seeds = np.asarray(seeds)
    if seeds_divisor > 1:
        if every > 0 and every % seeds_divisor:
            raise ValueError(
                f"checkpoint every={every} must be a multiple of the "
                f"data-shard count {seeds_divisor}: each segment's seeds "
                "are split strided across the data axis")
        if len(seeds) % seeds_divisor:
            raise ValueError(
                f"{len(seeds)} seeds do not divide across "
                f"{seeds_divisor} data shards")
    # with an optimizer AND thread_state=True (opt-in: the trainer must
    # support the opt_state/return_state surface, e.g. train_ddp), the
    # checkpointed tree is (params, opt_state) and the state threads
    # through each segment — an interrupted Adam run resumes its
    # statistics exactly. Otherwise the optimizer passes straight through
    # to the trainer and the resume rejection guards genuinely stateful
    # rules (Optimizer.stateless is the single source of truth).
    thread = bool(thread_state)
    if optimizer is not None and not thread:
        kwargs["optimizer"] = optimizer
        stateful = stateful or not getattr(optimizer, "stateless", False)
        optimizer = None
    opt_state = optimizer.init(params) if optimizer is not None else None
    tree = (params, opt_state) if optimizer is not None else params

    start = 0
    wait_pending()  # flush any in-flight native saves before reading state
    if resume and (agreed := _agreed_latest_step(ckpt_dir)) is not None:
        if stateful and optimizer is None and agreed > 0:
            # only params are checkpointed on this path: resuming/extending
            # a partly-trained run would re-init optimizer state (mu/nu/
            # count back to zero) and silently change the math vs an
            # uninterrupted run. Fail loudly instead.
            raise ValueError(
                f"cannot resume a stateful-optimizer run from step "
                f"{agreed}: optimizer state is not checkpointed for this "
                "trainer; pass resume=False (--no_resume) to retrain from "
                "step 0, or use the stateless sgd optimizer")
        # restore_shardings: place restored leaves straight onto their
        # mesh layout (FSDP's 1/n shards, fsdp.checkpoint_shardings) —
        # without it a big resume materializes params + full Adam state
        # replicated on one device, the spike FSDP exists to avoid
        # verify=False: the agreed step was verified by
        # _agreed_latest_step (on the primary, whose broadcast anchors
        # every process) — re-checksumming here would double the restore
        # I/O and re-introduce per-host verification divergence
        tree, start, saved = restore_checkpoint(
            ckpt_dir, tree, step=agreed, shardings=restore_shardings,
            verify=False)
        if optimizer is not None:
            params, opt_state = tree
        else:
            params = tree
        if saved is not None and len(saved):
            if len(seeds) > len(saved):
                # a longer re-run extends the saved run: completed steps keep
                # their saved data, the extra steps use the new schedule
                seeds = np.concatenate([saved, seeds[len(saved):]])
            else:
                seeds = saved  # saved schedule is authoritative on resume
        # ---- topology-elastic resume (docstring): the saved data-shard
        # count is authoritative for the remaining schedule's striding
        saved_shards = read_meta(ckpt_dir, agreed).get("data_shards")
        divisor = max(1, seeds_divisor)
        if saved_shards and saved_shards != divisor:
            if not elastic:
                raise ValueError(
                    f"checkpoint step_{agreed} was saved under "
                    f"{saved_shards} data shards but this run has "
                    f"{divisor} (elastic=False)")
            if saved_shards % divisor == 0:
                accum = saved_shards // divisor
                import inspect
                try:
                    ps = inspect.signature(train_fn).parameters
                    has_surface = ("seed_accum" in ps or any(
                        p.kind is inspect.Parameter.VAR_KEYWORD
                        for p in ps.values()))
                except (TypeError, ValueError):
                    has_surface = True
                if not has_surface:
                    raise ValueError(
                        f"elastic resume from {saved_shards} shards onto "
                        f"{divisor} needs {accum}-way seed accumulation, "
                        f"but {getattr(train_fn, '__name__', train_fn)} "
                        "has no seed_accum surface (ddp/fsdp have one)")
                kwargs["seed_accum"] = accum
                seeds_divisor = saved_shards  # global batch preserved
            elif divisor % saved_shards == 0:
                accum = 1  # scale-up: the NEW global batch takes over
                seeds_divisor = divisor
            else:
                raise ValueError(
                    f"elastic resume needs the save-time shard count "
                    f"({saved_shards}) and the current one ({divisor}) "
                    "to divide one another (M|N or N|M)")
            if every > 0 and every % seeds_divisor:
                raise ValueError(
                    f"checkpoint every={every} does not tile the "
                    f"{seeds_divisor}-seed global batch preserved by "
                    "the elastic resume")
            if len(seeds) % seeds_divisor:
                raise ValueError(
                    f"{len(seeds)} seeds do not divide across the "
                    f"{seeds_divisor}-seed elastic global batch")
            _emit_event(on_event, {
                "event": "elastic_resume", "step": start,
                "saved_shards": int(saved_shards),
                "current_shards": int(divisor),
                "seed_accum": int(accum),
                "n_devices": jax.device_count()})
    else:
        if _primary() and os.path.isdir(ckpt_dir):
            # restart: drop stale step_* dirs so a later resume can't pick
            # up a higher step from a previous run
            for name in os.listdir(ckpt_dir):
                if _STEP_RE.match(name):
                    shutil.rmtree(os.path.join(ckpt_dir, name))
        _sync("restart-cleared")
        # publish step_0 so the schedule survives a crash in segment 1
        save_checkpoint(ckpt_dir, tree, 0, seeds, backend=backend,
                        meta={"data_shards": int(max(1, seeds_divisor)),
                              "n_devices": jax.device_count()})
    # every published step records the EFFECTIVE data-shard count (the
    # global batch in seeds) — the anchor a later elastic resume restrides
    # the remaining schedule against
    ckpt_meta = {"data_shards": int(max(1, seeds_divisor)),
                 "n_devices": jax.device_count()}
    gstate = None
    g_seen = None
    if guard is not None:
        from .runtime.guardrails import host_state, summarize
        gstate = host_state(guard_state, guard)
        g_seen = summarize(gstate)
        kwargs = dict(kwargs, guard=guard)
    # spike-guard baseline: fresh runs baseline on their first segment;
    # a rollback/restart retry passes the PRE-SPIKE baseline back in
    # (LossSpikeError.baseline via the supervisor) so a persistent spike
    # re-fires on the retrained segment instead of re-baselining on it
    prev_delta = spike_baseline
    total = len(seeds)
    chunk = every if every > 0 else total
    if chaos is not None:
        # publish faults only fire ON a publish boundary; an off-boundary
        # step would silently never inject (the operator would believe
        # torn-checkpoint recovery was exercised when nothing happened)
        for f in getattr(chaos, "faults", ()):
            if f.kind in ("corrupt_ckpt", "kill") and (
                    f.step > total
                    or (f.step % chunk and f.step != total)):
                raise ValueError(
                    f"--chaos {f.kind}@{f.step} can never fire: publish "
                    f"faults key on checkpoint publishes, which happen "
                    f"at steps {chunk}, {2 * chunk}, ... {total} "
                    f"(every={every}, {total} steps)")
    while start < total:
        n = min(chunk, total - start)
        fn = train_fn
        seg_seeds = seeds[start:start + n]
        if chaos is not None:
            # in_graph_chaos=True routes nan/inf faults through seed
            # poisoning into the compiled chunk (the guardrail must
            # catch them). It is an EXPLICIT opt-in for callers who
            # know the data family carries the poison into a float
            # gradient (cli does, for the FFN family): families whose
            # data layer strips the bits (the LM's integer token draws)
            # would consume the fault without ever firing it — a chaos
            # drill that vacuously passes. Default: host-level poison,
            # which fires everywhere (guardrails or not).
            chaos.begin_segment(start, n,
                                in_graph=bool(in_graph_chaos)
                                and guard is not None)
            fn = chaos.wrap(train_fn)
            seg_seeds = chaos.poison_segment_seeds(seg_seeds)
        gkw = ({} if guard is None
               else {"guard_state": gstate, "return_guard": True})
        if optimizer is not None:
            out = fn(params, seg_seeds, *args, optimizer=optimizer,
                     opt_state=opt_state, return_state=True, **gkw,
                     **kwargs)
        else:
            out = fn(params, seg_seeds, *args, **gkw, **kwargs)
        if guard is not None:
            out, gstate = out
        if optimizer is not None:
            new_params, new_opt = out
            tree = (new_params, new_opt)
        else:
            new_params = out
            new_opt = None
            tree = new_params
        jax.block_until_ready(tree)
        if guard is not None:
            from .runtime.guardrails import anomaly_delta, summarize
            cur = summarize(gstate)
            delta = anomaly_delta(g_seen, cur, start + n,
                                  [start + 1, start + n])
            if delta is not None:
                _emit_event(on_event, dict(delta, event="anomaly"))
            g_seen = cur
        if nonfinite and not tree_finite(tree):
            if nonfinite == "raise":
                err = NonFiniteParamsError(
                    f"non-finite params after steps "
                    f"{start + 1}..{start + n}")
                # the live guard state survives the rollback rung: the
                # supervisor threads it back in so the dynamic loss
                # scale / counters don't reset on an in-process rewind
                err.guard_state = gstate
                raise err
            # skip: the poisoned step is never checkpointed; params stay
            # at the pre-segment state and the schedule advances past it
            print(f"checkpoint: non-finite params after steps "
                  f"{start + 1}..{start + n}; skipping the poisoned "
                  "segment (not checkpointed)", file=sys.stderr)
            _emit_event(on_event, {"event": "nonfinite_skip",
                                   "steps": [start + 1, start + n]})
            start += n
            continue
        if spike_factor > 0:
            # segment-delta spike guard (docstring): a finite but wildly
            # out-of-scale update is the loss-spike signature — refuse
            # to checkpoint it and let the supervisor's rollback rung
            # rewind to the last verified step
            from .runtime.guardrails import delta_norm
            delta = delta_norm(params, new_params)
            if (prev_delta is not None and prev_delta > 0
                    and delta > spike_factor * prev_delta):
                _emit_event(on_event, {
                    "event": "loss_spike",
                    "steps": [start + 1, start + n],
                    "delta": round(delta, 6),
                    "baseline": round(prev_delta, 6),
                    "factor": spike_factor})
                err = LossSpikeError(
                    f"update norm {delta:.4g} after steps "
                    f"{start + 1}..{start + n} exceeds {spike_factor}x "
                    f"the previous segment's {prev_delta:.4g} — "
                    "loss-spike rollback", baseline=prev_delta)
                err.guard_state = gstate  # see the nonfinite raise above
                raise err
            prev_delta = delta
        params = new_params
        if optimizer is not None:
            opt_state = new_opt
        start += n
        # with backend="native" this returns immediately (buffers copied);
        # the next segment's training overlaps the disk write
        path = save_checkpoint(ckpt_dir, tree, start, seeds,
                               backend=backend, meta=ckpt_meta)
        # one event per published segment: structured progress for the
        # supervisor's log AND its hang-detector re-arm (failure.py)
        _emit_event(on_event, {"event": "published", "step": start,
                               "steps": [start - n + 1, start]})
        if keep_last > 0:
            if _primary():
                _prune_old_steps(ckpt_dir, keep_last)
            _sync(f"pruned-{start}")
        if chaos is not None:
            wait_pending()  # publish faults need the async write landed
            if _primary():
                # one process owns the injected damage, like every other
                # filesystem mutation — P processes each truncating the
                # same file would compound frac and fire P audit events
                chaos.after_publish(start, path)
    wait_pending()  # durable-on-return contract for the native backend
    return params


# Integrity/durability primitives — lifted verbatim to runtime/wire.py
# (round 16: the serving fleet's wire-format KV handoff shares the exact
# same CRC-32 and fsync/tmp-rename posture) and re-bound here under
# their historical private names so every existing caller and contract
# test keeps working. Imported at the END of the module because
# runtime/__init__ pulls runtime.failure, which imports this module's
# late definitions (run_with_checkpointing) — a top-of-file import would
# close that cycle before they exist.
from .runtime import wire as _wire  # noqa: E402

_crc_file = _wire.crc_file
_fsync_file = _wire.fsync_file
_fsync_dir = _wire.fsync_dir
_np_dtype = _wire.np_dtype
