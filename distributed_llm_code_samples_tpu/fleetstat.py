"""`fleetstat` — the live fleet status surface.

The router publishes ONE atomic JSON status document per scheduling
round (throttled; ``decode/fleet.py`` via ``wire.publish_json``,
``runtime/telemetry.py`` ``STATUS_FILENAME``): per-engine liveness,
role, serving version, queue depth, pool watermarks, deploy state,
decision counters, and last-interval throughput. This tool renders it
— once, or as a live tail (``--follow``) that exits when the fleet
drains. Because the doc only ever REPLACES atomically, a read
mid-drill (workers being SIGKILLed, deploys mid-roll) sees the old
document or the new one, never a torn hybrid — the same guarantee the
checkpoint layer earned in round 6, applied to the ops plane.

Deliberately jax-free (stdlib only): the operator's terminal must not
pay a backend import to ask "is the fleet alive".

Exit codes: 0 = status rendered (a drained doc under ``--follow``
ends the tail); 2 = no status document at the given path (or none
appeared within ``--max_s`` under ``--follow``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .runtime.telemetry import STATUS_FILENAME


def _resolve(path: str) -> str:
    """DIR (a router metrics dir holding fleet_status.json) or the
    status file itself."""
    if os.path.isdir(path):
        return os.path.join(path, STATUS_FILENAME)
    return path


def _load(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return None
    except ValueError:
        # racing the atomic replace is impossible (rename is atomic);
        # an unparseable doc is real damage — surface it
        raise


def render(doc: dict) -> str:
    age = max(0.0, time.time() - float(doc.get("t") or 0.0))
    state = "DRAINED" if doc.get("drained") else "SERVING"
    tps = doc.get("tokens_per_sec_last_interval")
    out = [f"fleet status @ round {doc.get('round')} "
           f"(age {age:.1f}s) — {state}, "
           f"{doc.get('tokens_generated')} token(s)"
           + (f", {tps} tok/s last interval" if tps is not None
              else "")]
    for eid, e in sorted((doc.get("engines") or {}).items()):
        if not e.get("alive"):
            if e.get("retired"):
                # an autoscale scale-down, not a casualty: the member
                # drained zero-shed and left on purpose
                out.append(f"  {eid:4s} RETIRED (drained by "
                           "scale-down)")
            else:
                out.append(f"  {eid:4s} DEAD (killed at round "
                           f"{e.get('killed_at_round')})")
            continue
        # a worker-backed member names its socket family; a TCP link
        # that has survived reconnects says so (round 22)
        fam = ""
        if e.get("family"):
            fam = f" <{e['family']}>"
            if e.get("reconnects"):
                fam = f" <{e['family']}, {e['reconnects']} reconnect(s)>"
        # host-RAM spill tier (round 23): only shown when the engine
        # holds spilled blocks or has ever restored — a tier-less
        # engine's line stays byte-identical to the pre-v17 render
        spill = ""
        if e.get("spill_tier_blocks") or e.get("spill_restores"):
            spill = (f"  spill {e.get('spill_tier_blocks')} blk "
                     f"({e.get('spill_restores')} restore(s))")
        out.append(f"  {eid:4s} [{e.get('role')}]{fam} v"
                   f"{e.get('serving_version')}  waiting "
                   f"{e.get('waiting')}  active {e.get('active')}  "
                   f"free {e.get('free_blocks')} blk "
                   f"(+{e.get('evictable_blocks')} evictable)  util "
                   f"{e.get('utilization')}{spill}  last step "
                   f"{(e.get('last_step_s') or 0.0) * 1e3:.1f} ms")
    tens = doc.get("tenants") or {}
    for t, c in sorted(tens.items()):
        delta = c.get("shed_delta")
        out.append(f"  tenant {t:10s} in-flight {c.get('in_flight')}  "
                   f"offered {c.get('offered')}  shed {c.get('shed')}"
                   + (f" (+{delta} this interval)" if delta else ""))
    a = doc.get("autoscale")
    if a:
        cd = a.get("cooldown_remaining") or 0
        last = (f"{a.get('last_event')} ({a.get('last_reason')}) at "
                f"round {a.get('last_round')}"
                if a.get("last_event") else "none yet")
        out.append(f"  autoscale: {a.get('engines')}/"
                   f"{a.get('target_engines')} engines "
                   f"(bounds {a.get('min_engines')}.."
                   f"{a.get('max_engines')})  last decision {last}  "
                   f"cooldown {cd} round(s)  "
                   f"+{a.get('scale_ups')}/-{a.get('scale_downs')} "
                   "lifetime")
    al = doc.get("alerts")
    if al is not None:
        act = al.get("active") or []
        out.append(f"  alerts: {len(act)} active  "
                   f"({al.get('fired')} fired / "
                   f"{al.get('resolved')} resolved lifetime)")
        for a in act:
            bits = [f"    ALERT {a.get('detector')} "
                    f"[{a.get('severity')}] since round "
                    f"{a.get('since_round')}"]
            if a.get("burn_fast") is not None:
                bits.append(f"burn fast {a['burn_fast']} / slow "
                            f"{a['burn_slow']}")
            for k in ("waiting", "imbalance", "stalled_rounds",
                      "incidents"):
                if a.get(k) is not None:
                    bits.append(f"{k} {a[k]}")
            out.append("  ".join(bits))
    c = doc.get("counters") or {}
    out.append("  counters: " + ", ".join(
        f"{k} {c.get(k)}" for k in ("routed", "handoffs", "migrations",
                                    "sheds", "kills", "wire_rejects")
        ) + (f", reconnects {c['reconnects']}"
             if c.get("reconnects") is not None else ""))
    d = doc.get("deploy") or {}
    out.append(f"  deploys: {d.get('deploys')} completed, "
               f"{d.get('rollbacks')} rolled back"
               + (f", scheduled at round(s) {d['scheduled_rounds']}"
                  if d.get("scheduled_rounds") else ""))
    return "\n".join(out)


def fleetstat_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fleetstat",
        description="Render the fleet's live status document "
                    "(published atomically each round by the router "
                    "next to its metrics stream)")
    p.add_argument("status",
                   help="the router's metrics dir (holding "
                        f"{STATUS_FILENAME}) or the status file "
                        "itself")
    p.add_argument("--follow", action="store_true",
                   help="poll and re-render on change; exit rc 0 when "
                        "the doc reports the fleet drained")
    p.add_argument("--interval", type=float, default=0.5,
                   help="--follow poll cadence in seconds")
    p.add_argument("--max_s", type=float, default=60.0,
                   help="--follow gives up after this many seconds "
                        "(rc 0 if any status was ever rendered, rc 2 "
                        "if none appeared)")
    p.add_argument("--follow_max_s", type=float, default=None,
                   help="alias of --max_s (name parity with `report "
                        "--follow_max_s` so follow scripts can treat "
                        "the two tails interchangeably)")
    p.add_argument("--json", action="store_true",
                   help="print the raw status document")
    args = p.parse_args(argv)
    if args.follow_max_s is not None:
        args.max_s = args.follow_max_s
    if args.interval <= 0 or args.max_s <= 0:
        print("fleetstat: --interval/--max_s must be > 0",
              file=sys.stderr)
        return 2
    path = _resolve(args.status)

    if not args.follow:
        doc = _load(path)
        if doc is None:
            print(f"fleetstat: no status document at {path} (the "
                  "router publishes one when built with a metrics "
                  "dir / status_dir)", file=sys.stderr)
            return 2
        print(json.dumps(doc, indent=1) if args.json else render(doc))
        return 0

    t_start = time.monotonic()
    last_t = None
    rendered = False
    while True:
        # re-resolve each tick: following a router dir that the run
        # has not created yet must start rendering once it appears
        # (resolving once would freeze the dir itself as a file path)
        path = _resolve(args.status)
        doc = _load(path)
        if doc is not None and doc.get("t") != last_t:
            last_t = doc.get("t")
            rendered = True
            print(json.dumps(doc) if args.json else render(doc),
                  flush=True)
            if doc.get("drained"):
                return 0
        if time.monotonic() - t_start > args.max_s:
            if rendered:
                print("fleetstat: --max_s elapsed before the fleet "
                      "drained — stopping the tail")
                return 0
            print(f"fleetstat: no status document appeared at {path} "
                  f"within {args.max_s:.0f}s", file=sys.stderr)
            return 2
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(fleetstat_main())
