"""TPU-native distributed-LLM training framework, built from first principles.

A brand-new framework with the capabilities of
``martin-kukla/distributed-llm-code-samples`` (analyzed in ``SURVEY.md``),
re-designed for TPU:

- **Compute path**: JAX/XLA. The model math (FFN stacks) uses hand-written
  forward/backward kernels — no autograd for the model — wrapped in
  ``jax.custom_vjp`` so the manual math *is* the differentiation rule
  (mirrors the reference's no-``nn.Module``/no-autograd stance,
  ``train_ffns.py:1-3``).
- **Parallelism**: hand-rolled over raw XLA collectives
  (``psum`` / ``all_gather`` / ``psum_scatter`` / ``ppermute``) inside
  ``jax.shard_map`` on an explicit device mesh — the TPU analogue of
  "torch.distributed as a thin wrapper over NCCL collectives".
  Strategies: single-device, DDP, FSDP/ZeRO-3, Megatron-style TP, and a
  2-D hybrid DDP x TP mesh.

Subpackages: ``ops`` (numerical core), ``models`` (parameter containers and
model families), ``parallel`` (mesh, collectives, strategies, launcher),
``data`` (deterministic seeded mock data), ``optim`` (inline SGD).
"""

__version__ = "0.1.0"

# Training hyperparameters of the reference workload (train_ffns.py:29-30).
LR = 1e-5
DLOSS_DX_COEF = 0.1
