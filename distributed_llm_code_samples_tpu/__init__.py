"""TPU-native distributed-LLM training framework, built from first principles.

A brand-new framework with the capabilities of
``martin-kukla/distributed-llm-code-samples`` (analyzed in ``SURVEY.md``),
re-designed for TPU:

- **Compute path**: JAX/XLA. The model math (FFN stacks) uses hand-written
  forward/backward kernels — no autograd for the model — wrapped in
  ``jax.custom_vjp`` so the manual math *is* the differentiation rule
  (mirrors the reference's no-``nn.Module``/no-autograd stance,
  ``train_ffns.py:1-3``).
- **Parallelism**: hand-rolled over raw XLA collectives
  (``psum`` / ``all_gather`` / ``psum_scatter`` / ``ppermute``) inside
  ``jax.shard_map`` on an explicit device mesh — the TPU analogue of
  "torch.distributed as a thin wrapper over NCCL collectives".
  Strategies: single-device, DDP, FSDP/ZeRO-3, Megatron-style TP, and a
  2-D hybrid DDP x TP mesh.

Subpackages: ``ops`` (numerical core), ``models`` (parameter containers and
model families), ``parallel`` (mesh, collectives, strategies, launcher),
``data`` (deterministic seeded mock data), ``optim`` (inline SGD).
"""

__version__ = "0.1.0"

import jax as _jax  # noqa: E402

# --- jax version compat (a backend-environment robustness layer in the
# same spirit as the env-matrix probe: the framework must not die on the
# jax it is handed). The code targets the graduated >= 0.5 API surface;
# on older jax each shim maps to the pre-graduation equivalent. Every
# shim is hasattr-gated: all of this is a no-op on modern jax.

if not hasattr(_jax, "shard_map"):
    # shard_map lived under jax.experimental with the pre-graduation
    # keyword spelling (check_rep, renamed check_vma on graduation).
    # check_rep is pinned False: the old replication-checking discipline
    # predates the vma type system this code is written against (pcast/
    # pvary annotations below), and mixing the two only manufactures
    # spurious type errors — without it the shard_map is plain SPMD,
    # which is the semantics every strategy here hand-verifies anyway.
    from jax.experimental.shard_map import shard_map as _shard_map

    def _shard_map_compat(f, *, mesh, in_specs, out_specs,
                          check_vma=True, **kw):
        del check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, **kw)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    # the classic spelling: a psum of the literal 1 over the axis is
    # evaluated statically to the axis size
    def _axis_size(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size

if not hasattr(_jax, "typeof"):
    # jax.typeof + the vma (varying-manual-axes) type system arrived
    # with graduated shard_map. Pre-vma jax tracks no varying-axes type,
    # so: typeof exposes an aval whose .vma is empty, and the pcast /
    # pvary annotations that adjust vma types are identity functions —
    # with replication checking off (above) they carried no runtime
    # semantics to begin with.
    class _AvalView:
        __slots__ = ("_aval",)

        def __init__(self, aval):
            self._aval = aval

        def __getattr__(self, name):
            if name == "vma":
                return getattr(self._aval, "vma", frozenset())
            return getattr(self._aval, name)

    def _typeof(x):
        return _AvalView(_jax.core.get_aval(x))

    # the capability marker consumers key on: with vma typing erased,
    # NO cotangent is ever auto-reduced (transposes of the implicit
    # pvary don't exist), so grad_reduce's vma-off force contract is
    # the correct regime everywhere (parallel/collectives.py)
    _typeof.erased_vma = True
    _jax.typeof = _typeof

if not hasattr(_jax.lax, "pcast"):
    def _pcast(x, axes, *, to=None):
        del axes, to
        return x

    def _pvary(x, axes):
        del axes
        return x

    _jax.lax.pcast = _pcast
    if not hasattr(_jax.lax, "pvary"):
        _jax.lax.pvary = _pvary

try:
    _jax.ShapeDtypeStruct((), "float32", vma=frozenset())
except TypeError:
    # pre-vma ShapeDtypeStruct has no vma kwarg; the annotation carries
    # no information in the erased-vma regime, so swallow it
    _OrigSDS = _jax.ShapeDtypeStruct

    class _SDSCompat(_OrigSDS):
        def __init__(self, shape, dtype, *, vma=None, **kw):
            del vma
            super().__init__(shape, dtype, **kw)

    _jax.ShapeDtypeStruct = _SDSCompat

try:
    from jax.experimental.pallas import tpu as _pltpu

    if not hasattr(_pltpu, "CompilerParams"):
        # renamed from TPUCompilerParams when the pallas TPU surface
        # dropped its prefix; later fields (has_side_effects, ...) do
        # not exist pre-rename — drop them rather than die, the CPU
        # interpret paths this environment runs don't consume them
        import dataclasses as _dc

        _tpu_fields = {f.name
                       for f in _dc.fields(_pltpu.TPUCompilerParams)}

        def _compiler_params(**kw):
            return _pltpu.TPUCompilerParams(
                **{k: v for k, v in kw.items() if k in _tpu_fields})

        _pltpu.CompilerParams = _compiler_params
    if not hasattr(_pltpu, "InterpretParams"):
        # the dedicated TPU interpret mode (simulated RDMA/semaphores)
        # does not exist pre-graduation; fall back to generic
        # interpret=True, the best this jax can do off-TPU
        def _interpret_params(**kw):
            del kw
            return True

        _pltpu.InterpretParams = _interpret_params
except ImportError:  # pallas not on this build; ops modules self-guard
    pass

if not hasattr(_jax, "ffi"):
    # jax.ffi graduated from jax.extend.ffi with the same callable-
    # builder API; alias the module so both `jax.ffi.x` attribute access
    # and `import jax.ffi` resolve
    import sys as _sys

    from jax.extend import ffi as _ffi

    _jax.ffi = _ffi
    _sys.modules.setdefault("jax.ffi", _ffi)

# Training hyperparameters of the reference workload (train_ffns.py:29-30).
LR = 1e-5
DLOSS_DX_COEF = 0.1
