"""Sequence/context parallelism: ring attention over ``ppermute``.

Long sequences are sharded across the ``"seq"`` mesh axis. For the FFN
stack this is free (token-pointwise math — the reference already folds
sequence into batch, ``train_ffns.py:379``); attention is where sequence
parallelism earns its name: every query block must see every key/value
block without any device materializing the full sequence.

**Ring attention**: each shard keeps its Q block resident and its KV block
rotating. At step ``i`` a shard holds the KV block of shard
``(rank - i) mod n``, folds it into a running flash-style online softmax
(running row-max ``m``, denominator ``l``, numerator ``acc``), then passes
the KV block to its ring successor via ``ppermute`` — n steps, n-1 hops,
peak memory O(T/n * T/n) per shard. Causality uses *global* positions
(block offsets), so shards skip blocks entirely in their masked direction.
XLA schedules each hop's ``collective-permute`` asynchronously against the
block compute — compute/comm overlap on the ICI ring with no handles.

The backward pass is a hand-written second ring (``custom_vjp``, the
framework's stance for its flagship paths): the forward saves only
``(q, k, v, y, logsumexp)`` — O(T_local * d) per shard, independent of
the ring size — and the backward recomputes each step's probability block
from the saved logsumexp while rotating ``(k, v, dk, dv)`` around the
ring, so every KV block returns home with its gradient fully accumulated
after n hops. Autograd-through-the-loop would instead stash every ring
step's rotating KV blocks as residuals (O(n * T_local * d)), which defeats
the ring's memory story (VERDICT r1 item 5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.attention import causal_mask
from .mesh import SEQ_AXIS, require_axes

_NEG = -1e30  # finite -inf stand-in: keeps the online-softmax updates NaN-free


def _hop(t, axis_name: str, perm):
    """One ring hop (``lax.ppermute``) under the "comm" named scope —
    so ring traffic folds into the seq strategy's comm region in traces
    and HLO (utils/trace_analysis.SCOPES)."""
    with jax.named_scope("comm"):
        return lax.ppermute(t, axis_name, perm)


def _varying_like(t, ref, axis_name: str):
    """Type ``t`` as shard-varying over every axis ``ref`` varies on plus
    the ring axis — so fori_loop carries typecheck under shard_map's vma
    analysis on any mesh (a 2-D data x seq mesh adds "data" to the q/k/v
    blocks' vma; casting to the ring axis alone would drift after one
    fold)."""
    # sorted: iterating the frozenset union directly would make the axis
    # order (hence the lowered program text) hash-randomized run to run
    need = tuple(a for a in sorted(jax.typeof(ref).vma | {axis_name})
                 if a not in jax.typeof(t).vma)
    return lax.pcast(t, need, to="varying") if need else t


def _ring_fwd_core(q, k, v, axis_name: str, causal: bool):
    """One shard's forward ring; returns ``(y, lse)`` where ``lse`` is the
    per-row logsumexp of the full (masked) score matrix — the only softmax
    statistic the hand-written backward needs."""
    n = lax.axis_size(axis_name)
    # rank feeds only the causal mask offsets; a non-causal ring must not
    # emit it at all — a dead axis_index lowers to a PartitionId op that
    # older jax leaves outside the manual region, which SPMD rejects
    rank = lax.axis_index(axis_name) if causal else None
    t_local, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        k_blk, v_blk, m, l, acc = carry
        s = (q @ k_blk.T).astype(jnp.float32) * scale  # [T, T] scores
        if causal:
            src = (rank - i) % n  # whose KV block we hold at this step
            # global positions: this shard's Q block vs the held KV block
            allowed = causal_mask(t_local, t_local, rank * t_local,
                                  src * t_local)
            s = jnp.where(allowed, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)          # rescale old accumulator
        p = jnp.exp(s - m_new[:, None])     # [T, T]
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + p @ v_blk.astype(jnp.float32)
        # pass the KV block around the ring for the next step
        k_blk = _hop(k_blk, axis_name, perm)
        v_blk = _hop(v_blk, axis_name, perm)
        return k_blk, v_blk, m_new, l, acc

    m0 = _varying_like(jnp.full((t_local,), _NEG, jnp.float32), q, axis_name)
    l0 = _varying_like(jnp.zeros((t_local,), jnp.float32), q, axis_name)
    acc0 = _varying_like(jnp.zeros((t_local, d), jnp.float32), q, axis_name)
    *_, m, l, acc = lax.fori_loop(0, n, step, (k, v, m0, l0, acc0))
    return (acc / l[:, None]).astype(q.dtype), m + jnp.log(l)


def _hop_case(i, rank, n, causal):
    """Which of the three per-hop programs runs for the held block ``src =
    (rank - i) % n``: 0 = fully allowed (src strictly earlier), 1 = the
    diagonal block (standard causal masking), 2 = fully masked (skip —
    the flash FLOP saving at ring granularity)."""
    if not causal:
        return jnp.int32(0), None  # rank may be None: no mask, no src
    src = (rank - i) % n
    return jnp.where(src == rank, 1,
                     jnp.where(src < rank, 0, 2)).astype(jnp.int32), src


def _ring_fwd_flash(q, k, v, axis_name: str, causal: bool,
                    interpret: bool):
    """VERDICT r3 stretch: the ring's per-hop block compute FUSED — each
    held KV block goes through the Pallas flash kernel (online-softmax
    tiling in VMEM, no ``[T_local, T_local]`` probability matrix in HBM),
    and the per-hop ``(y_j, lse_j)`` partials merge by stable logsumexp:
    the same math as the plain ring's (m, l, acc) fold, carried in
    normalized-plus-lse form because that is what the kernel returns.
    The three hop cases map onto the kernel's own modes: earlier block →
    non-causal call, diagonal block → causal call (equal offsets make
    local causal == global causal), later block → skipped entirely."""
    from ..ops.pallas_attention import flash_attention_fwd
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name) if causal else None  # see _ring_fwd_core
    t_local, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop_full(args):
        qh, kb, vb = args
        return flash_attention_fwd(qh, kb, vb, causal=False,
                                   interpret=interpret)

    def hop_diag(args):
        qh, kb, vb = args
        return flash_attention_fwd(qh, kb, vb, causal=True,
                                   interpret=interpret)

    def hop_skip(args):
        qh = args[0]
        return (jnp.zeros_like(qh),
                jnp.full((t_local,), _NEG, jnp.float32)
                + jnp.zeros_like(qh[:, 0], jnp.float32))  # carries q's vma

    def step(i, carry):
        k_blk, v_blk, y_run, lse_run = carry
        case, _ = _hop_case(i, rank, n, causal)
        y_j, lse_j = lax.switch(case, [hop_full, hop_diag, hop_skip],
                                (q, k_blk, v_blk))
        # stable two-way merge of normalized partials: weights <= 1
        m = jnp.maximum(lse_run, lse_j)
        w_run = jnp.exp(lse_run - m)
        w_j = jnp.exp(lse_j - m)
        denom = w_run + w_j
        y_run = ((y_run.astype(jnp.float32) * w_run[:, None]
                  + y_j.astype(jnp.float32) * w_j[:, None])
                 / denom[:, None]).astype(q.dtype)
        lse_run = m + jnp.log(denom)
        k_blk = _hop(k_blk, axis_name, perm)
        v_blk = _hop(v_blk, axis_name, perm)
        return k_blk, v_blk, y_run, lse_run

    y0 = _varying_like(jnp.zeros_like(q), q, axis_name)
    lse0 = _varying_like(jnp.full((t_local,), _NEG, jnp.float32), q,
                         axis_name)
    *_, y, lse = lax.fori_loop(0, n, step, (k, v, y0, lse0))
    return y, lse


def _ring_bwd_flash(q, k, v, y, lse, dy, axis_name: str, causal: bool,
                    interpret: bool):
    """Backward ring with the flash backward kernels as the per-hop block
    compute. Same rotation structure as the plain backward (``(k, v, dk,
    dv)`` travel together; ``dq`` accumulates at home) — the kernels
    recompute each hop's probability tiles from the GLOBAL ``lse`` (and
    the global ``D = rowsum(dy*y)``), which is exactly the plain ring's
    ``p = exp(s - lse)`` / ``ds = p (dp - delta)`` math, tiled in VMEM."""
    from ..ops.pallas_attention import flash_attention_bwd
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name) if causal else None  # see _ring_fwd_core
    t_local, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop_full(args):
        kb, vb = args
        return flash_attention_bwd(dy, q, kb, vb, y, lse, causal=False,
                                   interpret=interpret)

    def hop_diag(args):
        kb, vb = args
        return flash_attention_bwd(dy, q, kb, vb, y, lse, causal=True,
                                   interpret=interpret)

    def hop_skip(args):
        kb, vb = args
        z = jnp.zeros_like(q)
        return z, jnp.zeros_like(kb), jnp.zeros_like(vb)

    def step(i, carry):
        k_blk, v_blk, dk, dv, dq = carry
        case, _ = _hop_case(i, rank, n, causal)
        dq_j, dk_j, dv_j = lax.switch(
            case, [hop_full, hop_diag, hop_skip], (k_blk, v_blk))
        dq = dq + dq_j.astype(jnp.float32)
        dk = dk + dk_j.astype(jnp.float32)
        dv = dv + dv_j.astype(jnp.float32)
        k_blk = _hop(k_blk, axis_name, perm)
        v_blk = _hop(v_blk, axis_name, perm)
        dk = _hop(dk, axis_name, perm)
        dv = _hop(dv, axis_name, perm)
        return k_blk, v_blk, dk, dv, dq

    zeros = _varying_like(jnp.zeros((t_local, d), jnp.float32), q, axis_name)
    *_, dk, dv, dq = lax.fori_loop(0, n, step, (k, v, zeros, zeros, zeros))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_attention(q, k, v, axis_name: str, causal: bool,
                    impl: str | None = None, interpret: bool = False):
    if impl == "flash":
        return _ring_fwd_flash(q, k, v, axis_name, causal, interpret)[0]
    y, _ = _ring_fwd_core(q, k, v, axis_name, causal)
    return y


def _ring_attention_fwd(q, k, v, axis_name, causal, impl, interpret):
    if impl == "flash":
        y, lse = _ring_fwd_flash(q, k, v, axis_name, causal, interpret)
    else:
        y, lse = _ring_fwd_core(q, k, v, axis_name, causal)
    # residuals are O(T_local * d): own blocks + output + one softmax stat.
    # No rotating block is saved — the backward re-runs the ring.
    return y, (q, k, v, y, lse)


def _ring_attention_bwd_dispatch(axis_name, causal, impl, interpret, res,
                                 dy):
    if impl == "flash":
        q, k, v, y, lse = res
        return _ring_bwd_flash(q, k, v, y, lse, dy, axis_name, causal,
                               interpret)
    return _ring_attention_bwd(axis_name, causal, res, dy)


def _ring_attention_bwd(axis_name, causal, res, dy):
    """Second ring pass. Per step, with the held KV block ``j``:
    ``p_ij = exp(s_ij - lse_i)`` (recomputed), ``dv_j += p_ij^T dy_i``,
    ``ds_ij = p_ij * (dy_i v_j^T - delta_i)`` (softmax VJP with
    ``delta = rowsum(dy * y)``), ``dq_i += ds_ij k_j * scale``,
    ``dk_j += ds_ij^T q_i * scale``. ``(k, v, dk, dv)`` rotate together so
    after n hops every KV block is home with its gradient complete."""
    q, k, v, y, lse = res
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name) if causal else None  # see _ring_fwd_core
    t_local, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]
    dy32 = dy.astype(jnp.float32)
    q32 = q.astype(jnp.float32)
    delta = jnp.sum(dy32 * y.astype(jnp.float32), axis=-1)  # [T_local]

    def step(i, carry):
        k_blk, v_blk, dk, dv, dq = carry
        s = (q @ k_blk.T).astype(jnp.float32) * scale
        if causal:
            src = (rank - i) % n
            allowed = causal_mask(t_local, t_local, rank * t_local,
                                  src * t_local)
            s = jnp.where(allowed, s, _NEG)
        p = jnp.exp(s - lse[:, None])       # masked entries exp to 0
        dv = dv + p.T @ dy32
        dp = dy32 @ v_blk.astype(jnp.float32).T
        ds = p * (dp - delta[:, None])
        dq = dq + (ds @ k_blk.astype(jnp.float32)) * scale
        dk = dk + (ds.T @ q32) * scale
        k_blk = _hop(k_blk, axis_name, perm)
        v_blk = _hop(v_blk, axis_name, perm)
        dk = _hop(dk, axis_name, perm)
        dv = _hop(dv, axis_name, perm)
        return k_blk, v_blk, dk, dv, dq

    zeros = _varying_like(jnp.zeros((t_local, d), jnp.float32), q, axis_name)
    *_, dk, dv, dq = lax.fori_loop(0, n, step,
                                   (k, v, zeros, zeros, zeros))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd_dispatch)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = SEQ_AXIS, causal: bool = True,
                   attn_impl: str | None = None,
                   interpret: bool = False):
    """Ring attention for one shard (call under ``shard_map``).

    ``q, k, v: [T_local, d]`` — this shard's sequence block. Returns the
    ``[T_local, d]`` attention output as if computed over the full
    sequence. Differentiation runs the hand-written backward ring above.

    ``attn_impl="flash"`` fuses the per-hop block compute end to end:
    every held KV block runs through the Pallas flash kernels (forward
    AND backward), so the long-context path never materializes a
    ``[T_local, T_local]`` probability block in HBM — cross-chip ring
    over ICI, within-chip online-softmax tiling in VMEM. ``interpret``
    runs the kernels in interpreter mode off-TPU."""
    return _ring_attention(q, k, v, axis_name, causal, attn_impl,
                           interpret)


def resolve_seq_attn(seq_impl: str, n: int, n_heads: int, seq_len: int,
                     axis: str = SEQ_AXIS, attn_impl: str | None = None,
                     interpret: bool = False):
    """Shared dispatch for the sequence-parallel trainers (transformer and
    LM families): validates shard divisibility and returns the multi-head
    attention op (``[H, T_local, dh]`` per batch element) whose
    cross-shard traffic is the hand-written ring (KV rotating over
    ``ppermute``) or Ulysses (two ``all_to_all``s). ``attn_impl="flash"``
    runs the per-hop (ring) / local (Ulysses) block compute on the fused
    Pallas kernels."""
    if seq_len % n:
        raise ValueError(f"seq_len={seq_len} not divisible by seq-axis "
                         f"size {n}")
    if seq_impl == "ring":
        def attn(q, k, v, causal):  # ring per head
            return jax.vmap(
                lambda q, k, v: ring_attention(q, k, v, axis, causal,
                                               attn_impl=attn_impl,
                                               interpret=interpret)
            )(q, k, v)
        return attn
    if seq_impl == "ulysses":
        from .transformer import resolve_attn
        if n_heads % n:
            raise ValueError(f"n_heads={n_heads} not divisible by "
                             f"seq-axis size {n} (Ulysses scatters heads)")
        local_op = resolve_attn(attn_impl)
        return lambda q, k, v, causal: ulysses_attention(q, k, v, axis,
                                                         causal,
                                                         attn=local_op)
    raise ValueError(f"unknown seq_impl {seq_impl!r} "
                     "(expected 'ring' or 'ulysses')")


def sequence_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                                mesh, causal: bool = True) -> jax.Array:
    """Launcher: shard ``[T, d]`` tensors over the ``"seq"`` axis, run ring
    attention, return the global result (sharded along the same axis)."""
    require_axes(mesh, SEQ_AXIS)
    n = mesh.shape[SEQ_AXIS]
    if q.shape[0] % n:
        raise ValueError(f"sequence length {q.shape[0]} not divisible by "
                         f"{n} seq shards")
    spec = P(SEQ_AXIS, None)
    sharded = [jax.device_put(t, NamedSharding(mesh, spec)) for t in (q, k, v)]
    return _ring_fn(mesh, causal)(*sharded)


@functools.lru_cache(maxsize=32)
def _ring_fn(mesh, causal: bool):
    """Cached jitted ring program per (mesh, causal) so repeat calls hit
    the jit cache instead of retracing."""
    spec = P(SEQ_AXIS, None)
    return jax.jit(jax.shard_map(
        functools.partial(ring_attention, axis_name=SEQ_AXIS, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))


# --- Ulysses: all_to_all head-scatter / sequence-gather -------------------
#
# The other canonical sequence-parallel scheme (DeepSpeed-Ulysses): instead
# of rotating KV blocks around a ring, two all_to_alls re-shard the problem
# so that attention itself runs unsharded. Shards hold a sequence block of
# every head; the first a2a trades heads for sequence (each shard ends up
# with the FULL sequence of H/n heads), full-sequence hand-VJP attention
# runs locally, and the second a2a trades back. Communication is 2 a2a of
# the activations per call (vs n-1 ppermute hops of KV for the ring) —
# cheaper when H >= n and the sequence fits per-head; the ring wins when
# the sequence itself must never materialize. Both are exposed; both
# differentiate through the a2a transposes around the hand-written rule.

def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = SEQ_AXIS, causal: bool = True,
                      attn=None, comm: str = "psum"):
    """Ulysses attention for one shard (call under ``shard_map``).

    ``q, k, v: [H, T_local, dh]`` — this shard's sequence block of every
    head; ``H`` must be divisible by the axis size. Returns the same shape,
    exact full-sequence attention. ``attn`` swaps the local multi-head op
    (None = quadratic hand-VJP ``mha``; pass the fused Pallas ``flash_mha``
    — the a2a re-shard hands each shard FULL sequences of ``H/n`` heads,
    exactly the shape the flash kernels tile best). ``comm="pallas_a2a"``
    runs BOTH re-shards (and, via the custom VJP, their backward
    transposes) through the hand-scheduled peer fan-out kernel
    (``ops.pallas_ring.all_to_all_dma``) instead of XLA's all_to_all.
    """
    from ..models.attention import mha
    from .collectives import all_to_all

    if comm == "pallas_a2a":
        from ..ops.pallas_ring import all_to_all_dma_dims
        _a2a = lambda t, s, c: all_to_all_dma_dims(  # noqa: E731
            t, axis_name, s, c, None)
    elif comm == "psum":
        _a2a = lambda t, s, c: all_to_all(t, axis_name,  # noqa: E731
                                          split_dim=s, concat_dim=c)
    else:
        raise ValueError(f"unknown comm {comm!r} "
                         "(expected 'psum' or 'pallas_a2a')")

    def a2a(t, s, c):
        with jax.named_scope("comm"):  # the heads<->sequence re-shards
            return _a2a(t, s, c)

    op = mha if attn is None else attn
    y = op(*(a2a(t, 0, 1) for t in (q, k, v)), causal)
    return a2a(y, 1, 0)


def ulysses_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                               mesh, causal: bool = True,
                               attn_impl: str | None = None) -> jax.Array:
    """Launcher: shard ``[H, T, dh]`` tensors over the ``"seq"`` axis
    (sequence dim), run Ulysses, return the result sharded the same way.
    ``attn_impl="flash"`` runs the local attention on the fused Pallas
    kernels (interpret mode off-TPU)."""
    require_axes(mesh, SEQ_AXIS)
    n = mesh.shape[SEQ_AXIS]
    if q.shape[1] % n:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by "
                         f"{n} seq shards")
    if q.shape[0] % n:
        raise ValueError(f"head count {q.shape[0]} not divisible by "
                         f"{n} seq shards (Ulysses scatters heads)")
    spec = P(None, SEQ_AXIS, None)
    sharded = [jax.device_put(t, NamedSharding(mesh, spec))
               for t in (q, k, v)]
    return _ulysses_fn(mesh, causal, attn_impl)(*sharded)


@functools.lru_cache(maxsize=32)
def _ulysses_fn(mesh, causal: bool, attn_impl: str | None = None):
    from .transformer import resolve_attn
    spec = P(None, SEQ_AXIS, None)
    # the Pallas interpreter mis-types scratch-vs-operand vma for the
    # non-causal kernels (jax's own error suggests check_vma=False as the
    # workaround); the oracle path keeps full vma checking
    check = attn_impl in (None, "oracle") or causal
    return jax.jit(jax.shard_map(
        functools.partial(ulysses_attention, axis_name=SEQ_AXIS,
                          causal=causal, attn=resolve_attn(attn_impl)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=check))
