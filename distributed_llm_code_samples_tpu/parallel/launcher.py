"""Shared launcher tail for every multi-device strategy.

The reference's launchers all share the same skeleton — shard params and
seeds, spawn workers, join, re-assemble (``train_ffns.py:174-193, :262-287,
:315-338``). The SPMD analogue is one function: ``shard_map`` the per-shard
step loop over the mesh, jit with donation, run. Each strategy is then just
its specs + hooks.

Self-healing hooks (round 8):

- ``guard`` (a ``runtime.guardrails.GuardrailConfig``) compiles the
  in-graph anomaly guardrail into ANY strategy's scan: the step's carry
  is extended with a ``GuardState``, the finite check + ``jnp.where``
  skip-select wraps every step, and the final counters come back with
  the result (``return (out, GuardState)``). Because the wrap happens
  here — at the one place every strategy's scan is built — a new
  strategy gets skip-step protection for free.
- ``accum`` re-strides the seed schedule for topology-elastic resume
  (``data.shard_seeds_elastic``): each scan step consumes a VECTOR of
  ``accum`` seeds per rank, preserving the save-time global batch when
  a checkpoint resumes onto fewer devices. The step function must
  accept the vector (``seed_accum`` surface in ddp/fsdp).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax

# Test/introspection hook: when a list is installed here, every launch
# also lowers+compiles its program AOT and appends the optimized HLO
# text (the named-scope presence contract is asserted against the REAL
# launched program, not a reconstruction — tests/test_telemetry.py).
# None (the default) costs nothing.
CAPTURE_COMPILED: list | None = None


def _maybe_capture(jitted, *args) -> None:
    if CAPTURE_COMPILED is not None:
        CAPTURE_COMPILED.append(
            jitted.lower(*args).compile().as_text())


def launch(step: Callable, params, seeds_arr, mesh, param_specs, seed_spec,
           select_local: Callable = lambda s: s,
           make_carry: Callable | None = None,
           check_vma: bool = True,
           state=None, state_specs=None, return_state: bool = False,
           guard=None, guard_state=None, guard_scale: bool = False):
    """Run ``lax.scan(step)`` over the seed schedule under ``shard_map``.

    ``select_local`` maps the shard's view of the seed array to its 1-D
    schedule (e.g. ``s[:, 0]`` for a strided column split). ``params`` must
    already be owned by the launcher (cloned/resharded) — they are donated.

    Stateful strategies (optimizer state, ZeRO shards) pass ``make_carry``:
    it builds the scan carry from the per-shard params *inside* the
    ``shard_map`` body (so per-shard state can be sliced from the shard's
    view), ``step`` then maps ``(carry, seed) -> carry``, and the carry's
    first element is returned as the final params.

    Alternatively, ``state``/``state_specs`` pass explicit optimizer
    state *through* the program boundary: the carry is ``(params,
    state)`` and with ``return_state=True`` the final state comes back
    out — what checkpoint/resume needs to continue an Adam run exactly.

    ``check_vma=False`` disables shard_map's varying-manual-axes typing for
    strategies whose replicated outputs the type system cannot prove —
    e.g. ZeRO-1's params re-assembled by ``all_gather`` from
    ``axis_index``-sliced shards (identical by construction on every
    rank, but typed varying; JAX offers no varying->invariant cast).

    ``guard`` arms the in-graph anomaly guardrail (module docstring):
    the return value becomes ``(normal_result, GuardState)``, with the
    guard state replicated (its finite flag is ``psum``-reduced over
    every mesh axis, so all shards skip — or keep — the same steps).
    ``guard_scale=True`` passes the live loss scale into the step as a
    third argument (the mixed-precision strategies' scaling hook).
    """
    from jax.sharding import PartitionSpec as P

    gstate = None
    if guard is not None:
        from ..runtime.guardrails import (guarded_scan_step, init_state,
                                          mesh_world)
        if guard.scaling and not guard_scale:
            # a scaling config on a strategy without the loss-scale hook
            # would never scale anything while GuardState.loss_scale
            # still ran its grow/shrink schedule — refuse the silent lie
            raise ValueError(
                "guard.loss_scale > 0 but this strategy has no "
                "loss-scale hook: dynamic scaling is a mixed-precision "
                "DDP/FSDP surface — pass loss_scale=0 here")
        axes, world = mesh_world(mesh)
        step = guarded_scan_step(step, guard, axis_names=axes, world=world,
                                 takes_scale=guard_scale)
        gstate = init_state(guard) if guard_state is None else guard_state

    if state is not None:
        if guard is None:
            def run_state(params, state, seeds):
                local = select_local(seeds)
                out = lax.scan(lambda c, s: (step(c, s), None),
                               (params, state), local)[0]
                return out if return_state else out[0]

            out_specs = ((param_specs, state_specs) if return_state
                         else param_specs)
            run_sharded = jax.shard_map(
                run_state, mesh=mesh,
                in_specs=(param_specs, state_specs, seed_spec),
                out_specs=out_specs, check_vma=check_vma)
            jitted = jax.jit(run_sharded, donate_argnums=(0, 1))
            _maybe_capture(jitted, params, state, seeds_arr)
            return jitted(params, state, seeds_arr)

        def run_state_g(params, state, gstate, seeds):
            local = select_local(seeds)
            carry, g = lax.scan(lambda c, s: (step(c, s), None),
                                ((params, state), gstate), local)[0]
            return (carry if return_state else carry[0]), g

        out_specs = (((param_specs, state_specs) if return_state
                      else param_specs), P())
        run_sharded = jax.shard_map(
            run_state_g, mesh=mesh,
            in_specs=(param_specs, state_specs, P(), seed_spec),
            out_specs=out_specs, check_vma=check_vma)
        jitted = jax.jit(run_sharded, donate_argnums=(0, 1))
        _maybe_capture(jitted, params, state, gstate, seeds_arr)
        return jitted(params, state, gstate, seeds_arr)

    if guard is None:
        def run(params, seeds):
            local = select_local(seeds)
            carry = params if make_carry is None else make_carry(params)
            out = lax.scan(lambda c, s: (step(c, s), None), carry, local)[0]
            return out if make_carry is None else out[0]

        run_sharded = jax.shard_map(run, mesh=mesh,
                                    in_specs=(param_specs, seed_spec),
                                    out_specs=param_specs,
                                    check_vma=check_vma)
        jitted = jax.jit(run_sharded, donate_argnums=0)
        _maybe_capture(jitted, params, seeds_arr)
        return jitted(params, seeds_arr)

    def run_g(params, gstate, seeds):
        local = select_local(seeds)
        carry = params if make_carry is None else make_carry(params)
        out, g = lax.scan(lambda c, s: (step(c, s), None),
                          (carry, gstate), local)[0]
        return (out if make_carry is None else out[0]), g

    run_sharded = jax.shard_map(run_g, mesh=mesh,
                                in_specs=(param_specs, P(), seed_spec),
                                out_specs=(param_specs, P()),
                                check_vma=check_vma)
    jitted = jax.jit(run_sharded, donate_argnums=0)
    _maybe_capture(jitted, params, gstate, seeds_arr)
    return jitted(params, gstate, seeds_arr)


def launch_strided(step: Callable, params, seeds, mesh, axis: str,
                   param_specs, accum: int = 1, **kwargs):
    """``launch`` with the strided seed split every data-sharding strategy
    uses (``train_ffns.py:182`` semantics, ``data.shard_seeds_strided``):
    rank ``r``'s step ``t`` consumes global seed ``seeds[t*n + r]``. One
    helper so the convention — which silently breaks the DDP==FSDP
    differential tests if it drifts — lives in one place. The shard count
    is ``mesh.shape[axis]`` by construction: a caller-supplied count could
    silently mis-assign seeds if it drifted from the mesh.

    ``accum > 1`` switches to the elastic re-stride
    (``data.shard_seeds_elastic``): each scan step hands the strategy a
    ``[accum]`` seed vector per rank, preserving an ``accum * n``-seed
    global batch — the topology-elastic resume path (the step must have
    the ``seed_accum`` surface)."""
    from jax.sharding import PartitionSpec as P

    from ..data import shard_seeds_elastic, shard_seeds_strided
    n = dict(mesh.shape)[axis]
    if accum > 1:
        seed_cols = shard_seeds_elastic(seeds, n, accum)
        return launch(step, params, seed_cols, mesh,
                      param_specs=param_specs,
                      seed_spec=P(None, None, axis),
                      select_local=lambda s: s[:, :, 0], **kwargs)
    seed_cols = shard_seeds_strided(seeds, n)
    return launch(step, params, seed_cols, mesh, param_specs=param_specs,
                  seed_spec=P(None, axis), select_local=lambda s: s[:, 0],
                  **kwargs)
