"""Shared launcher tail for every multi-device strategy.

The reference's launchers all share the same skeleton — shard params and
seeds, spawn workers, join, re-assemble (``train_ffns.py:174-193, :262-287,
:315-338``). The SPMD analogue is one function: ``shard_map`` the per-shard
step loop over the mesh, jit with donation, run. Each strategy is then just
its specs + hooks.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax


def launch(step: Callable, params, seeds_arr, mesh, param_specs, seed_spec,
           select_local: Callable = lambda s: s):
    """Run ``lax.scan(step)`` over the seed schedule under ``shard_map``.

    ``select_local`` maps the shard's view of the seed array to its 1-D
    schedule (e.g. ``s[:, 0]`` for a strided column split). ``params`` must
    already be owned by the launcher (cloned/resharded) — they are donated.
    """

    def run(params, seeds):
        local = select_local(seeds)
        return lax.scan(lambda p, s: (step(p, s), None), params, local)[0]

    run_sharded = jax.shard_map(run, mesh=mesh,
                                in_specs=(param_specs, seed_spec),
                                out_specs=param_specs)
    return jax.jit(run_sharded, donate_argnums=0)(params, seeds_arr)
