"""Megatron-style tensor parallelism: column-parallel W1, row-parallel W2.

Parity target: ``train_tp`` / ``train_process_tp`` (``train_ffns.py:289-338``).
W1 is chunked on its output (ffn) dim — column parallel — and W2 on its
input (ffn) dim — row parallel (``chunk_p(p, dim=i)``, ``:316-319``). The
chunk dims are conjugate, so **no communication crosses the ReLU** (the
Megatron f/g trick): each rank computes a full-width slice of the hidden
activation, and one ``all_reduce(SUM)`` per layer per direction restores the
replicated activation (forward ``:303``) / input grad (backward ``:309``).
Data is replicated to all ranks (``:324``); weight grads stay local — each
rank owns its shard's optimizer step (``:311-312``).

TPU translation: params sharded ``w1: P(None, "model", None)``,
``w2: P(None, None, "model")`` on the stacked layout; ``block_fwd`` /
``block_bwd`` append the per-layer ``psum`` — injected through the same hook
surface the other strategies use (``ops.stack``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from jax import lax

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, reshard_copy
from ..optim import sgd
from ..ops.ffn import ffn_bwd, ffn_bwd_mixed, ffn_fwd, ffn_fwd_mixed
from ..ops.stack import stack_fwd, stack_bwd
from .collectives import all_gather, all_reduce, axis_index, reduce_scatter
from .launcher import launch
from .mesh import MODEL_AXIS, require_axes

# w1 [L, ffn, d] sharded on ffn (column-parallel); w2 [L, d, ffn] sharded on
# ffn (row-parallel) — train_ffns.py:316-319 on the stacked layout.
PARAM_SPECS = FFNStackParams(w1=P(None, MODEL_AXIS, None),
                             w2=P(None, None, MODEL_AXIS))


def shard_params(params: FFNStackParams, mesh) -> FFNStackParams:
    return reshard_copy(params, FFNStackParams(
        w1=NamedSharding(mesh, PARAM_SPECS.w1),
        w2=NamedSharding(mesh, PARAM_SPECS.w2)))


def make_step(batch_size: int, model_size: int, lr: float = LR,
              unroll: bool = True, axis: str = MODEL_AXIS,
              mixed: bool = False):
    # `mixed` swaps the local block math for the bf16-MXU/f32-accumulate
    # rule; the per-layer psums carry f32 partials (each rank's
    # contraction slice accumulates f32), so the Megatron reduction
    # semantics are unchanged.
    fwd = ffn_fwd_mixed if mixed else ffn_fwd
    bwd = ffn_bwd_mixed if mixed else ffn_bwd

    def block_fwd(w1_shard, w2_shard, x):
        # Partial y per rank, then sync all_reduce(SUM) — train_ffns.py:302-303.
        y = fwd(w1_shard, w2_shard, x)
        with jax.named_scope("comm"):  # Megatron g -> tp/fwd/comm
            return all_reduce(y, axis)

    def block_bwd(dy, w1_shard, w2_shard, x):
        # Local VJP on the shard, then all_reduce the input grad — :308-309.
        # The recompute of the (local slice of the) pre-activation happens
        # inside the block bwd, same as the reference's per-rank recompute.
        dx, grads = bwd(dy, w1_shard, w2_shard, x)
        with jax.named_scope("comm"):
            return all_reduce(dx, axis), grads

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        # named-scope regions (tp/fwd, tp/bwd, nested comm psums,
        # tp/optim) — utils/trace_analysis.SCOPES
        with jax.named_scope("tp"):
            x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                          params.w1.dtype)
            _, acts = stack_fwd(params.w1, params.w2, x,
                                block_fwd=block_fwd, unroll=unroll)
            _, (g1, g2) = stack_bwd(dloss_dx, params.w1, params.w2, acts,
                                    block_bwd=block_bwd, unroll=unroll)
            with jax.named_scope("optim"):
                # Weight grads are local to the shard; local SGD (:311-312).
                return sgd(params, FFNStackParams(g1, g2), lr)

    return step


def make_sp_step(batch_size: int, model_size: int, n_shards: int,
                 lr: float = LR, unroll: bool = True,
                 axis: str = MODEL_AXIS, mixed: bool = False):
    """Megatron *sequence-parallel* TP (Korthikanti et al.): between
    blocks the activation stream lives **token-sharded** (``[T/n, d]``
    per rank) instead of replicated, and each per-layer-per-direction
    ``all_reduce`` is replaced by its ring-equal decomposition
    ``all_gather`` (tokens in) + ``reduce_scatter`` (tokens out) — same
    bytes on the wire, but every saved residual shrinks by ``n``.

    The backward is hand-threaded through the same hook surface as plain
    TP: the block backward **re-gathers** its token shard (recompute, not
    residual — the whole point), gathers the upstream grad (the
    ``reduce_scatter`` transpose), runs the hand-written block VJP on
    full tokens, and ``reduce_scatter``s the input grad (the
    ``all_gather`` transpose — which also sums the partials, the sharded
    form of ``train_ffns.py:309``'s all_reduce). Weight grads see all
    tokens, so they are complete per shard, exactly like plain TP."""
    if batch_size % n_shards:
        raise ValueError(f"tokens {batch_size} not divisible by "
                         f"{n_shards} model shards (sequence-parallel TP "
                         "shards the token dim between blocks)")
    t_local = batch_size // n_shards
    fwd = ffn_fwd_mixed if mixed else ffn_fwd
    bwd = ffn_bwd_mixed if mixed else ffn_bwd

    def block_fwd(w1_shard, w2_shard, x_s):
        with jax.named_scope("comm"):
            full = all_gather(x_s, axis, dim=0)          # [T, d]
        part = fwd(w1_shard, w2_shard, full)             # partial over ffn
        with jax.named_scope("comm"):
            return reduce_scatter(part, axis, dim=0)     # [T/n, d], summed

    def block_bwd(dy_s, w1_shard, w2_shard, x_s):
        with jax.named_scope("comm"):
            full = all_gather(x_s, axis, dim=0)    # recomputed, not saved
            dy_full = all_gather(dy_s, axis, dim=0)  # rs transpose
        dx_full, grads = bwd(dy_full, w1_shard, w2_shard, full)
        with jax.named_scope("comm"):
            # all_gather transpose: scatter AND sum the rank-partial dx
            return reduce_scatter(dx_full, axis, dim=0), grads

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        with jax.named_scope("tp"):
            x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                          params.w1.dtype)
            r = axis_index(axis)
            x_s, dy_s = (lax.dynamic_slice_in_dim(t, r * t_local,
                                                  t_local, 0)
                         for t in (x, dloss_dx))
            # acts holds the SHARDED block inputs — [L, T/n, d], the 1/n
            # activation-memory claim (structurally asserted in tests)
            _, acts = stack_fwd(params.w1, params.w2, x_s,
                                block_fwd=block_fwd, unroll=unroll)
            _, (g1, g2) = stack_bwd(dy_s, params.w1, params.w2, acts,
                                    block_bwd=block_bwd, unroll=unroll)
            with jax.named_scope("optim"):
                return sgd(params, FFNStackParams(g1, g2), lr)

    return step


def train_tp_sp(params: FFNStackParams, seeds, batch_size: int,
                model_size: int, mesh, lr: float = LR,
                unroll: bool = True, mixed: bool = False) -> FFNStackParams:
    """Sequence-parallel Megatron TP (see ``make_sp_step``). Data is
    replicated like plain TP (each rank regenerates the step's batch and
    slices its token block), so ``train_tp_sp == train_tp == single`` —
    the decomposition changes memory and comms shape, never the math."""
    require_axes(mesh, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    if params.w1.shape[1] % n:
        raise ValueError(f"ffn_dim {params.w1.shape[1]} not divisible by "
                         f"{n} model shards")
    params = shard_params(params, mesh)
    step = make_sp_step(batch_size, model_size, n, lr, unroll, mixed=mixed)

    # check_vma off: reduce_scatter of a varying partial and the final
    # replicated-params claim mirror zero1's situation (launcher.launch)
    return launch(step, params, jnp.asarray(seeds), mesh,
                  param_specs=PARAM_SPECS, seed_spec=P(),
                  check_vma=False)


def train_tp(params: FFNStackParams, seeds, batch_size: int,
             model_size: int, mesh, lr: float = LR,
             unroll: bool = True, mixed: bool = False) -> FFNStackParams:
    """Run the full TP schedule. Data (seeds) is replicated to all shards
    (``train_ffns.py:324``), so TP consumes the *same* steps as the
    single-device run — they must agree numerically (a differential test
    the reference never asserted). ``mixed`` runs the bf16-MXU block rule
    (to tolerance vs the f32 path: the contraction is split across
    shards, so bf16 rounding composes with the psum order)."""
    require_axes(mesh, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    if params.w1.shape[1] % n:
        raise ValueError(f"ffn_dim {params.w1.shape[1]} not divisible by "
                         f"{n} model shards")
    params = shard_params(params, mesh)
    step = make_step(batch_size, model_size, lr, unroll, mixed=mixed)

    return launch(step, params, jnp.asarray(seeds), mesh,
                  param_specs=PARAM_SPECS, seed_spec=P())
