"""MoE-transformer trainers — GShard's layout on one ``"expert"`` axis.

Attention runs **data-parallel** (each shard owns whole sequences of its
own seed column) while the MoE FFN runs **expert-parallel** (experts
sharded, tokens routed through the ``all_to_all`` dispatch of
``parallel.expert``) — the composition GShard trains with, on this
framework's transformer (``models.moe_transformer``).

Gradients: attention projections, LayerNorms, and the router are
replicated, so their per-shard partials take one ``psum`` over the
expert axis (SUM, unscaled LR — ``train_ffns.py:165`` semantics);
expert FFN weights are complete on their owner shard (the a2a is the
reduction's data movement, ``parallel/expert.py``).

``train_moe_transformer_dense`` is the no-mesh oracle: ``n_groups=n``
reproduces the n-shard EP run exactly (strided seed split, grouped
dispatch with the per-group capacity share, summed replicated-weight
grads) — the user-facing differential check, like ``train_moe_dense``
for the flat MoE stack.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import LR
from ..data import batch_from_seed, shard_seeds_strided
from ..models.ffn_stack import clone_params
from ..models.moe_transformer import (MoETransformerParams,
                                      moe_transformer_fwd_aux)
from ..optim import sgd
from .expert import _local_capacity, moe_layer_ep
from .collectives import grad_reduce, vma_erased
from .launcher import launch_strided
from .mesh import EXPERT_AXIS, require_axes

# Expert FFN weights sharded on the expert dim; everything else replicated.
EP_SPECS = MoETransformerParams(
    ln1=P(), wq=P(), wk=P(), wv=P(), wo=P(), ln2=P(), wg=P(),
    w1=P(None, EXPERT_AXIS), w2=P(None, EXPERT_AXIS))

# grads for these leaves are per-shard partials over the expert axis
_REPLICATED = ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg")


def _validate(params, batch_size: int, seq_len: int, n: int,
              model_size: int, n_heads: int) -> int:
    if model_size % n_heads:
        raise ValueError(f"model_size={model_size} not divisible by "
                         f"n_heads={n_heads} (head dim must be whole)")
    if batch_size % n:
        raise ValueError(f"batch_size={batch_size} tokens not divisible "
                         f"by {n} expert shards")
    t_local = batch_size // n
    if t_local % seq_len:
        raise ValueError(f"per-shard tokens {t_local} not divisible by "
                         f"seq_len={seq_len} (shards own whole sequences)")
    if params.n_experts % n:
        raise ValueError(f"n_experts={params.n_experts} not divisible by "
                         f"expert-axis size {n}")
    return t_local


def train_moe_transformer_ep(params: MoETransformerParams, seeds,
                             batch_size: int, model_size: int, mesh,
                             lr: float = LR, *, seq_len: int, n_heads: int,
                             causal: bool = True,
                             capacity_factor: float = 2.0, k: int = 1,
                             aux_coef: float = 0.0,
                             attn_impl: str | None = None,
                             dispatch: str = "dense"
                             ) -> MoETransformerParams:
    """Run the GShard schedule; ``batch_size`` is global tokens per step
    (each shard trains ``batch_size/n`` tokens of its own strided seed
    column as ``[B/n, seq_len, d]`` sequences). ``attn_impl`` selects the
    attention core like every transformer trainer (None/'oracle' or
    'flash' for the fused Pallas kernels)."""
    from .transformer import resolve_attn
    require_axes(mesh, EXPERT_AXIS)
    n = mesh.shape[EXPERT_AXIS]
    t_local = _validate(params, batch_size, seq_len, n,
                        model_size, n_heads)
    b_local = t_local // seq_len
    attn = resolve_attn(attn_impl)

    def moe_fn(wg, w1_local, w2_local, h):
        return moe_layer_ep(wg, w1_local, w2_local, h, capacity_factor,
                            EXPERT_AXIS, k, dispatch)

    def step(params: MoETransformerParams, seed) -> MoETransformerParams:
        x, dloss_dx = batch_from_seed(seed, t_local, model_size,
                                      params.w1.dtype)
        x = x.reshape(b_local, seq_len, model_size)
        dloss_dx = dloss_dx.reshape(b_local, seq_len, model_size)
        # named-scope regions (moe_tf/fwd, moe_tf/bwd, moe_tf/comm,
        # moe_tf/optim; the a2a pair adds nested comm scopes)
        with jax.named_scope("moe_tf"):
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(
                    lambda p: moe_transformer_fwd_aux(
                        p, x, n_heads, causal, moe_fn=moe_fn, attn=attn),
                    params)
            coef = lax.pcast(jnp.asarray(aux_coef, jnp.float32),
                             EXPERT_AXIS, to="varying")
            with jax.named_scope("bwd"):
                grads = vjp((dloss_dx, coef))[0]
            with jax.named_scope("comm"):
                grads = grads._replace(**{
                    f: grad_reduce(getattr(grads, f), EXPERT_AXIS,
                                   force=vma_erased())
                    for f in _REPLICATED})
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return launch_strided(step, clone_params(params), seeds, mesh,
                          EXPERT_AXIS, EP_SPECS)


def train_moe_transformer_dense(params: MoETransformerParams, seeds,
                                batch_size: int, model_size: int,
                                lr: float = LR, *, seq_len: int,
                                n_heads: int, causal: bool = True,
                                capacity_factor: float = 2.0, k: int = 1,
                                aux_coef: float = 0.0, n_groups: int = 1,
                                attn_impl: str | None = None
                                ) -> MoETransformerParams:
    """Single-device dense trainer with EP's exact semantics — the
    user-facing oracle for ``train_moe_transformer_ep`` (``n_groups=n``),
    or plain dense MoE-transformer training (``n_groups=1``)."""
    from .transformer import resolve_attn
    t_local = _validate(params, batch_size, seq_len, n_groups,
                        model_size, n_heads)
    b_local = t_local // seq_len
    cap = _local_capacity(t_local, n_groups, params.n_experts,
                          capacity_factor)
    rows = shard_seeds_strided(seeds, n_groups)
    attn = resolve_attn(attn_impl)

    def fwd_aux(p, xs):  # xs [n_groups, b_local, seq, d]
        y, aux = jax.vmap(lambda x: moe_transformer_fwd_aux(
            p, x, n_heads, causal, capacity_factor, k, cap,
            attn=attn))(xs)
        return y, jnp.sum(aux)

    def step(p, row):
        xs, dls = jax.vmap(lambda s: batch_from_seed(
            s, t_local, model_size, p.w1.dtype))(row)
        xs = xs.reshape(n_groups, b_local, seq_len, model_size)
        dls = dls.reshape(n_groups, b_local, seq_len, model_size)
        _, vjp = jax.vjp(lambda p: fwd_aux(p, xs), p)
        grads = vjp((dls, jnp.asarray(aux_coef, jnp.float32)))[0]
        return sgd(p, grads, lr), None

    run = jax.jit(lambda p, rows: lax.scan(step, p, rows)[0],
                  donate_argnums=0)
    return run(clone_params(params), rows)
