"""Pipeline parallelism: layers staged across the ``"pipe"`` axis, with
hand-rolled ``ppermute`` send/recv, two microbatch schedules, and optional
data/tensor axes — the full 3-D composition.

The reference has **no** pipeline parallelism and no point-to-point
send/recv anywhere (SURVEY.md section 2.2) — but the driver's BASELINE
config 3 asks for an "MP mode, 8-layer FFN split across 4 devices
(exercise send/recv + barrier)". This module is that path, built the TPU
way: one SPMD program over a ``("pipe",)`` mesh axis where every stage
runs the same code and neighbor transfer is ``lax.ppermute`` over the ICI
ring (``collectives.ring_shift``) — the XLA lowering of NCCL send/recv.

Three schedules, selected by ``schedule=``:

**"gpipe"** (default): all ``M`` forwards wave through the ring
(``M + S - 1`` ticks), then all backwards in reverse. At tick ``t`` stage
``s`` computes microbatch ``t - s``; bubble ticks take a ``lax.cond``
idle branch, so a stage *skips* its out-of-wavefront compute instead of
computing-and-masking it. The stash holds one activation set per
**microbatch** (``[M, L/S, mb, d]``) — the minimum GPipe needs.

**"1f1b"**: forward and backward wavefronts share one slot stream of the
same ``2(M + S - 1)`` length, with stage ``s`` forwarding microbatch
``m`` at slot ``s + 2m`` and backwarding it at slot ``2S - 1 - s + 2m``
(the classic one-forward-one-backward interleave, expressed lockstep:
F and B land on opposite slot parities per stage so each slot runs at
most one block compute via ``lax.switch``). A microbatch's activations
live ``2(S - s) - 1`` slots, so the stash is a circular buffer of depth
``min(S, M)`` — peak activation memory is bounded by the *stage depth*,
not the microbatch count, which is the whole point of 1F1B: with
``M >> S`` the GPipe stash grows linearly while this one is constant
(pinned by a structural test on the traced program's buffer shapes).

(Why no "interleaved-1f1b" combining both wins: in this lockstep
uniform-slot model a 1F1B interleave must dilate the slot stream so
forward and backward land on opposite parities — which doubles the
fill cost. Worked through: the dilated interleaved schedule runs
``~2vM + 2vS - S`` chunk-slots with stash ``~S(v+1)/v`` stage-units —
i.e. 1F1B-class memory at 1F1B-class bubble ``S/(M+S)``, strictly
worse in time than "interleaved"'s ``2(vM + S - 1)`` and no better in
bubble than "1f1b". The asynchronous per-rank form Megatron runs does
beat both simultaneously, but only because its slots are not uniform —
outside what one SPMD lockstep program expresses. Hence the menu below:
pick memory OR bubble.)

**"interleaved"**: Megatron virtual stages — each device holds
``interleave`` non-contiguous layer chunks placed round-robin
(virtual stage ``q = c*S + d`` on device ``d``), so every
virtual-stage hop is ``+1`` on the ring and a wavefront over all
``v*S`` virtual stages packs with NO per-chunk conflicts. The fill
costs ``(S-1)/v`` of a stage's work instead of ``S-1``: bubble
fraction ``(S-1)/(v*M + S - 1)``, the ~1/v Megatron reduction
(see ``_interleaved_step``). This schedule buys bubble; "1f1b" buys
memory. All three families (FFN / transformer / LM) run it, with the
LM's embed/head roles gated on *virtual* stage ends.

Every slot moves both streams: activation ``+1`` and gradient ``-1``
ring shifts. Stage 0 injects inputs, the last stage injects
``dloss_dx``. Because the mock loss needs no forward output
(``dloss_dx`` is generated from the step seed, ``train_ffns.py:150``),
the last stage starts each microbatch's backward from its own
locally-generated slice — no loss broadcast.

**3-D composition**: give ``train_pp`` a mesh with ``"data"`` and/or
``"model"`` axes alongside ``"pipe"`` and it becomes full 3-D
parallelism. The ``data`` axis replicates the pipeline, strides the seed
schedule DDP-style, and sums weight grads with one ``psum`` per step;
the ``model`` axis Megatron-shards each stage's layers (column/row
conjugate chunks, one ``psum`` per layer per direction *inside* the
stage compute, riding an axis orthogonal to the pipe ring). Under
shard_map's vma typing, all schedule carries are normalized to vary over
every participating axis (``_vary_to``), since the wavefront state mixes
pipe-varying indices with data-varying batches and model-varying shards.

Gradient semantics are exact under both schedules and all compositions:
microbatch weight-grads sum to the full-batch grad, so PP's final params
equal the single-device run's (and dp x pp [x tp] equals DDP over the
data axis alone) — differential tests assert every composition. Weight
grads never cross stages; each stage runs SGD on its own layers
(``train_ffns.py:311-312`` locality, transplanted to the layer dimension).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, reshard_copy
from ..optim import sgd
from ..ops.ffn import ffn_fwd, ffn_bwd
from ..ops.stack import stack_fwd, stack_bwd
from .collectives import all_reduce, ring_shift, axis_index, barrier
from .launcher import launch, launch_strided
from .mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, require_axes


def _send(x, axis: str, shift: int):
    """One inter-stage activation/grad transfer (``ring_shift``) under
    the "comm" named scope — the pipeline's p2p traffic folds into the
    pp strategy's comm region in traces and HLO
    (utils/trace_analysis.SCOPES)."""
    with jax.named_scope("comm"):
        return ring_shift(x, axis, shift=shift)


# Layers are staged: stacked layer axis sharded across the pipe ring.
PARAM_SPECS = FFNStackParams(w1=P(PIPE_AXIS, None, None),
                             w2=P(PIPE_AXIS, None, None))
# With a model axis, each stage's layers are additionally Megatron-sharded
# (w1 column-parallel on ffn, w2 row-parallel on ffn — tp.py's layout).
PARAM_SPECS_TP = FFNStackParams(w1=P(PIPE_AXIS, MODEL_AXIS, None),
                                w2=P(PIPE_AXIS, None, MODEL_AXIS))

SCHEDULES = ("gpipe", "1f1b", "interleaved")


def shard_params(params: FFNStackParams, mesh,
                 specs: FFNStackParams = PARAM_SPECS) -> FFNStackParams:
    return reshard_copy(params, FFNStackParams(
        w1=NamedSharding(mesh, specs.w1),
        w2=NamedSharding(mesh, specs.w2)))


def _vary_to(t, vary_axes):
    """Normalize ``t`` to vary over ``vary_axes``: schedule carries and
    ``cond``/``switch`` branch outputs must share one vma type even
    though their ingredients vary over different axis subsets (pipe
    indices, data batches, model shards)."""
    need = tuple(a for a in vary_axes if a not in jax.typeof(t).vma)
    return lax.pcast(t, need, to="varying") if need else t


def _vzeros(shape, dtype, vary_axes):
    return _vary_to(jnp.zeros(shape, dtype), vary_axes)


def _vary_tree(tree, vary_axes):
    """``_vary_to`` over a pytree — normalizes a schedule branch's whole
    output tuple in one place for both schedules."""
    return jax.tree_util.tree_map(lambda t: _vary_to(t, vary_axes), tree)


def _acts_struct(stage_fwd, params, x0):
    """Shape/dtype of one microbatch's stashed residuals (trace-only)."""
    return jax.eval_shape(lambda p, x: stage_fwd(p, x)[1], params, x0)


def _grad_zeros(params, vary_axes):
    """Per-leaf gradient accumulators typed over ``vary_axes`` UNION the
    leaf's own vma: a model-sharded leaf's grads vary over the model axis
    even when the schedule carries (activation stream) deliberately do not
    (tp_block requires a model-invariant stream — see
    ``make_transformer_pp_step``)."""
    return jax.tree_util.tree_map(
        lambda l: _vary_to(jnp.zeros_like(l),
                           tuple(vary_axes) + tuple(jax.typeof(l).vma)),
        params)


def _gpipe_step(params, x_mb, dy_mb, s, M: int, S: int,
                axis: str, vary_axes, stage_fwd, stage_bwd):
    """GPipe: forward wavefront, fence, backward wavefront.

    Generic over the stage compute: ``stage_fwd(params, x) -> (y, acts)``
    and ``stage_bwd(dy, params, acts, m) -> (dx, grads)`` (``m`` = the
    microbatch index, for stages whose backward needs per-microbatch data
    — the LM head recomputes its targets from it) where ``params`` /
    ``grads`` are any matching pytree and ``acts`` is a stashable array
    pytree (the FFN stack stashes block inputs, the transformer stack
    block inputs of its blocks — both recompute internals in backward)."""
    x_shape, dtype = x_mb.shape[1:], x_mb.dtype
    ticks = M + S - 1

    def vary(tree):
        return _vary_tree(tree, vary_axes)

    def stash_zeros(struct):
        return jax.tree_util.tree_map(
            lambda l: _vzeros((M,) + l.shape, l.dtype, vary_axes), struct)

    # ---- forward wavefront: activation streams +1 around the ring ----
    state = _vzeros(x_shape, dtype, vary_axes)
    stash = stash_zeros(_acts_struct(stage_fwd, params, x_mb[0]))
    for t in range(ticks):
        m = t - s  # this stage's microbatch this tick (traced: s varies)
        valid = (m >= 0) & (m < M)
        mc = jnp.clip(m, 0, M - 1)
        # stage 0 injects microbatch t; everyone else consumes the recv
        inp = jnp.where(s == 0, x_mb[min(t, M - 1)], state)

        def fwd_branch(stash):
            y, acts = stage_fwd(params, inp)
            stash = jax.tree_util.tree_map(
                lambda st, a: st.at[mc].set(a), stash, acts)
            return vary((stash, y))

        def fwd_idle(stash):
            return stash, _vzeros(x_shape, dtype, vary_axes)

        # bubble ticks skip the block compute entirely (idle branch), they
        # don't compute-and-mask
        stash, y = lax.cond(valid, fwd_branch, fwd_idle, stash)
        state = _send(y, axis, 1)

    # the reference's host-side Barrier between phases
    # (test_mp_barrier_gpus.py:32-34) becomes an in-program fence on
    # the stash the backward consumes
    stash = barrier(stash, axis)

    # ---- backward wavefront: grads stream -1 around the ring ----
    dstate = _vzeros(x_shape, dtype, vary_axes)
    grads = _grad_zeros(params, vary_axes)
    for u in range(ticks):
        m = u - (S - 1) + s  # stage s backward-processes microbatch m
        valid = (m >= 0) & (m < M)
        mc = jnp.clip(m, 0, M - 1)
        dy_in = jnp.where(s == S - 1, dy_mb[min(u, M - 1)], dstate)

        def bwd_branch(grads):
            dx, dg = stage_bwd(
                dy_in, params,
                jax.tree_util.tree_map(lambda st: st[mc], stash), mc)
            return vary((jax.tree_util.tree_map(jnp.add, grads, dg), dx))

        def bwd_idle(grads):
            return grads, _vzeros(x_shape, dtype, vary_axes)

        grads, dx = lax.cond(valid, bwd_branch, bwd_idle, grads)
        dstate = _send(dx, axis, -1)

    return grads


def _1f1b_step(params, x_mb, dy_mb, s, M: int, S: int,
               axis: str, vary_axes, stage_fwd, stage_bwd):
    """1F1B: one slot stream; stage ``s`` forwards microbatch ``m`` at slot
    ``s + 2m`` and backwards it at slot ``2S - 1 - s + 2m``. The two land
    on opposite slot parities per stage, so every slot is exactly one of
    {forward, backward, bubble} — picked by ``lax.switch``. The circular
    stash never clobbers a live entry: slot ``m % K``'s next write
    (forward of ``m + K``) happens at ``s + 2m + 2K >= s + 2m + 2S``,
    after its read (backward of ``m``) at ``2S - 1 - s + 2m``. Generic
    over the stage compute (see ``_gpipe_step``)."""
    x_shape, dtype = x_mb.shape[1:], x_mb.dtype
    K = min(S, M)  # in-flight microbatches per stage — the 1F1B bound

    def vary(tree):
        return _vary_tree(tree, vary_axes)

    state_f = _vzeros(x_shape, dtype, vary_axes)  # activation from s-1
    state_b = _vzeros(x_shape, dtype, vary_axes)  # gradient from s+1
    stash = jax.tree_util.tree_map(
        lambda l: _vzeros((K,) + l.shape, l.dtype, vary_axes),
        _acts_struct(stage_fwd, params, x_mb[0]))
    grads = _grad_zeros(params, vary_axes)

    for tau in range(2 * (M + S - 1)):
        mf = (tau - s) // 2  # fwd microbatch, live when (tau - s) is even
        f_valid = ((tau - s) % 2 == 0) & (mf >= 0) & (mf < M)
        mbk = (tau + s + 1 - 2 * S) // 2  # bwd microbatch, opposite parity
        b_valid = ((tau + s + 1 - 2 * S) % 2 == 0) & (mbk >= 0) & (mbk < M)
        mfc = jnp.clip(mf, 0, M - 1)
        mbc = jnp.clip(mbk, 0, M - 1)

        inp = jnp.where(s == 0, x_mb[mfc], state_f)
        dy_in = jnp.where(s == S - 1, dy_mb[mbc], state_b)

        def idle(carry):
            stash, grads = carry
            z = _vzeros(x_shape, dtype, vary_axes)
            return stash, grads, z, z

        def fwd_branch(carry):
            stash, grads = carry
            y, acts = stage_fwd(params, inp)
            stash = jax.tree_util.tree_map(
                lambda st, a: st.at[mfc % K].set(a), stash, acts)
            return vary((stash, grads, y, jnp.zeros(x_shape, dtype)))

        def bwd_branch(carry):
            stash, grads = carry
            dx, dg = stage_bwd(
                dy_in, params,
                jax.tree_util.tree_map(lambda st: st[mbc % K], stash),
                mbc)
            return vary((stash, jax.tree_util.tree_map(jnp.add, grads, dg),
                         jnp.zeros(x_shape, dtype), dx))

        which = jnp.where(f_valid, 1, jnp.where(b_valid, 2, 0))
        stash, grads, y, dx = lax.switch(
            which, (idle, fwd_branch, bwd_branch), (stash, grads))
        state_f = _send(y, axis, 1)
        state_b = _send(dx, axis, -1)

    return grads


def interleave_perm(n_layers: int, n_stages: int, v: int) -> list:
    """Device-major layer order for the interleaved schedule: canonical
    layer ``l`` lives in virtual stage ``q = l // Lc`` (chunk ``c = q //
    S`` of device ``d = q % S``). The returned ``perm`` satisfies
    ``new[j] = old[perm[j]]`` and groups each device's ``v``
    non-contiguous chunks contiguously (``[S, v, Lc]`` order), so the
    standard contiguous ``P(PIPE_AXIS, ...)`` sharding lands chunk ``c``
    of device ``d`` exactly where the schedule's ``[v, Lc]`` local view
    expects it. ``argsort(perm)`` inverts it."""
    lc = n_layers // (n_stages * v)
    perm = []
    for d in range(n_stages):
        for c in range(v):
            q = c * n_stages + d
            perm.extend(range(q * lc, (q + 1) * lc))
    return perm


def _interleave_apply(tree, n_layers: int, S: int, V: int):
    """Validate the chunking and permute ``tree``'s stacked leaves into
    device-major order; returns ``(permuted_tree, perm)``. Shared by all
    three family trainers so the checks/permutation can't drift."""
    if V < 1:
        raise ValueError(f"interleave must be >= 1, got {V}")
    if n_layers % (S * V):
        raise ValueError(f"{n_layers} layers not divisible into {S} "
                         f"stages x {V} virtual chunks")
    idx = jnp.asarray(interleave_perm(n_layers, S, V))
    return jax.tree_util.tree_map(lambda w: w[idx], tree), idx


def _interleave_restore(tree, perm):
    """Invert ``_interleave_apply`` on the trained output."""
    inv = jnp.argsort(perm)
    return jax.tree_util.tree_map(lambda w: w[inv], tree)


def _interleaved_step(params, x_mb, dy_mb, s, M: int, S: int, V: int,
                      axis: str, vary_axes, chunk_fwd, chunk_bwd,
                      is_static=None):
    """Megatron-style interleaved virtual stages: each device holds ``V``
    non-contiguous layer chunks (virtual stage ``q = c*S + d`` on device
    ``d``), so the round-robin placement makes EVERY virtual-stage
    transition a ``+1`` ring hop — including the wrap from device
    ``S-1``'s chunk ``c`` to device 0's chunk ``c+1``. A wavefront over
    the ``V*S`` virtual stages then packs perfectly: device ``d``
    forwards microbatch ``m = g*S + r`` through chunk ``c`` at slot
    ``t = g*V*S + c*S + d + r`` — one chunk compute per slot, busy for
    ``V*S`` consecutive slots per microbatch group of ``S``. The fill
    cost is ``S - 1`` *chunk*-slots (each ``1/V`` of a stage's work)
    instead of GPipe's ``S - 1`` stage-slots: the pipeline bubble
    shrinks by ``1/V`` — fraction ``(S-1)/(V*M + S - 1)`` versus GPipe's
    ``(S-1)/(M + S - 1)`` (Megatron-LM's interleaved-schedule result,
    Narayanan et al. 2021, built lockstep/SPMD here instead of with
    per-rank NCCL streams).

    The backward phase mirrors it exactly (reversed chain, ``-1`` hops):
    bwd of chunk ``c`` on device ``d`` at slot ``g*V*S + (V-1-c)*S +
    (S-1-d) + r``. Memory: the stash holds all ``[V, M]`` chunk
    activations (= GPipe's M stage-activations); this schedule buys
    bubble, ``"1f1b"`` buys memory — both compose with data/model axes.
    Weight grads accumulate per chunk (``.at[c].add``) and never cross
    stages (``train_ffns.py:311-312`` locality).

    ``is_static(path) -> bool`` marks leaves that are NOT layer-stacked
    (the LM's ``wte``/``wpe``/``ln_f``): they pass to every chunk whole,
    and their grads accumulate unchunked. Chunk-role gating (the LM's
    head on the last virtual stage, embed on the first) lives in the
    family's ``chunk_bwd`` via its 5th argument — the chunk index."""
    x_shape, dtype = x_mb.shape[1:], x_mb.dtype
    P_ = V * S
    # last valid forward slot: microbatch M-1 (group g0, offset r0)
    # through the last virtual stage (c = V-1, d = S-1)
    g0, r0 = (M - 1) // S, (M - 1) % S
    ticks = g0 * P_ + (V - 1) * S + (S - 1) + r0 + 1
    static = is_static if is_static is not None else (lambda path: False)
    tmap = jax.tree_util.tree_map_with_path

    def vary(tree):
        return _vary_tree(tree, vary_axes)

    # local chunked view of the device-major layer axis: [V*Lc] -> [V, Lc]
    def _chunked(w):
        if w.shape[0] % V:
            # direct make_*_step callers bypass the trainers'
            # _interleave_apply check — fail clean at trace, not with an
            # opaque reshape error
            raise ValueError(f"local layer dim {w.shape[0]} not "
                             f"divisible by interleave={V}")
        return w.reshape((V, w.shape[0] // V) + w.shape[1:])

    cparams = tmap(lambda p, w: w if static(p) else _chunked(w), params)

    def chunk_at(c):
        return tmap(lambda p, w: w if static(p) else w[c], cparams)

    def fwd_coords(t):
        k = t - s  # traced: s = axis_index; jnp //,% are floor/Python-mod,
        g, rem = k // P_, k % P_  # so k < 0 yields m < 0 => invalid
        c, r = rem // S, rem % S
        m = g * S + r
        valid = (k >= 0) & (m >= 0) & (m < M)
        return valid, jnp.clip(c, 0, V - 1), jnp.clip(m, 0, M - 1)

    def bwd_coords(u):
        k = u - (S - 1 - s)  # mirrored chain: chunk V-1-ch, device S-1-d
        g, rem = k // P_, k % P_
        ch, r = rem // S, rem % S
        m = g * S + r
        valid = (k >= 0) & (m >= 0) & (m < M)
        return valid, jnp.clip(V - 1 - ch, 0, V - 1), jnp.clip(m, 0, M - 1)

    acts_struct = jax.eval_shape(lambda p, x: chunk_fwd(p, x)[1],
                                 chunk_at(0), x_mb[0])
    stash = jax.tree_util.tree_map(
        lambda l: _vzeros((V, M) + l.shape, l.dtype, vary_axes),
        acts_struct)

    # ---- forward wavefront over the V*S virtual stages ----
    state = _vzeros(x_shape, dtype, vary_axes)
    for t in range(ticks):
        valid, c, m = fwd_coords(t)
        # virtual stage 0 (chunk 0 of device 0) injects fresh microbatches
        inp = jnp.where((s == 0) & (c == 0), x_mb[m], state)

        def fwd_branch(stash):
            y, acts = chunk_fwd(chunk_at(c), inp)
            stash = jax.tree_util.tree_map(
                lambda st, a: st.at[c, m].set(a), stash, acts)
            return vary((stash, y))

        def fwd_idle(stash):
            return stash, _vzeros(x_shape, dtype, vary_axes)

        stash, y = lax.cond(valid, fwd_branch, fwd_idle, stash)
        state = _send(y, axis, 1)

    stash = barrier(stash, axis)  # the inter-phase fence (as in GPipe)

    # ---- backward wavefront: mirrored chain, grads stream -1 ----
    dstate = _vzeros(x_shape, dtype, vary_axes)
    grads = _grad_zeros(cparams, vary_axes)
    for u in range(ticks):
        valid, c, m = bwd_coords(u)
        # the LAST virtual stage (chunk V-1 of device S-1) injects dloss
        dy_in = jnp.where((s == S - 1) & (c == V - 1), dy_mb[m], dstate)

        def bwd_branch(grads):
            dx, dg = chunk_bwd(
                dy_in, chunk_at(c),
                jax.tree_util.tree_map(lambda st: st[c, m], stash), m, c)
            grads = tmap(
                lambda p, acc, g: acc + g if static(p)
                else acc.at[c].add(g), grads, dg)
            return vary((grads, dx))

        def bwd_idle(grads):
            return grads, _vzeros(x_shape, dtype, vary_axes)

        grads, dx = lax.cond(valid, bwd_branch, bwd_idle, grads)
        dstate = _send(dx, axis, -1)

    # back to the flat (device-major) local layer axis
    return tmap(
        lambda p, g: g if static(p)
        else g.reshape((g.shape[0] * g.shape[1],) + g.shape[2:]), grads)


def _make_sched(schedule: str, interleave: int, is_static=None):
    """Uniform schedule dispatch: every schedule is called as
    ``sched(params, x_mb, dy_mb, s, M, S, axis, vary_axes, sf, sb)``.
    ``sb`` takes ``(dy, params, acts, m)`` plus, under the interleaved
    schedule, the chunk index as a 5th argument."""
    if schedule == "interleaved":
        def sched(params, x_mb, dy_mb, s, M, S, axis, vary_axes, sf, sb):
            return _interleaved_step(params, x_mb, dy_mb, s, M, S,
                                     interleave, axis, vary_axes, sf, sb,
                                     is_static=is_static)
        return sched
    return _gpipe_step if schedule == "gpipe" else _1f1b_step


def make_step(batch_size: int, model_size: int, n_stages: int,
              n_microbatches: int, lr: float = LR, axis: str = PIPE_AXIS,
              schedule: str = "gpipe", data_axis: str | None = None,
              model_axis: str | None = None, interleave: int = 2):
    """One PP step for one stage (local views: ``w1 [L/S, ffn(/n), d]``).

    ``data_axis`` strides the batch DDP-style (the seed arriving here is
    already this replica's column) and psums weight grads; ``model_axis``
    runs each block Megatron-sharded with one ``psum`` per layer per
    direction inside the stage (``tp.py`` semantics on the pipe ring).
    ``interleave`` (schedule="interleaved" only) is the virtual-stage
    count per device; the caller must hand params in ``interleave_perm``
    device-major layer order."""
    S, M = n_stages, n_microbatches
    if batch_size % M:
        raise ValueError(f"tokens {batch_size} not divisible by "
                         f"{M} microbatches")
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(expected one of {SCHEDULES})")
    mb = batch_size // M
    sched = _make_sched(schedule, interleave)
    vary_axes = tuple(a for a in (axis, data_axis, model_axis) if a)

    if model_axis is None:
        block_fwd, block_bwd = ffn_fwd, ffn_bwd
    else:
        def block_fwd(w1_shard, w2_shard, x):
            # Megatron g: partial y per model shard, then psum — the TP
            # reduction rides the model axis inside the stage compute
            return all_reduce(ffn_fwd(w1_shard, w2_shard, x), model_axis)

        def block_bwd(dy, w1_shard, w2_shard, x):
            dx, grads = ffn_bwd(dy, w1_shard, w2_shard, x)
            return all_reduce(dx, model_axis), grads

    def stage_fwd(p: FFNStackParams, x):
        return stack_fwd(p.w1, p.w2, x, block_fwd=block_fwd)

    def stage_bwd(dy, p: FFNStackParams, acts, m, chunk=0):
        dx, (g1, g2) = stack_bwd(dy, p.w1, p.w2, acts,
                                 block_bwd=block_bwd)
        return dx, FFNStackParams(g1, g2)

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        # named-scope regions (pp/fwd, pp/bwd via the stage walks,
        # pp/comm on the ring transfers + DDP psum, pp/optim)
        with jax.named_scope("pp"):
            s = axis_index(axis)
            x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                          params.w1.dtype)
            x_mb = x.reshape(M, mb, model_size)
            dy_mb = dloss_dx.reshape(M, mb, model_size)
            grads = sched(params, x_mb, dy_mb, s, M, S, axis, vary_axes,
                          stage_fwd, stage_bwd)
            if data_axis is not None:
                with jax.named_scope("comm"):
                    # DDP reduction across pipeline replicas (SUM,
                    # unscaled LR, train_ffns.py:165 semantics)
                    grads = jax.tree_util.tree_map(
                        lambda g: all_reduce(g, data_axis), grads)
            with jax.named_scope("optim"):
                # per-stage SGD on the stage's own layers (and model shard)
                return sgd(params, grads, lr)

    return step


def make_transformer_pp_step(batch_size: int, model_size: int,
                             seq_len: int, n_heads: int, n_stages: int,
                             n_microbatches: int, lr: float = LR,
                             axis: str = PIPE_AXIS,
                             schedule: str = "gpipe",
                             data_axis: str | None = None,
                             model_axis: str | None = None,
                             causal: bool = True, attn=None,
                             interleave: int = 2):
    """One transformer-PP step for one stage: the same two schedules over
    stages of pre-LN blocks (``[L/S]`` blocks per stage, activations
    ``[mb, T, d]``). The stash keeps each block's *input* only; the
    backward recomputes block internals via ``jax.vjp`` of the block at
    the stashed input — the framework's recompute policy
    (``train_ffns.py:63``) transplanted to the transformer stage. With a
    ``model_axis``, each stage's blocks run Megatron-sharded (``tp_block``:
    heads column-, wo/w2 row-parallel, psums riding the orthogonal model
    axis inside the stage compute)."""
    from ..models.transformer import TransformerParams, transformer_block
    from .transformer import tp_block
    S, M = n_stages, n_microbatches
    b = batch_size // seq_len
    if batch_size % seq_len:
        raise ValueError(f"tokens {batch_size} not divisible by "
                         f"seq_len {seq_len}")
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} microbatches")
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(expected one of {SCHEDULES})")
    mb = b // M
    sched = _make_sched(schedule, interleave)
    # The model axis is deliberately NOT in the carry typing: tp_block's
    # f-gate discipline (psum exactly the pending cotangents) requires the
    # activation stream typed invariant over the model axis — its psums
    # and residual adds preserve that; force-casting the stream varying
    # makes complete cotangents look partial and over-reduces (measured:
    # every grad off by O(1) at tp=2). Sharded param grads still type
    # model-varying via _grad_zeros' per-leaf union.
    vary_axes = tuple(a for a in (axis, data_axis) if a)

    if model_axis is None:
        def block(leaves, x):
            return transformer_block(*leaves, x, n_heads, causal, attn)
    else:
        def block(leaves, x):
            return tp_block(*leaves, x, n_heads, axis=model_axis,
                            causal=causal, attn=attn)

    def stage_fwd(p: TransformerParams, x):
        with jax.named_scope("fwd"):
            acts = []
            for l in range(p.ln1.shape[0]):
                acts.append(x)
                x = block(tuple(leaf[l] for leaf in p), x)
            return x, jnp.stack(acts)      # [L/S, mb, T, d] block inputs

    def stage_bwd(dy, p: TransformerParams, acts, m, chunk=0):
        with jax.named_scope("bwd"):
            grads = jax.tree_util.tree_map(jnp.zeros_like, p)
            for l in reversed(range(p.ln1.shape[0])):
                leaves = tuple(leaf[l] for leaf in p)
                _, vjp = jax.vjp(block, leaves, acts[l])
                dleaves, dy = vjp(dy)
                grads = TransformerParams(*(
                    g.at[l].set(dg) for g, dg in zip(grads, dleaves)))
            return dy, grads

    def step(params: TransformerParams, seed) -> TransformerParams:
        from .transformer import _reshape_batch
        s = axis_index(axis)
        x, dloss_dx = _reshape_batch(seed, batch_size, seq_len, model_size,
                                     params.ln1.dtype)
        x_mb = x.reshape(M, mb, seq_len, model_size)
        dy_mb = dloss_dx.reshape(M, mb, seq_len, model_size)
        # Type the params varying over every schedule axis BEFORE the
        # block vjps: the attention projections are plain ops, and
        # against data-invariant params their transposes auto-insert a
        # psum over the data axis (the pvary transpose) — which the
        # explicit all_reduce below would double-count. Varying-typed
        # params keep every weight cotangent partial, exactly like the
        # custom_vjp rules' (grad_reduce doctrine, collectives.py), so
        # the explicit reductions below are the only ones.
        with jax.named_scope("pp"):
            grads = sched(_vary_tree(params, vary_axes), x_mb, dy_mb, s,
                          M, S, axis, vary_axes, stage_fwd, stage_bwd)
            # LN-gain grads need no model-axis collective: the stream
            # typing keeps them invariant (complete, identical on every
            # model shard); if that ever regressed, the scan-carry
            # typecheck fails at trace.
            if data_axis is not None:
                with jax.named_scope("comm"):
                    grads = jax.tree_util.tree_map(
                        lambda g: all_reduce(g, data_axis), grads)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return step


def train_transformer_pp(params, seeds, batch_size: int, model_size: int,
                         mesh, lr: float = LR, *, seq_len: int,
                         n_heads: int, n_microbatches: int | None = None,
                         schedule: str = "gpipe", causal: bool = True,
                         attn_impl: str | None = None,
                         interleave: int = 2):
    """Pipeline the transformer family over the ``"pipe"`` ring, with the
    same mesh compositions as the FFN path: ``data`` replicates the
    pipeline (strided seeds, one grad psum), ``model`` Megatron-shards
    each stage's blocks — ``data x pipe x model`` on one mesh. A pure
    pipe mesh equals the single-device transformer run (microbatch grads
    sum to the full-batch grad); every composition is differential-tested.
    Microbatching splits the *batch* dim (sequences stay whole — attention
    needs them)."""
    from ..models.transformer import TransformerParams
    from .transformer import _validate_shapes, _validate_tp, resolve_attn
    require_axes(mesh, PIPE_AXIS)
    shape = dict(mesh.shape)
    S = shape[PIPE_AXIS]
    dp = shape.get(DATA_AXIS, 1)
    tp_n = shape.get(MODEL_AXIS, 1)
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    if params.ln1.shape[0] % S:
        raise ValueError(f"{params.ln1.shape[0]} layers not divisible "
                         f"into {S} pipeline stages")
    perm = None
    if schedule == "interleaved":
        params, perm = _interleave_apply(params, params.ln1.shape[0], S,
                                         interleave)
    h_eff = n_heads
    if tp_n > 1:
        h_eff = _validate_tp(params, n_heads, tp_n)
    M = S if n_microbatches is None else n_microbatches

    col = P(PIPE_AXIS, MODEL_AXIS, None) if tp_n > 1 \
        else P(PIPE_AXIS, None, None)
    row = P(PIPE_AXIS, None, MODEL_AXIS) if tp_n > 1 \
        else P(PIPE_AXIS, None, None)
    specs = TransformerParams(
        ln1=P(PIPE_AXIS, None), wq=col, wk=col, wv=col, wo=row,
        ln2=P(PIPE_AXIS, None), w1=col, w2=row)
    sharded = reshard_copy(params, jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda v: isinstance(v, P)))
    step = make_transformer_pp_step(
        batch_size, model_size, seq_len, h_eff, S, M, lr,
        schedule=schedule, data_axis=DATA_AXIS if dp > 1 else None,
        model_axis=MODEL_AXIS if tp_n > 1 else None, causal=causal,
        attn=resolve_attn(attn_impl), interleave=interleave)

    if dp > 1:
        out = launch_strided(step, sharded, seeds, mesh, DATA_AXIS, specs)
    else:
        out = launch(step, sharded, jnp.asarray(seeds), mesh,
                     param_specs=specs, seed_spec=P())
    if perm is not None:
        out = _interleave_restore(out, perm)
    return out


def make_lm_pp_step(batch_size: int, model_size: int, seq_len: int,
                    n_heads: int, vocab: int, n_stages: int,
                    n_microbatches: int, lr: float = LR,
                    axis: str = PIPE_AXIS, schedule: str = "gpipe",
                    data_axis: str | None = None, attn=None,
                    interleave: int = 2):
    """One LM-PP step for one stage: the full language model pipelined —
    embedding on stage 0, transformer-block stages along the ring, tied
    head + REAL cross-entropy on the last stage. Runs under both
    schedules: the stage roles are runtime-gated on ``axis_index`` inside
    the uniform SPMD stage functions (``lax.cond`` on a shard-varying
    stage id, the schedules' bubble-skipping mechanism):

    - every stage stashes its block inputs AND its output, so the last
      stage's backward can start from the loss: it recomputes its
      microbatch's targets from the step seed (``m`` passed by the
      schedules), takes the head+xent vjp at the stashed output
      (1/M-scaled — microbatch means sum to the full-batch mean), and
      feeds the result into its block walk in place of the ring ``dy``;
    - stage 0's backward folds the embedding vjp of its final ``dx``
      into the gradient tree.

    Embedding/head/final-LN grads are per-stage partials (zero on
    non-owner stages) completed by one ``psum`` over the pipe axis; block
    grads stay stage-local. ``data_axis`` composes DDP exactly as the
    other PP families."""
    from ..data import lm_batch_from_seed
    from ..models.lm import LMParams
    from ..models.transformer import transformer_block
    from ..ops.norm import layernorm
    from ..ops.xent import xent_loss
    S, M = n_stages, n_microbatches
    if batch_size % seq_len:
        raise ValueError(f"tokens {batch_size} not divisible by "
                         f"seq_len {seq_len}")
    b = batch_size // seq_len
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} microbatches")
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         f"(expected one of {SCHEDULES})")
    mb = b // M
    V = interleave if schedule == "interleaved" else 1
    # the LM's unstacked leaves ride every chunk whole; blocks chunk
    sched = _make_sched(schedule, V,
                        is_static=lambda path: path[0].name != "blocks")
    vary_axes = tuple(a for a in (axis, data_axis) if a)

    def blocks_walk_fwd(p: LMParams, x):
        with jax.named_scope("fwd"):
            acts = []
            for l in range(p.blocks.ln1.shape[0]):
                acts.append(x)
                x = transformer_block(
                    *(leaf[l] for leaf in p.blocks), x, n_heads,
                    attn=attn)
            return x, (jnp.stack(acts), x)  # block inputs + stage output

    def step(params: LMParams, seed) -> LMParams:
        s = axis_index(axis)
        tokens, targets = lm_batch_from_seed(seed, b, seq_len, vocab)
        x = params.wte[tokens] + params.wpe[:seq_len]   # replicated embed
        x_mb = x.reshape(M, mb, seq_len, model_size)
        dy_mb = jnp.zeros_like(x_mb)  # unused: the head replaces it

        def vary(tree):
            return _vary_tree(tree, vary_axes)

        def stage_bwd(dy_in, p: LMParams, acts, m, chunk=0):
            block_inputs, y_out = acts
            tok_mb = lax.dynamic_slice_in_dim(tokens, m * mb, mb, 0)
            tgt_mb = lax.dynamic_slice_in_dim(targets, m * mb, mb, 0)
            # role gates: the head lives after the LAST virtual stage
            # (chunk V-1 of the last device), the embedding before the
            # first (chunk 0 of device 0); for gpipe/1f1b V == 1 and
            # these reduce to the plain stage conditions
            is_head = (s == S - 1) & jnp.equal(chunk, V - 1)
            is_embed = (s == 0) & jnp.equal(chunk, 0)

            def head_branch(_):
                def head_loss(ln_f, wte, h):
                    hh = layernorm(ln_f, h).reshape(-1, model_size)
                    return xent_loss(hh @ wte.T,
                                     tgt_mb.reshape(-1)) / M
                dln_f, dwte, dy = jax.grad(head_loss, argnums=(0, 1, 2))(
                    p.ln_f, p.wte, y_out)
                return vary((dy, dln_f, dwte))

            def ring_branch(_):
                return vary((dy_in, jnp.zeros_like(p.ln_f),
                             jnp.zeros_like(p.wte)))

            dy_eff, g_lnf, g_wte = lax.cond(is_head, head_branch,
                                            ring_branch, None)

            # block walk (recompute internals at the stashed inputs)
            bgrads = jax.tree_util.tree_map(jnp.zeros_like, p.blocks)
            dy = dy_eff
            for l in reversed(range(p.blocks.ln1.shape[0])):
                leaves = tuple(leaf[l] for leaf in p.blocks)
                _, vjp = jax.vjp(
                    lambda lv, xx: transformer_block(*lv, xx, n_heads,
                                                     attn=attn),
                    leaves, block_inputs[l])
                dleaves, dy = vjp(dy)
                bgrads = type(p.blocks)(*(
                    g.at[l].set(dg) for g, dg in zip(bgrads, dleaves)))

            def embed_branch(_):
                def embed(wte, wpe):
                    return (wte[tok_mb]
                            + lax.dynamic_slice_in_dim(wpe, 0, seq_len, 0))
                _, evjp = jax.vjp(embed, p.wte, p.wpe)
                return vary(tuple(evjp(dy)))

            def no_embed(_):
                return vary((jnp.zeros_like(p.wte),
                             jnp.zeros_like(p.wpe)))

            g_wte_e, g_wpe = lax.cond(is_embed, embed_branch, no_embed,
                                      None)
            grads = LMParams(wte=g_wte + g_wte_e, wpe=g_wpe,
                             blocks=bgrads, ln_f=g_lnf)
            return dy, grads

        def stage_bwd_scoped(*a, **kw):
            with jax.named_scope("bwd"):
                return stage_bwd(*a, **kw)

        with jax.named_scope("pp"):
            grads = sched(_vary_tree(params, vary_axes), x_mb, dy_mb, s,
                          M, S, axis, vary_axes, blocks_walk_fwd,
                          stage_bwd_scoped)
            with jax.named_scope("comm"):
                # embedding/head/final-LN grads live on 1-2 stages; the
                # psum over the pipe ring completes them (others
                # contributed zeros)
                grads = grads._replace(wte=all_reduce(grads.wte, axis),
                                       wpe=all_reduce(grads.wpe, axis),
                                       ln_f=all_reduce(grads.ln_f, axis))
                if data_axis is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g: all_reduce(g, data_axis), grads)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return step


def train_lm_pp(params, seeds, batch_size: int, model_size: int, mesh,
                lr: float = LR, *, seq_len: int, n_heads: int,
                n_microbatches: int | None = None,
                schedule: str = "gpipe", attn_impl: str | None = None,
                interleave: int = 2):
    """Pipeline the full LM over the ``"pipe"`` ring (embedding on stage
    0, blocks staged, tied head + real loss on the last stage); a
    ``data`` axis composes DDP. Pipe-only equals the single-device LM
    trainer (microbatch mean-losses are 1/M-scaled so their grads sum to
    the full-batch mean's); differential-tested under both schedules."""
    from ..models.lm import LMParams
    require_axes(mesh, PIPE_AXIS)
    shape = dict(mesh.shape)
    S = shape[PIPE_AXIS]
    dp = shape.get(DATA_AXIS, 1)
    if model_size % n_heads:
        raise ValueError(f"model_size={model_size} not divisible by "
                         f"n_heads={n_heads}")
    if seq_len > params.max_seq_len:
        raise ValueError(f"seq_len={seq_len} exceeds max_seq_len="
                         f"{params.max_seq_len}")
    if params.blocks.ln1.shape[0] % S:
        raise ValueError(f"{params.blocks.ln1.shape[0]} layers not "
                         f"divisible into {S} pipeline stages")
    perm = None
    if schedule == "interleaved":
        blocks, perm = _interleave_apply(
            params.blocks, params.blocks.ln1.shape[0], S, interleave)
        params = params._replace(blocks=blocks)
    M = S if n_microbatches is None else n_microbatches
    blk = P(PIPE_AXIS, None, None)
    specs = LMParams(
        wte=P(), wpe=P(),
        blocks=type(params.blocks)(
            ln1=P(PIPE_AXIS, None), wq=blk, wk=blk, wv=blk, wo=blk,
            ln2=P(PIPE_AXIS, None), w1=blk, w2=blk),
        ln_f=P())
    sharded = reshard_copy(params, jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda v: isinstance(v, P)))
    from .transformer import resolve_attn
    step = make_lm_pp_step(batch_size, model_size, seq_len, n_heads,
                           params.vocab, S, M, lr, schedule=schedule,
                           data_axis=DATA_AXIS if dp > 1 else None,
                           attn=resolve_attn(attn_impl),
                           interleave=interleave)
    if dp > 1:
        out = launch_strided(step, sharded, seeds, mesh, DATA_AXIS, specs)
    else:
        out = launch(step, sharded, jnp.asarray(seeds), mesh,
                     param_specs=specs, seed_spec=P())
    if perm is not None:
        out = out._replace(blocks=_interleave_restore(out.blocks, perm))
    return out


def train_pp(params: FFNStackParams, seeds, batch_size: int,
             model_size: int, mesh, lr: float = LR,
             n_microbatches: int | None = None,
             schedule: str = "gpipe",
             interleave: int = 2) -> FFNStackParams:
    """Run the full PP schedule over ``mesh``. A pure ``("pipe",)`` mesh
    replicates the data (every stage regenerates the step's batch and
    consumes its own slice of the wavefront), so PP equals the
    single-device run. Adding ``"data"`` and/or ``"model"`` axes gives
    dp x pp x tp — 3-D parallelism — which equals DDP over the data axis
    alone (differential tests pin every composition).

    ``schedule="interleaved"`` places ``interleave`` non-contiguous layer
    chunks per device (Megatron virtual stages) to cut the pipeline
    bubble by ``1/interleave``: layers are re-ordered device-major
    (``interleave_perm``) before sharding and restored after, so the
    caller's canonical layer order is preserved end to end."""
    require_axes(mesh, PIPE_AXIS)
    shape = dict(mesh.shape)
    S = shape[PIPE_AXIS]
    dp = shape.get(DATA_AXIS, 1)
    tp_n = shape.get(MODEL_AXIS, 1)
    L = params.w1.shape[0]
    if L % S:
        raise ValueError(f"{L} layers not divisible into "
                         f"{S} pipeline stages")
    if params.w1.shape[1] % tp_n:
        raise ValueError(f"ffn_dim {params.w1.shape[1]} not divisible by "
                         f"{tp_n} model shards")
    perm = None
    if schedule == "interleaved":
        params, perm = _interleave_apply(params, L, S, interleave)
    M = S if n_microbatches is None else n_microbatches
    specs = PARAM_SPECS_TP if tp_n > 1 else PARAM_SPECS
    params = shard_params(params, mesh, specs)
    step = make_step(batch_size, model_size, S, M, lr, schedule=schedule,
                     data_axis=DATA_AXIS if dp > 1 else None,
                     model_axis=MODEL_AXIS if tp_n > 1 else None,
                     interleave=interleave)

    if dp > 1:
        out = launch_strided(step, params, seeds, mesh, DATA_AXIS, specs)
    else:
        out = launch(step, params, jnp.asarray(seeds), mesh,
                     param_specs=specs, seed_spec=P())
    if perm is not None:
        out = _interleave_restore(out, perm)
    return out
