"""Pipeline parallelism: layers staged across the ``"pipe"`` axis, with
hand-rolled ``ppermute`` send/recv and GPipe microbatching.

The reference has **no** pipeline parallelism and no point-to-point
send/recv anywhere (SURVEY.md section 2.2) — but the driver's BASELINE
config 3 asks for an "MP mode, 8-layer FFN split across 4 devices
(exercise send/recv + barrier)". This module is that path, built the TPU
way: one SPMD program over a ``("pipe",)`` mesh axis where every stage
runs the same code and neighbor transfer is ``lax.ppermute`` over the ICI
ring (``collectives.ring_shift``) — the XLA lowering of NCCL send/recv.

Schedule (GPipe): the step's ``tokens`` are split into ``M`` microbatches.
Forward runs ``M + S - 1`` ticks; at tick ``t`` stage ``s`` computes
microbatch ``t - s`` (a bubble of ``S - 1`` idle ticks per direction is
masked out, the standard GPipe cost). Activations stream stage-to-stage
with a ``+1`` ring shift. The backward walks the same wavefront in
reverse with a ``-1`` shift, consuming per-tick stashed block inputs.
Because the mock loss needs no forward output (``dloss_dx`` is generated
from the step seed, ``train_ffns.py:150``), the last stage starts the
backward from its own locally-generated ``dloss_dx`` — no loss broadcast.

Gradient semantics are exact: microbatch weight-grads sum to the
full-batch grad, so PP's final params equal the single-device run's
bit-for-tolerance (a differential test the suite asserts). Weight grads
never cross stages; each stage runs SGD on its own layers
(``train_ffns.py:311-312`` locality, transplanted to the layer dimension).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, reshard_copy
from ..optim import sgd
from ..ops.stack import stack_fwd, stack_bwd
from .collectives import ring_shift, axis_index, barrier
from .launcher import launch
from .mesh import PIPE_AXIS, require_axes

# Layers are staged: stacked layer axis sharded across the pipe ring.
PARAM_SPECS = FFNStackParams(w1=P(PIPE_AXIS, None, None),
                             w2=P(PIPE_AXIS, None, None))


def shard_params(params: FFNStackParams, mesh) -> FFNStackParams:
    return reshard_copy(params, FFNStackParams(
        w1=NamedSharding(mesh, PARAM_SPECS.w1),
        w2=NamedSharding(mesh, PARAM_SPECS.w2)))


def make_step(batch_size: int, model_size: int, n_stages: int,
              n_microbatches: int, lr: float = LR, axis: str = PIPE_AXIS):
    """One PP step for one stage (local views: ``w1 [L/S, ffn, d]``)."""
    S, M = n_stages, n_microbatches
    if batch_size % M:
        raise ValueError(f"tokens {batch_size} not divisible by "
                         f"{M} microbatches")
    mb = batch_size // M
    ticks = M + S - 1

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        s = axis_index(axis)
        x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                      params.w1.dtype)
        x_mb = x.reshape(M, mb, model_size)
        dy_mb = dloss_dx.reshape(M, mb, model_size)
        n_local = params.w1.shape[0]

        # ---- forward wavefront: activation streams +1 around the ring ----
        state = jnp.zeros((mb, model_size), x.dtype)
        stash = jnp.zeros((ticks, n_local, mb, model_size), x.dtype)
        for t in range(ticks):
            # stage 0 injects microbatch t; everyone else consumes the recv
            inp = jnp.where(s == 0, x_mb[min(t, M - 1)], state)
            y, acts = stack_fwd(params.w1, params.w2, inp)
            stash = stash.at[t].set(acts)
            state = ring_shift(y, axis, shift=1)

        # the reference's host-side Barrier between phases
        # (test_mp_barrier_gpus.py:32-34) becomes an in-program fence on
        # the stash the backward consumes
        stash = barrier(stash, axis)

        # ---- backward wavefront: grads stream -1 around the ring ----
        dstate = jnp.zeros((mb, model_size), x.dtype)
        g1 = jnp.zeros_like(params.w1)
        g2 = jnp.zeros_like(params.w2)
        for u in range(ticks):
            # stage s backward-processes microbatch m at tick u
            m = u - (S - 1) + s
            valid = (m >= 0) & (m < M)
            dy_in = jnp.where(s == S - 1, dy_mb[min(u, M - 1)], dstate)
            # its forward stash for microbatch m lives at tick m + s
            t_idx = jnp.clip(u - (S - 1) + 2 * s, 0, ticks - 1)
            acts = jnp.take(stash, t_idx, axis=0)
            dx, (dg1, dg2) = stack_bwd(dy_in, params.w1, params.w2, acts)
            g1 = g1 + jnp.where(valid, dg1, jnp.zeros((), g1.dtype))
            g2 = g2 + jnp.where(valid, dg2, jnp.zeros((), g2.dtype))
            dstate = ring_shift(dx, axis, shift=-1)

        # per-stage SGD on the stage's own layers
        return sgd(params, FFNStackParams(g1, g2), lr)

    return step


def train_pp(params: FFNStackParams, seeds, batch_size: int,
             model_size: int, mesh, lr: float = LR,
             n_microbatches: int | None = None) -> FFNStackParams:
    """Run the full PP schedule. Data (seeds) is replicated — every stage
    regenerates the step's batch locally and uses the slice of the
    wavefront that is its own, so PP consumes the same steps as the
    single-device run and must agree with it numerically."""
    require_axes(mesh, PIPE_AXIS)
    S = mesh.shape[PIPE_AXIS]
    if params.w1.shape[0] % S:
        raise ValueError(f"{params.w1.shape[0]} layers not divisible into "
                         f"{S} pipeline stages")
    M = S if n_microbatches is None else n_microbatches
    params = shard_params(params, mesh)
    step = make_step(batch_size, model_size, S, M, lr)

    return launch(step, params, jnp.asarray(seeds), mesh,
                  param_specs=PARAM_SPECS, seed_spec=P())
