"""Transformer trainers: single-device, DDP, FSDP/ZeRO-3, and Megatron TP.

The strategies mirror the FFN-stack ones (``ddp.py``, ``fsdp.py``,
``tp.py``) applied to the full pre-LN block stack (``models.transformer``).
The backward composes the hand-written block rules via ``jax.vjp`` (the
framework's composition precedent), with the collectives placed by hand:

- **DDP**: replicated params, strided seed shards, one grad ``psum`` per
  step (SUM, unscaled LR — ``train_ffns.py:165`` semantics).
- **FSDP**: every param stack sharded over the data axis, layers
  ``all_gather``-ed transiently per step; the gather's AD transpose is
  ``psum_scatter``, which sums grads across shards and scatters them onto
  the local chunks in one collective.
- **TP**: Megatron attention + FFN sharding on the ``"model"`` axis. Heads
  are column-parallel (``wq/wk/wv`` split on the output dim — each shard
  runs ``H/n`` whole heads), ``wo`` row-parallel, FFN ``w1``/``w2``
  column/row-parallel (the existing ``tp.py`` layout), LN replicated. The
  Megatron f/g operator pair is explicit: ``g`` is the forward ``psum``
  after each sublayer's row-parallel matmul (backward: identity — ``psum``'s
  transpose); ``f`` is ``_f_gate`` below — identity forward, ``psum``
  backward — applied to each sublayer's post-LN input so the partial
  input-gradients of the column-parallel projections are summed before
  flowing into the (replicated) LayerNorm backward. Omitting ``f`` leaves
  ``dx`` partial and silently wrong — the TP==single differential test is
  the guard.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import clone_params, reshard_copy
from ..models.transformer import (TransformerParams, attn_sublayer,
                                  transformer_block, transformer_fwd)
from ..ops.ffn import ffn_block
from ..ops.norm import layernorm
from ..optim import sgd
from .collectives import (all_gather, all_reduce, axis_index, grad_reduce,
                          reduce_scatter, vma_erased)
from .launcher import launch, launch_strided
from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, require_axes

# TP layout: column-parallel projections shard the output dim (heads for
# attention, ffn features for w1); row-parallel shard the input dim.
TP_SPECS = TransformerParams(
    ln1=P(), wq=P(None, MODEL_AXIS, None), wk=P(None, MODEL_AXIS, None),
    wv=P(None, MODEL_AXIS, None), wo=P(None, None, MODEL_AXIS),
    ln2=P(), w1=P(None, MODEL_AXIS, None), w2=P(None, None, MODEL_AXIS))

# FSDP layout: every stack sharded on its first per-layer dim (stacked
# axis 1) across the data axis — the reference's chunk-along-dim-0
# (train_ffns.py:265-266) on the transformer's parameter surface.
FSDP_SPECS = TransformerParams(
    ln1=P(None, DATA_AXIS), wq=P(None, DATA_AXIS, None),
    wk=P(None, DATA_AXIS, None), wv=P(None, DATA_AXIS, None),
    wo=P(None, DATA_AXIS, None), ln2=P(None, DATA_AXIS),
    w1=P(None, DATA_AXIS, None), w2=P(None, DATA_AXIS, None))


def _shard(params: TransformerParams, mesh, specs) -> TransformerParams:
    """Lay params out per a spec pytree (fresh buffers, launcher-owned)."""
    return reshard_copy(params, jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda v: isinstance(v, P)))


def _f_gate(axis: str):
    """Megatron's ``f`` operator: identity forward, all-reduce backward —
    but *vma-aware*. Under JAX's varying-manual-axes typing, cotangents
    flowing back through plain ops are auto-reduced when they cross an
    implicit ``pvary`` (its transpose is ``psum``), while cotangents
    produced inside hand-written ``custom_vjp`` rules (``ffn_block``,
    ``attention``) come back still partial (axis in ``typeof(dy).vma``).
    The gate psums exactly when the cotangent is still partial — a static,
    trace-time check — so neither path is double-reduced. (The symptom of
    an unconditional psum: LN grads scale by the axis size on whichever
    sublayer's backward was auto-reduced.)"""

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None),
             lambda _, dy: (grad_reduce(dy, axis, force=vma_erased()),))
    return f


def _reshape_batch(seed, tokens: int, seq_len: int, model_size: int, dtype):
    x, dloss_dx = batch_from_seed(seed, tokens, model_size, dtype)
    b = tokens // seq_len
    return (x.reshape(b, seq_len, model_size),
            dloss_dx.reshape(b, seq_len, model_size))


def _validate_shapes(batch_size: int, seq_len: int, model_size: int,
                     n_heads: int) -> None:
    if batch_size % seq_len:
        raise ValueError(f"tokens {batch_size} not divisible by "
                         f"seq_len {seq_len}")
    if model_size % n_heads:
        raise ValueError(f"model_size={model_size} not divisible by "
                         f"n_heads={n_heads} (head dim must be whole)")


def resolve_attn(attn_impl: str | None):
    """Map an ``attn_impl`` name to the multi-head attention op the model
    plugs in (``models.transformer.attn_sublayer``): None/"oracle" = the
    quadratic hand-VJP ``mha``; "flash" = the fused Pallas kernels
    (interpret mode automatically off-TPU), custom-VJP'd end to end,
    GQA shapes via repeat-KV fan-out; "rope" = rotary positions applied
    to q/k before the hand-VJP kernel (GQA shapes compose)."""
    if attn_impl in (None, "oracle"):
        return None
    if attn_impl == "flash":
        from ..ops.pallas_attention import flash_mha
        interpret = jax.default_backend() != "tpu"
        fn = lambda q, k, v, causal: flash_mha(q, k, v, causal, interpret)
        fn.supports_gqa = flash_mha.supports_gqa  # single declaration
        return fn
    if attn_impl == "rope":
        from ..models.attention import rope_mha
        return rope_mha
    raise ValueError(f"unknown attn_impl {attn_impl!r} "
                     "(expected 'oracle', 'flash', or 'rope')")


def _make_single_step(tokens: int, model_size: int, seq_len: int,
                      n_heads: int, lr: float, causal: bool = True,
                      attn=None, mixed: bool = False):
    def step(params: TransformerParams, seed) -> TransformerParams:
        # named-scope regions (tf/fwd, tf/bwd, tf/optim) — the naming
        # map lives in utils/trace_analysis.SCOPES
        with jax.named_scope("tf"):
            x, dloss_dx = _reshape_batch(seed, tokens, seq_len,
                                         model_size, params.w1.dtype)
            if mixed:
                # the LM family's bf16 stance (models.lm.lm_loss(mixed=)),
                # head-less: bf16 params + activations through the blocks,
                # f32 master params/grads/update — the cotangent enters in
                # bf16 (the fwd output's dtype) and the grads come back f32
                # through the cast transposes
                xm = x.astype(jnp.bfloat16)

                def fwd(p):
                    pc = jax.tree_util.tree_map(
                        lambda a: a.astype(jnp.bfloat16), p)
                    return transformer_fwd(pc, xm, n_heads, causal, attn)

                with jax.named_scope("fwd"):
                    _, vjp = jax.vjp(fwd, params)
                with jax.named_scope("bwd"):
                    grads = vjp(dloss_dx.astype(jnp.bfloat16))[0]
            else:
                with jax.named_scope("fwd"):
                    _, vjp = jax.vjp(
                        lambda p: transformer_fwd(p, x, n_heads, causal,
                                                  attn), params)
                with jax.named_scope("bwd"):
                    grads = vjp(dloss_dx)[0]
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return step


@partial(jax.jit, static_argnums=tuple(range(2, 10)), donate_argnums=0)
def _run_single(params, seeds, batch_size, model_size, lr, seq_len,
                n_heads, causal, attn_impl, mixed=False):
    """Module-level jit (the ``single.py`` pattern): repeat calls with the
    same static config reuse the compiled program instead of re-tracing —
    load-bearing for the bench's best-of-N timing loops."""
    step = _make_single_step(batch_size, model_size, seq_len, n_heads, lr,
                             causal, resolve_attn(attn_impl), mixed)
    return lax.scan(lambda p, s: (step(p, s), None), params, seeds)[0]


def train_transformer_single(params: TransformerParams, seeds,
                             batch_size: int, model_size: int, mesh=None,
                             lr: float = LR, *, seq_len: int, n_heads: int,
                             causal: bool = True,
                             attn_impl: str | None = None,
                             mixed: bool = False
                             ) -> TransformerParams:
    """Single-device trainer; ``batch_size`` is tokens/step (seq folded,
    CLI convention ``train_ffns.py:379``), unfolded to
    ``[batch_size/seq_len, seq_len, d]`` for attention. ``mixed`` runs
    the blocks in bf16 with f32 master params/grads/update."""
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    return _run_single(clone_params(params), jnp.asarray(seeds),
                       batch_size, model_size, lr, seq_len, n_heads,
                       causal, attn_impl, mixed)


def train_transformer_ddp(params: TransformerParams, seeds, batch_size: int,
                          model_size: int, mesh, lr: float = LR, *,
                          seq_len: int, n_heads: int, causal: bool = True,
                          attn_impl: str | None = None) -> TransformerParams:
    """DDP: each shard trains its seed column on the full replicated model;
    grads psum per step."""
    require_axes(mesh, DATA_AXIS)
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    attn = resolve_attn(attn_impl)

    def step(params: TransformerParams, seed) -> TransformerParams:
        with jax.named_scope("tf"):
            x, dloss_dx = _reshape_batch(seed, batch_size, seq_len,
                                         model_size, params.w1.dtype)
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(
                    lambda p: transformer_fwd(p, x, n_heads, causal,
                                              attn), params)
            with jax.named_scope("bwd"):
                grads = vjp(dloss_dx)[0]
            with jax.named_scope("comm"):
                grads = jax.tree_util.tree_map(
                    lambda g: grad_reduce(g, DATA_AXIS,
                                          force=vma_erased()), grads)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return launch_strided(step, clone_params(params), seeds, mesh,
                          DATA_AXIS, P())


def train_transformer_fsdp(params: TransformerParams, seeds,
                           batch_size: int, model_size: int, mesh,
                           lr: float = LR, *, seq_len: int, n_heads: int,
                           causal: bool = True,
                           attn_impl: str | None = None
                           ) -> TransformerParams:
    """FSDP/ZeRO-3 on the transformer: every param stack sharded over the
    data axis, each layer ``all_gather``-ed transiently per step (the
    unrolled loop lets XLA prefetch layer l+1's gathers during layer l's
    compute, ``train_ffns.py:200-249``). The backward needs no explicit
    collective at all: the AD transpose of the forward's ``all_gather`` IS
    ``psum_scatter``, so grads come back simultaneously summed across the
    data shards and scattered onto the local chunks (the gather/
    reduce-scatter correspondence the reference built by hand at
    ``:245-256``). Sharded SGD on the local chunk only.
    """
    require_axes(mesh, DATA_AXIS)
    n = mesh.shape[DATA_AXIS]
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    for name, leaf in zip(TransformerParams._fields, params):
        if leaf.shape[1] % n:
            raise ValueError(f"{name} dim {leaf.shape[1]} not divisible by "
                             f"{n} shards")
    attn = resolve_attn(attn_impl)

    def step(params: TransformerParams, seed) -> TransformerParams:
        x, dloss_dx = _reshape_batch(seed, batch_size, seq_len, model_size,
                                     params.w1.dtype)

        def fwd(p):
            y = x
            for l in range(p.w1.shape[0]):
                # gather this layer's full params (transient, never stored)
                # and run the exact single-device block on them
                with jax.named_scope("comm"):
                    full = [all_gather(leaf[l], DATA_AXIS, dim=0)
                            for leaf in p]
                y = transformer_block(*full, y, n_heads, causal, attn)
            return y

        with jax.named_scope("tf"):
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(fwd, params)
            with jax.named_scope("bwd"):
                # psum_scatter'd by the gather transpose
                grads = vjp(dloss_dx)[0]
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return launch_strided(step, _shard(params, mesh, FSDP_SPECS), seeds,
                          mesh, DATA_AXIS, FSDP_SPECS)


def tp_block(ln1, wq, wk, wv, wo, ln2, w1, w2, x, n_heads_local: int,
             axis: str = MODEL_AXIS, causal: bool = True, attn=None):
    """One TP transformer block, per-shard view (local weights)."""
    f = _f_gate(axis)

    def g(t):  # Megatron g: the forward psum, named for trace analysis
        with jax.named_scope("comm"):
            return all_reduce(t, axis)

    b, s, d = x.shape
    a = f(layernorm(ln1, x))
    x = x + g(attn_sublayer(wq, wk, wv, wo, a, n_heads_local, causal,
                            attn))
    h = f(layernorm(ln2, x)).reshape(b * s, d)
    y = g(ffn_block(w1, w2, h))
    return x + y.reshape(b, s, d)


def sp_block(ln1, wq, wk, wv, wo, ln2, w1, w2, x_s, n_heads_local: int,
             axis: str = MODEL_AXIS, causal: bool = True, attn=None):
    """One sequence-parallel TP transformer block (Korthikanti et al.),
    per-shard view: ``x_s [b, s/n, d]`` — the residual stream, LayerNorms,
    and both residual adds live on this rank's **token shard**; only the
    sublayer cores see full tokens, via ``all_gather`` (sequence in) +
    ``reduce_scatter`` (sequence out) — the ring-equal decomposition of
    ``tp_block``'s two ``psum``s, with every stream activation 1/n the
    size. The gathers/scatters differentiate by their exact transposes
    (gather <-> scatter+sum), composed by ``jax.vjp`` around the
    hand-written sublayer rules; the ``_f_gate`` is subsumed — the
    backward's ``reduce_scatter`` already sums the column-parallel
    projections' partial input-grads."""
    def g(t):
        with jax.named_scope("comm"):
            return all_gather(t, axis, dim=1)

    def rs(t):
        with jax.named_scope("comm"):
            return reduce_scatter(t, axis, dim=1)

    b, s_local, d = x_s.shape
    a = g(layernorm(ln1, x_s))                          # [b, s, d] full
    x_s = x_s + rs(
        attn_sublayer(wq, wk, wv, wo, a, n_heads_local, causal, attn))
    h = g(layernorm(ln2, x_s))
    full_tokens = b * s_local * lax.axis_size(axis)
    y = rs(ffn_block(w1, w2, h.reshape(full_tokens, d)).reshape(b, -1, d))
    return x_s + y


def _validate_tp(params, n_heads: int, n: int) -> int:
    if n_heads % n:
        raise ValueError(f"n_heads={n_heads} not divisible by model-axis "
                         f"size {n}")
    dh = params.wq.shape[1] // n_heads
    kv_heads = params.wk.shape[1] // dh
    if kv_heads % n:
        raise ValueError(f"n_kv_heads={kv_heads} (GQA) not divisible by "
                         f"model-axis size {n}")
    ffn_dim = params.w1.shape[1]
    if ffn_dim % n:
        raise ValueError(f"ffn_dim={ffn_dim} not divisible by model-axis "
                         f"size {n}")
    return n_heads // n


def train_transformer_tp(params: TransformerParams, seeds, batch_size: int,
                         model_size: int, mesh, lr: float = LR, *,
                         seq_len: int, n_heads: int, causal: bool = True,
                         attn_impl: str | None = None,
                         sequence_parallel: bool = False
                         ) -> TransformerParams:
    """Megatron TP over the ``"model"`` axis: data replicated, heads and
    FFN features sharded, two psums per block per direction
    (``train_ffns.py:303, :309`` cadence on the transformer block).

    ``sequence_parallel=True`` selects the Korthikanti et al. form
    (``sp_block``): the residual stream, LayerNorms, and dropout-free
    elementwise work live token-sharded (``[b, s/n, d]``), each psum
    decomposed into ``all_gather`` + ``reduce_scatter``. Same math
    (differential-tested against this trainer's plain form and the
    single-device oracle), 1/n the stream activations. LN gains then see
    only the shard's tokens, so their grads pick up one ``psum`` over the
    model axis; projection/FFN grads stay shard-complete."""
    require_axes(mesh, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    h_local = _validate_tp(params, n_heads, n)
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    step = make_tp_step(batch_size, model_size, seq_len, h_local, n, lr,
                        causal, resolve_attn(attn_impl), sequence_parallel)
    return launch(step, _shard(params, mesh, TP_SPECS), jnp.asarray(seeds),
                  mesh, param_specs=TP_SPECS, seed_spec=P())


def make_tp_step(batch_size: int, model_size: int, seq_len: int,
                 h_local: int, n_shards: int, lr: float = LR,
                 causal: bool = True, attn=None,
                 sequence_parallel: bool = False):
    """One TP step for one shard — the shared builder behind
    ``train_transformer_tp`` (tests shard_map this directly to pin the
    comms schedule against the real implementation)."""
    if sequence_parallel and seq_len % n_shards:
        raise ValueError(f"seq_len={seq_len} not divisible by model-axis "
                         f"size {n_shards} (sequence-parallel TP shards "
                         "tokens)")
    t_local = seq_len // n_shards if sequence_parallel else seq_len
    block = sp_block if sequence_parallel else tp_block

    def step(params: TransformerParams, seed) -> TransformerParams:
        x, dloss_dx = _reshape_batch(seed, batch_size, seq_len, model_size,
                                     params.w1.dtype)
        if sequence_parallel:
            r = axis_index(MODEL_AXIS)
            x, dloss_dx = (
                lax.dynamic_slice_in_dim(t, r * t_local, t_local, 1)
                for t in (x, dloss_dx))

        def fwd(p):
            y = x
            for l in range(p.w1.shape[0]):
                y = block(p.ln1[l], p.wq[l], p.wk[l], p.wv[l], p.wo[l],
                          p.ln2[l], p.w1[l], p.w2[l], y, h_local,
                          causal=causal, attn=attn)
            return y

        with jax.named_scope("tf"):
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(fwd, params)
            with jax.named_scope("bwd"):
                grads = vjp(dloss_dx)[0]
            if sequence_parallel:
                with jax.named_scope("comm"):
                    # LN gains saw only this shard's tokens: sum over the
                    # model axis. Everything else saw full (gathered)
                    # tokens and is complete per shard.
                    grads = grads._replace(
                        ln1=grad_reduce(grads.ln1, MODEL_AXIS,
                                        force=vma_erased()),
                        ln2=grad_reduce(grads.ln2, MODEL_AXIS,
                                        force=vma_erased()))
            # projection/FFN grads are shard-local (each shard owns its
            # heads/features); in the plain form LN grads replicate —
            # data and dx are identical on all shards after the f-gate
            # psums
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return step


def train_transformer_seq(params: TransformerParams, seeds,
                          batch_size: int, model_size: int, mesh,
                          lr: float = LR, *, seq_len: int, n_heads: int,
                          causal: bool = True,
                          seq_impl: str = "ring") -> TransformerParams:
    """Long-context training: the sequence dim sharded over the ``"seq"``
    axis — the first-class path that makes ring attention / Ulysses a
    *training* capability rather than an op-level demo.

    Everything token-pointwise (LN, projections, FFN, residuals) runs on
    the shard's own ``T/n`` tokens untouched; only attention crosses
    shards, via the hand-written ring (KV blocks rotating over
    ``ppermute``, ``sequence.ring_attention``) or Ulysses (two
    ``all_to_all``s trading heads for sequence). No device ever holds the
    full ``[T, T]`` score matrix — or, for the ring, even the full
    sequence of activations.

    Within a data replica, data is replicated like TP (every seq shard
    generates the step's full batch from the seed and slices its own
    token block — global causal positions stay exact); weight grads are
    per-shard partials over the token dim, summed with one ``psum`` per
    step (SUM, unscaled LR, ``train_ffns.py:165`` semantics).

    A 2-D ``(data, seq)`` mesh composes long context with data
    parallelism: the seed schedule shards strided over ``data`` (each
    data replica trains its own steps, DDP-style) while each replica's
    sequence shards over ``seq`` — the grad psum then rides both axes.
    Differential guarantees (tests/test_transformer.py):
    seq-only == ``train_transformer_single``; data x seq ==
    ``train_transformer_ddp`` over the data axis alone.
    """
    from .sequence import resolve_seq_attn
    require_axes(mesh, SEQ_AXIS)
    n = mesh.shape[SEQ_AXIS]
    dp = dict(mesh.shape).get(DATA_AXIS, 1)
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    attn = resolve_seq_attn(seq_impl, n, n_heads, seq_len)
    t_local = seq_len // n

    def step(params: TransformerParams, seed) -> TransformerParams:
        x, dloss_dx = _reshape_batch(seed, batch_size, seq_len, model_size,
                                     params.w1.dtype)
        r = axis_index(SEQ_AXIS)
        # this shard's token block (global batch regenerated from the
        # seed, so positions/causality are exact without a scatter)
        x, dloss_dx = (lax.dynamic_slice_in_dim(t, r * t_local, t_local, 1)
                       for t in (x, dloss_dx))

        with jax.named_scope("seq"):
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(
                    lambda p: transformer_fwd(p, x, n_heads, causal,
                                              attn), params)
            with jax.named_scope("bwd"):
                grads = vjp(dloss_dx)[0]
            with jax.named_scope("comm"):
                # weight grads are partial sums over this shard's tokens
                # — and, on a 2-D mesh, over the data replicas (DDP
                # semantics). One fused psum over both axes per leaf,
                # not one per axis.
                axes = (SEQ_AXIS, DATA_AXIS) if dp > 1 else (SEQ_AXIS,)
                grads = jax.tree_util.tree_map(
                    lambda g: grad_reduce(g, axes, force=vma_erased()),
                    grads)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    if dp > 1:
        return launch_strided(step, clone_params(params), seeds, mesh,
                              DATA_AXIS, P())
    return launch(step, clone_params(params), jnp.asarray(seeds), mesh,
                  param_specs=P(), seed_spec=P())


def train_transformer_hybrid(params: TransformerParams, seeds,
                             batch_size: int, model_size: int, mesh,
                             lr: float = LR, *, seq_len: int, n_heads: int,
                             causal: bool = True,
                             attn_impl: str | None = None
                             ) -> TransformerParams:
    """Hybrid DDP x TP on a 2-D ``(data, model)`` mesh — the BASELINE
    config-4 composition on the transformer: TP's two per-block psums ride
    the ``"model"`` axis inside each block, DDP's weight-grad psum rides
    the orthogonal ``"data"`` axis once per step (``hybrid.py`` semantics
    on the transformer surface). Seeds shard strided over ``data``
    (``train_ffns.py:182``); params shard over ``model`` only."""
    require_axes(mesh, DATA_AXIS, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    h_local = _validate_tp(params, n_heads, n)
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    attn = resolve_attn(attn_impl)

    def step(params: TransformerParams, seed) -> TransformerParams:
        x, dloss_dx = _reshape_batch(seed, batch_size, seq_len, model_size,
                                     params.w1.dtype)

        def fwd(p):
            y = x
            for l in range(p.w1.shape[0]):
                y = tp_block(p.ln1[l], p.wq[l], p.wk[l], p.wv[l], p.wo[l],
                             p.ln2[l], p.w1[l], p.w2[l], y, h_local,
                             causal=causal, attn=attn)
            return y

        with jax.named_scope("tf"):
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(fwd, params)
            with jax.named_scope("bwd"):
                grads = vjp(dloss_dx)[0]
            with jax.named_scope("comm"):
                # TP leaves weight grads complete within a model shard;
                # the data axis still needs the DDP reduction (orthogonal
                # psums, the 2-D mesh composition)
                grads = jax.tree_util.tree_map(
                    lambda g: grad_reduce(g, DATA_AXIS,
                                          force=vma_erased()), grads)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    # params: sharded over model, replicated over data; seeds: one strided
    # column per data shard, same column for every model shard
    return launch_strided(step, _shard(params, mesh, TP_SPECS), seeds,
                          mesh, DATA_AXIS, TP_SPECS)
