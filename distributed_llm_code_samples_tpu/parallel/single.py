"""Single-device trainer — reference semantics for the ops layer.

Parity target: ``train_1gpu`` (``train_ffns.py:101-116``): per step, forward
the stack, hand-written backward, functional SGD rebuild ``p - LR*g``. The
step loop is a ``lax.scan`` over the seed schedule so the whole run is one
XLA program (steps/sec is measured without per-step dispatch overhead).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, clone_params
from ..optim import sgd
from ..ops.ffn import ffn_fwd, ffn_bwd
from ..ops.stack import (accumulated_grads, stack_fwd, stack_bwd,
                         stack_grads)


def make_step(batch_size: int, model_size: int, lr: float = LR,
              unroll: bool = True, use_pallas: bool = False,
              interpret: bool = False, manual_loop: bool = False,
              remat: bool | None = None, mixed: bool = False,
              accum: int = 1):
    """Build one training step ``(params, seed) -> params`` — forward,
    manual backward, inline SGD (``train_ffns.py:105-114``).

    By default the chain is composed functionally (``ops.stack.stack_grads``):
    each block still runs the hand-written VJP rule via ``custom_vjp``, but
    residual plumbing is left to XLA — ~10% faster on v5e than restacking
    activations by hand. ``manual_loop=True`` selects the literal
    reference-shaped loops (``stack_fwd``/``stack_bwd``); both paths run the
    same per-block math and agree to float tolerance (allclose-verified in
    tests/test_ops.py — XLA may schedule the two programs differently, so
    equality is not bitwise).

    ``use_pallas`` swaps the per-block compute for the fused Pallas TPU
    kernels (``ops.pallas_ffn``); ``interpret`` runs them in interpreter
    mode for CPU testing.

    ``remat=False`` saves the post-ReLU activation instead of recomputing
    the ffn1 pre-activation in the backward (``ops.ffn.ffn_block_saved``)
    — one fewer matmul per block backward, same hand-written math, same
    gradients. Measured on the v5e-class bench chip at the BASELINE
    config-5 shape the two are throughput-equal (the step is
    matmul-issue-bound either way), so the default keeps the reference's
    memory-lean recompute policy (``train_ffns.py:63``).

    ``mixed`` selects the TPU-first precision policy: bf16 matmul
    inputs on the MXU, fp32 params/gradients/accumulation, bf16
    residuals. Composes with the residual policy (same default as f32 —
    the reference's recompute stance): ``remat=True``/None recomputes
    the pre-activation from a bf16-stashed block input
    (``ops.ffn.ffn_block_mixed_remat``); ``remat=False`` saves the bf16
    post-ReLU (``ops.ffn.ffn_block_mixed``). The MXU time is identical to
    f32 either way (default-precision f32 matmuls are single bf16
    passes); the halved stash bytes are the single-chip lever, and
    bench.py measures which residual policy wins.

    ``accum`` splits the step's tokens into that many gradient-
    accumulation chunks (``lax.scan``, summed grads, one update): peak
    activation memory drops ~1/accum while the math is exactly the
    full-batch step (grads are linear in the batch; the mock loss has no
    mean to rescale — SUM semantics throughout, ``train_ffns.py:165``)."""
    if mixed and (use_pallas or manual_loop):
        raise ValueError("mixed=True is its own block implementation; it "
                         "cannot combine with use_pallas/manual_loop")
    if use_pallas and remat is False:
        raise ValueError("the Pallas block has its own residual policy; "
                         "remat=False cannot combine with use_pallas")
    if remat is None:
        remat = True  # the reference's recompute policy is the default

    def accumulate(grad_fn, x, dy):
        return accumulated_grads(grad_fn, x, dy, accum)

    if manual_loop:
        if use_pallas:
            from ..ops.pallas_ffn import ffn_fwd_pallas, ffn_bwd_pallas
            block_fwd = lambda w1, w2, x: ffn_fwd_pallas(  # noqa: E731
                w1, w2, x, interpret=interpret)
            block_bwd = lambda dy, w1, w2, x: ffn_bwd_pallas(  # noqa: E731
                dy, w1, w2, x, interpret=interpret)
        else:
            block_fwd, block_bwd = ffn_fwd, ffn_bwd

        def step(params: FFNStackParams, seed) -> FFNStackParams:
            # named-scope regions (single/fwd, single/bwd, single/optim):
            # stable trace/HLO names, utils/trace_analysis.SCOPES
            with jax.named_scope("single"):
                x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                              params.w1.dtype)

                def grad_fn(x, dy):
                    _, acts = stack_fwd(params.w1, params.w2, x,
                                        block_fwd=block_fwd, unroll=unroll)
                    _, (g1, g2) = stack_bwd(dy, params.w1, params.w2, acts,
                                            block_bwd=block_bwd,
                                            unroll=unroll)
                    return FFNStackParams(g1, g2)

                grads = accumulate(grad_fn, x, dloss_dx)
                with jax.named_scope("optim"):
                    return sgd(params, grads, lr)

        return step

    if use_pallas:
        from ..ops.pallas_ffn import pallas_ffn_block
        block = lambda w1, w2, x: pallas_ffn_block(  # noqa: E731
            w1, w2, x, interpret)
    elif mixed:
        if remat:
            from ..ops.ffn import ffn_block_mixed_remat as block
        else:
            from ..ops.ffn import ffn_block_mixed as block
    elif remat:
        from ..ops.ffn import ffn_block as block
    else:
        from ..ops.ffn import ffn_block_saved as block

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        with jax.named_scope("single"):
            x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                          params.w1.dtype)

            def grad_fn(x, dy):
                return FFNStackParams(*stack_grads(
                    params.w1, params.w2, x, dy, block=block,
                    unroll=unroll)[1])

            grads = accumulate(grad_fn, x, dloss_dx)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return step


@partial(jax.jit, static_argnums=tuple(range(2, 12)), donate_argnums=0)
def _run(params, seeds, batch_size, model_size, lr, unroll, use_pallas,
         interpret, manual_loop, remat, mixed, accum):
    step = make_step(batch_size, model_size, lr, unroll, use_pallas,
                     interpret, manual_loop, remat, mixed, accum)
    return lax.scan(lambda p, s: (step(p, s), None), params, seeds)[0]


@partial(jax.jit, static_argnums=tuple(range(3, 14)), donate_argnums=0)
def _run_guarded(params, gstate, seeds, batch_size, model_size, lr,
                 unroll, use_pallas, interpret, manual_loop, remat, mixed,
                 accum, guard):
    """The guarded scan: every step's candidate params pass the in-graph
    finite check and a bad step is ``jnp.where``-skipped — params
    untouched, skip counter advanced (``runtime/guardrails.py``).
    ``guard`` is a frozen (hashable) config, so it rides the static-args
    cache like the rest of the step configuration."""
    from ..runtime.guardrails import guarded_scan_step
    step = make_step(batch_size, model_size, lr, unroll, use_pallas,
                     interpret, manual_loop, remat, mixed, accum)
    gstep = guarded_scan_step(step, guard)
    return lax.scan(lambda c, s: (gstep(c, s), None), (params, gstate),
                    seeds)[0]


def train_single(params: FFNStackParams, seeds, batch_size: int,
                 model_size: int, mesh=None, lr: float = LR,
                 unroll: bool = True, use_pallas: bool = False,
                 interpret: bool = False, manual_loop: bool = False,
                 remat: bool | None = None, mixed: bool = False,
                 accum: int = 1, guard=None, guard_state=None,
                 return_guard: bool = False) -> FFNStackParams:
    """Uniform launcher signature (SURVEY.md L4); ``mesh`` ignored.

    ``guard`` (a ``runtime.guardrails.GuardrailConfig``) compiles the
    in-graph skip-step guardrail into the scan; with ``return_guard``
    the final ``GuardState`` (skip counters) returns alongside the
    params. The single-device path carries no collectives, so the
    finite flag needs no reduction; loss scaling is a mixed-strategy
    (DDP/FSDP) surface."""
    from ..runtime.guardrails import check_guard_args, host_state
    check_guard_args(guard, guard_state, return_guard)
    if guard is not None and guard.scaling:
        raise ValueError(
            "guard.loss_scale > 0 but train_single has no loss-scale "
            "hook: dynamic scaling is a mixed-precision DDP/FSDP "
            "surface — pass loss_scale=0 here")
    if guard is None:
        return _run(clone_params(params), jnp.asarray(seeds), batch_size,
                    model_size, lr, unroll, use_pallas, interpret,
                    manual_loop, remat, mixed, accum)
    out, g = _run_guarded(clone_params(params), host_state(guard_state,
                                                           guard),
                          jnp.asarray(seeds), batch_size, model_size, lr,
                          unroll, use_pallas, interpret, manual_loop,
                          remat, mixed, accum, guard)
    return (out, g) if return_guard else out
