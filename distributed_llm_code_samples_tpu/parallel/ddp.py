"""DDP: replicated params, sharded data, per-layer gradient all-reduce.

Parity target: ``train_ddp`` / ``train_process_ddp``
(``train_ffns.py:154-193``). The reference clones params onto every GPU,
splits the seed schedule stride-wise across ranks, and — the load-bearing
detail — fires an **async all_reduce(SUM) per layer the moment that layer's
grads exist** (``ddp_comms_hook``, ``:164-165``), waiting only when the
optimizer needs the result, so gradient communication overlaps the rest of
the backward.

TPU translation: ``jax.shard_map`` over a 1-D ``("data",)`` mesh. Params
enter replicated (``P()``), each shard consumes its own seed column, and the
``grad_hook`` injects ``psum`` per layer inside the backward walk — XLA emits
``all-reduce-start/done`` pairs and its latency-hiding scheduler overlaps
them with the remaining backward compute, which is exactly the role of the
reference's handle bookkeeping (``:168-172``). Gradient reduction is SUM
with unscaled LR (``:165``, ``optim.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, clone_params
from ..optim import Optimizer, check_state_args, sgd
from ..ops.ffn import ffn_bwd_mixed, ffn_fwd_mixed
from ..ops.stack import accumulated_grads, stack_fwd, stack_bwd
from .collectives import all_reduce
from .launcher import launch, launch_strided
from .mesh import DATA_AXIS, require_axes


def grads_for_batch(params: FFNStackParams, x, dy, unroll: bool = True,
                    grad_hook=None, mixed: bool = False) -> FFNStackParams:
    """One fwd/bwd over given data — the compute shared by DDP, ZeRO-1,
    and the gradient-accumulation chunks. ``mixed`` swaps the per-block
    math for the bf16-MXU/f32-accumulate rule (``ops.ffn.ffn_*_mixed``);
    grads come out f32 either way, so the reduction semantics (SUM,
    unscaled LR) are unchanged."""
    kw = ({"block_fwd": ffn_fwd_mixed} if mixed else {})
    bkw = ({"block_bwd": ffn_bwd_mixed} if mixed else {})
    _, acts = stack_fwd(params.w1, params.w2, x, unroll=unroll, **kw)
    _, (g1, g2) = stack_bwd(dy, params.w1, params.w2, acts,
                            grad_hook=grad_hook, unroll=unroll, **bkw)
    return FFNStackParams(g1, g2)


def local_grads(params: FFNStackParams, seed, batch_size: int,
                model_size: int, unroll: bool = True, grad_hook=None,
                accum: int = 1, mixed: bool = False, dy_scale=None):
    """One shard's step grads from its seed (see ``grads_for_batch``).

    ``accum > 1`` sums over token chunks (``ops.stack.accumulated_grads``)
    — UNREDUCED: the hook does not apply on this path, so the caller
    reduces the summed grads once (DDP all_reduce / ZeRO-1 reduce_scatter).

    ``dy_scale`` multiplies the upstream gradient before the backward —
    the dynamic-loss-scaling hook (``runtime/guardrails.py``): under
    ``mixed`` the scaled ``dy`` rides the bf16 blocks, and the caller
    unscales the f32 grads after its reduction.
    """
    x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                  params.w1.dtype)
    if dy_scale is not None:
        dloss_dx = (dloss_dx * dy_scale.astype(dloss_dx.dtype))
    if accum == 1:
        return grads_for_batch(params, x, dloss_dx, unroll, grad_hook,
                               mixed)
    return accumulated_grads(
        lambda x, dy: grads_for_batch(params, x, dy, unroll, mixed=mixed),
        x, dloss_dx, accum)


def make_step(batch_size: int, model_size: int, lr: float = LR,
              unroll: bool = True, axis: str = DATA_AXIS,
              optimizer: Optimizer | None = None, accum: int = 1,
              mixed: bool = False, comm: str = "psum",
              ring_interpret: bool | None = None, guard=None,
              seed_accum: int = 1):
    """One DDP step for one shard: local fwd/bwd with per-layer grad psum.

    Without ``optimizer`` the step is the reference's stateless inline SGD
    (``(params, seed) -> params``). With one, the step maps
    ``((params, opt_state), seed) -> (params, opt_state)`` — the optimizer
    state is replicated like the params (the baseline ZeRO-1 improves on,
    ``parallel/zero1.py``).

    ``accum > 1`` gradient-accumulates over token chunks
    (``ops.stack.accumulated_grads``): local grads sum across chunks
    unreduced, then ONE tree-wide psum replaces the per-layer-per-chunk
    hooks — same math, 1/accum the collectives and ~1/accum the
    activation memory.

    ``comm`` selects the gradient-reduction transport: ``"psum"`` (XLA
    collectives, async-split by the latency-hiding scheduler — the
    default) or ``"pallas_ring"`` (the hand-scheduled
    ``make_async_remote_copy`` ring of ``ops/pallas_ring.py`` — the
    explicit-control path, load-bearing in a real strategy; same sums,
    ring accumulation order).

    ``seed_accum > 1`` is the topology-elastic surface: the step takes
    a ``[seed_accum]`` seed VECTOR, sums the per-seed grads locally,
    and reduces once — preserving the save-time global batch when a
    checkpoint resumes onto fewer devices (``data.shard_seeds_elastic``).

    ``guard`` (a ``GuardrailConfig``) arms the in-graph hooks that live
    INSIDE the step math: dynamic loss scaling under ``mixed`` (the
    step then takes ``(carry, seed, loss_scale)`` — the launcher's
    ``guard_scale`` contract) and global-norm clipping
    (``guard.clip_norm``) on the stateless-SGD path. The skip-select
    and counters live in the launcher wrap (``guardrails.py``)."""
    from ..runtime.guardrails import finalize_grads, require_mixed_for_scaling
    require_mixed_for_scaling(guard, mixed)
    if comm not in ("psum", "pallas_ring"):
        raise ValueError(f"unknown comm {comm!r} "
                         "(expected 'psum' or 'pallas_ring')")
    if comm == "pallas_ring":
        from ..ops.pallas_ring import ring_all_reduce
        # interpret=None lets the kernel auto-detect (interpreter
        # off-TPU, Mosaic on chip); AOT codegen callers pass False
        reduce = lambda g: ring_all_reduce(  # noqa: E731
            g, axis, interpret=ring_interpret)
    else:
        reduce = lambda g: all_reduce(g, axis)  # noqa: E731

    def grad_hook(dw1, dw2):  # fires per layer, like train_ffns.py:164-165
        with jax.named_scope("comm"):  # -> ddp/bwd/comm in traces/HLO
            return reduce(dw1), reduce(dw2)

    def grads_of(params, seed, scale=None):
        if seed_accum > 1:
            # elastic resume: `seed` is a [seed_accum] vector — sum the
            # per-seed grads locally (the grads of the lost ranks), then
            # reduce ONCE, like the token-accum path
            total = local_grads(params, seed[0], batch_size, model_size,
                                unroll, accum=accum, mixed=mixed,
                                dy_scale=scale)
            for j in range(1, seed_accum):
                total = jax.tree_util.tree_map(
                    jnp.add, total,
                    local_grads(params, seed[j], batch_size, model_size,
                                unroll, accum=accum, mixed=mixed,
                                dy_scale=scale))
            with jax.named_scope("comm"):
                grads = jax.tree_util.tree_map(reduce, total)
        elif accum == 1:
            grads = local_grads(params, seed, batch_size, model_size,
                                unroll, grad_hook, mixed=mixed,
                                dy_scale=scale)
        else:
            total = local_grads(params, seed, batch_size, model_size,
                                unroll, accum=accum, mixed=mixed,
                                dy_scale=scale)
            with jax.named_scope("comm"):  # one tree-wide reduction
                grads = jax.tree_util.tree_map(reduce, total)
        return finalize_grads(grads, scale, guard)

    def step(params: FFNStackParams, seed, scale=None) -> FFNStackParams:
        # named-scope regions (ddp/fwd, ddp/bwd, ddp/bwd/comm, ddp/optim)
        # — the naming map lives in utils/trace_analysis.SCOPES
        with jax.named_scope("ddp"):
            grads = grads_of(params, seed, scale)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    def step_opt(carry, seed, scale=None):
        params, state = carry
        with jax.named_scope("ddp"):
            grads = grads_of(params, seed, scale)
            with jax.named_scope("optim"):
                return optimizer.update(grads, state, params, lr)

    return step if optimizer is None else step_opt


def train_ddp(params: FFNStackParams, seeds, batch_size: int,
              model_size: int, mesh, lr: float = LR, unroll: bool = True,
              optimizer: Optimizer | None = None, accum: int = 1,
              opt_state=None, return_state: bool = False,
              mixed: bool = False, comm: str = "psum",
              guard=None, guard_state=None, return_guard: bool = False,
              seed_accum: int = 1):
    """Run the full DDP schedule; returns the (replicated) final params.

    ``seeds`` is the *global* schedule; the strided split across ranks
    reproduces ``train_ffns.py:182`` so differential tests against FSDP
    keep their power. ``optimizer`` selects a stateful update rule
    (``optim.momentum``/``optim.adam``) with replicated state; None keeps
    the reference's inline SGD. ``accum`` gradient-accumulates each step
    over token chunks (see ``make_step``).

    ``opt_state``/``return_state`` pass the optimizer state through the
    program boundary: a resumed segment continues Adam's statistics
    exactly where a previous segment's returned state left them (the
    checkpoint subsystem's stateful-resume path).

    ``mixed`` runs every block in the bf16-MXU/f32-accumulate policy
    (``ops.ffn.ffn_fwd_mixed``/``ffn_bwd_mixed``); params, grads, and the
    psum stay f32, so DDP(mixed) == FSDP(mixed) differentials keep their
    power.

    ``comm="pallas_ring"`` swaps every gradient reduction for the
    hand-scheduled ICI ring kernel (see ``make_step``) — same sums in
    ring order, pinned against the psum path.

    ``guard``/``guard_state``/``return_guard`` arm the in-graph anomaly
    guardrail (``runtime/guardrails.py``): a non-finite update is
    skipped inside the compiled scan (params and optimizer state
    untouched) and the skip/overflow counters (+ the live loss scale,
    dynamic under ``mixed``) return alongside the result when
    ``return_guard``. ``seed_accum`` is the topology-elastic surface
    (see ``make_step``).
    """
    require_axes(mesh, DATA_AXIS)
    from ..runtime.guardrails import check_guard_args
    check_guard_args(guard, guard_state, return_guard)
    step = make_step(batch_size, model_size, lr, unroll,
                     optimizer=optimizer, accum=accum, mixed=mixed,
                     comm=comm, guard=guard, seed_accum=seed_accum)

    # the ring kernel's outputs are typed shard-varying (value-replicated
    # by construction, like zero1's re-assembled params) — vma checking
    # cannot prove the replicated out_specs
    check = comm == "psum"
    check_state_args(optimizer, opt_state, return_state)
    gkw = {}
    if guard is not None:
        gkw = dict(guard=guard, guard_state=guard_state,
                   guard_scale=guard.scaling)
    if optimizer is None:
        out = launch_strided(step, clone_params(params), seeds, mesh,
                             DATA_AXIS, P(), accum=seed_accum,
                             check_vma=check, **gkw)
    else:
        state = optimizer.init(params) if opt_state is None else opt_state
        out = launch_strided(step, clone_params(params), seeds, mesh,
                             DATA_AXIS, P(), accum=seed_accum,
                             state=state, state_specs=P(),
                             return_state=return_state, check_vma=check,
                             **gkw)
    if guard is not None and not return_guard:
        out = out[0]
    return out
