"""DDP: replicated params, sharded data, per-layer gradient all-reduce.

Parity target: ``train_ddp`` / ``train_process_ddp``
(``train_ffns.py:154-193``). The reference clones params onto every GPU,
splits the seed schedule stride-wise across ranks, and — the load-bearing
detail — fires an **async all_reduce(SUM) per layer the moment that layer's
grads exist** (``ddp_comms_hook``, ``:164-165``), waiting only when the
optimizer needs the result, so gradient communication overlaps the rest of
the backward.

TPU translation: ``jax.shard_map`` over a 1-D ``("data",)`` mesh. Params
enter replicated (``P()``), each shard consumes its own seed column, and the
``grad_hook`` injects ``psum`` per layer inside the backward walk — XLA emits
``all-reduce-start/done`` pairs and its latency-hiding scheduler overlaps
them with the remaining backward compute, which is exactly the role of the
reference's handle bookkeeping (``:168-172``). Gradient reduction is SUM
with unscaled LR (``:165``, ``optim.py``).
"""

from __future__ import annotations

from functools import partial

from jax.sharding import PartitionSpec as P

from .. import LR
from ..data import batch_from_seed, shard_seeds_strided
from ..models.ffn_stack import FFNStackParams, clone_params
from ..optim import sgd
from ..ops.stack import stack_fwd, stack_bwd
from .collectives import all_reduce
from .launcher import launch
from .mesh import DATA_AXIS, require_axes


def make_step(batch_size: int, model_size: int, lr: float = LR,
              unroll: bool = True, axis: str = DATA_AXIS):
    """One DDP step for one shard: local fwd/bwd with per-layer grad psum."""

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                      params.w1.dtype)
        _, acts = stack_fwd(params.w1, params.w2, x, unroll=unroll)

        def grad_hook(dw1, dw2):  # fires per layer, like train_ffns.py:164-165
            return all_reduce(dw1, axis), all_reduce(dw2, axis)

        _, (g1, g2) = stack_bwd(dloss_dx, params.w1, params.w2, acts,
                                grad_hook=grad_hook, unroll=unroll)
        return sgd(params, FFNStackParams(g1, g2), lr)

    return step


def train_ddp(params: FFNStackParams, seeds, batch_size: int,
              model_size: int, mesh, lr: float = LR,
              unroll: bool = True) -> FFNStackParams:
    """Run the full DDP schedule; returns the (replicated) final params.

    ``seeds`` is the *global* schedule; the strided split across ranks
    reproduces ``train_ffns.py:182`` so differential tests against FSDP
    keep their power.
    """
    require_axes(mesh, DATA_AXIS)
    n = mesh.shape[DATA_AXIS]
    seed_cols = shard_seeds_strided(seeds, n)  # [steps/rank, n]
    step = make_step(batch_size, model_size, lr, unroll)

    return launch(step, clone_params(params), seed_cols, mesh,
                  param_specs=P(), seed_spec=P(None, DATA_AXIS),
                  select_local=lambda s: s[:, 0])
