"""MoE-LM trainers — GShard's expert-parallel layout under the real loss.

Same composition as ``parallel/moe_transformer.py`` (attention
data-parallel on strided seed columns, MoE FFN expert-parallel through
the ``all_to_all`` dispatch) with the objective upgraded from the mocked
upstream gradient to the LM family's hand-VJP cross-entropy plus the
router's load-balancing auxiliary loss: per shard
``loss = xent(local tokens) + aux_coef * aux``, gradients SUM-reduced
over the expert axis for every replicated leaf (embedding, positions,
attention, LNs, router — ``train_ffns.py:165`` semantics), expert FFN
weights complete on their owner shard.

``train_moe_lm_dense`` is the no-mesh oracle (``n_groups=n`` reproduces
the n-shard EP run exactly, grouped capacity and all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import LR
from ..data import lm_batch_from_seed, shard_seeds_strided
from ..models.ffn_stack import clone_params
from ..models.moe_lm import MoELMParams, moe_lm_loss_aux
from ..optim import sgd
from .collectives import grad_reduce
from .expert import _local_capacity, moe_layer_ep
from .launcher import launch_strided
from .mesh import EXPERT_AXIS, require_axes
from .moe_transformer import EP_SPECS, _REPLICATED, _validate

EP_LM_SPECS = MoELMParams(wte=P(), wpe=P(), blocks=EP_SPECS, ln_f=P())


def _validate_lm(params: MoELMParams, batch_size: int, seq_len: int,
                 n: int, model_size: int, n_heads: int) -> int:
    t_local = _validate(params.blocks, batch_size, seq_len, n,
                        model_size, n_heads)
    if seq_len > params.max_seq_len:
        raise ValueError(f"seq_len={seq_len} exceeds the model's "
                         f"max_seq_len={params.max_seq_len}")
    return t_local


def _reduce_replicated(grads: MoELMParams,
                       force: bool = False) -> MoELMParams:
    """psum the per-shard partials of every replicated leaf (vma-aware:
    leaves whose plain-op transposes already auto-reduced are skipped;
    ``force`` applies the vma-off unconditional-psum contract,
    ``collectives.grad_reduce``)."""
    grads = grads._replace(
        wte=grad_reduce(grads.wte, EXPERT_AXIS, force=force),
        wpe=grad_reduce(grads.wpe, EXPERT_AXIS, force=force),
        ln_f=grad_reduce(grads.ln_f, EXPERT_AXIS, force=force),
        blocks=grads.blocks._replace(**{
            f: grad_reduce(getattr(grads.blocks, f), EXPERT_AXIS,
                           force=force)
            for f in _REPLICATED}))
    return grads


def train_moe_lm_ep(params: MoELMParams, seeds, batch_size: int,
                    model_size: int, mesh, lr: float = LR, *,
                    seq_len: int, n_heads: int, causal: bool = True,
                    capacity_factor: float = 2.0, k: int = 1,
                    aux_coef: float = 0.0,
                    attn_impl: str | None = None,
                    dispatch: str = "dense",
                    head_impl: str | None = None) -> MoELMParams:
    """Run the GShard-LM schedule; ``batch_size`` is global tokens per
    step (each shard trains ``batch_size/n`` tokens of its own strided
    seed column). ``head_impl="fused"`` swaps the tied head + xent for
    the fused Pallas kernels per shard (``parallel.lm.resolve_head``;
    the launcher then runs the vma-off reduction contract on CPU)."""
    from .lm import _vma_check, resolve_head
    from .transformer import resolve_attn
    require_axes(mesh, EXPERT_AXIS)
    n = mesh.shape[EXPERT_AXIS]
    t_local = _validate_lm(params, batch_size, seq_len, n, model_size,
                           n_heads)
    b_local = t_local // seq_len
    vocab = params.vocab
    attn = resolve_attn(attn_impl)
    head = resolve_head(head_impl)
    check = _vma_check(attn_impl, head_impl)

    def moe_fn(wg, w1_local, w2_local, h):
        return moe_layer_ep(wg, w1_local, w2_local, h, capacity_factor,
                            EXPERT_AXIS, k, dispatch)

    def step(params: MoELMParams, seed) -> MoELMParams:
        tokens, targets = lm_batch_from_seed(seed, b_local, seq_len, vocab)

        def loss_fn(p):
            loss, aux = moe_lm_loss_aux(p, tokens, targets, n_heads,
                                        causal, moe_fn=moe_fn, attn=attn,
                                        head=head)
            return loss + aux_coef * aux.astype(loss.dtype)

        # named-scope regions (moe_lm/fwd, moe_lm/comm, moe_lm/optim;
        # the a2a dispatch inside moe_layer_ep adds nested comm scopes)
        with jax.named_scope("moe_lm"):
            with jax.named_scope("fwd"):
                grads = jax.grad(loss_fn)(params)
            with jax.named_scope("comm"):
                grads = _reduce_replicated(grads, force=not check)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return launch_strided(step, clone_params(params), seeds, mesh,
                          EXPERT_AXIS, EP_LM_SPECS, check_vma=check)


def train_moe_lm_dense(params: MoELMParams, seeds, batch_size: int,
                       model_size: int, lr: float = LR, *, seq_len: int,
                       n_heads: int, causal: bool = True,
                       capacity_factor: float = 2.0, k: int = 1,
                       aux_coef: float = 0.0, n_groups: int = 1,
                       attn_impl: str | None = None) -> MoELMParams:
    """Single-device dense trainer with EP's exact semantics — the
    oracle for ``train_moe_lm_ep`` (``n_groups=n``), or plain dense
    MoE-LM training (``n_groups=1``)."""
    from .transformer import resolve_attn
    t_local = _validate_lm(params, batch_size, seq_len, n_groups,
                           model_size, n_heads)
    b_local = t_local // seq_len
    cap = _local_capacity(t_local, n_groups, params.n_experts,
                          capacity_factor)
    rows = shard_seeds_strided(seeds, n_groups)
    vocab = params.vocab
    attn = resolve_attn(attn_impl)

    def step(p, row):
        toks, tgts = jax.vmap(
            lambda s: lm_batch_from_seed(s, b_local, seq_len, vocab))(row)

        def loss_fn(p):
            losses, auxes = jax.vmap(
                lambda tok, tg: moe_lm_loss_aux(
                    p, tok, tg, n_heads, causal, capacity_factor, k, cap,
                    attn=attn))(toks, tgts)
            # sum over groups == the EP shards' psum (SUM, unscaled LR)
            return (jnp.sum(losses)
                    + aux_coef * jnp.sum(auxes).astype(losses.dtype))

        grads = jax.grad(loss_fn)(p)
        return sgd(p, grads, lr), None

    run = jax.jit(lambda p, rows: lax.scan(step, p, rows)[0],
                  donate_argnums=0)
    return run(clone_params(params), rows)
