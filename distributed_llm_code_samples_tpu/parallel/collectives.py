"""Raw collective primitives, named after the reference's NCCL surface.

This is the native-surface ledger of SURVEY.md section 2.7 made code: every
collective the reference consumed through ``torch.distributed``/NCCL has a
TPU-native equivalent here, lowering to XLA collective HLOs that ride ICI:

=====================  ==============================  =======================
reference (NCCL)        usage                           here (XLA over ICI)
=====================  ==============================  =======================
``all_reduce(SUM)``     ``train_ffns.py:165,303,309``   ``lax.psum``
``all_gather``          ``train_ffns.py:203``           ``lax.all_gather``
``reduce_scatter(SUM)`` ``train_ffns.py:255-256``       ``lax.psum_scatter``
send/recv rings         (absent; BASELINE config 3)     ``lax.ppermute``
async handles+wait      ``train_ffns.py:165,170``       XLA async start/done
                                                        pairs, scheduler-driven
=====================  ==============================  =======================

All functions must be called under ``jax.shard_map`` with the named axis
bound by the mesh. Asynchrony is not expressed in user code: XLA emits
``all-reduce-start``/``all-reduce-done`` pairs and its latency-hiding
scheduler moves independent compute between them — the role the reference's
``async_op=True`` + ``handle.wait()`` discipline played by hand.
"""

from __future__ import annotations

import functools

import jax
from jax import lax


def vma_erased() -> bool:
    """True when this process runs the pre-vma jax compat layer (package
    ``__init__``): no varying-manual-axes typing exists, so every launch
    must take its vma-off path — ``check_vma=False`` semantics, explicit
    ``force=True`` reductions — exactly the contract the interpret-mode
    Pallas launches already exercise on modern jax."""
    return getattr(jax.typeof, "erased_vma", False)


if vma_erased():
    # Pre-vma jax transposes psum to ANOTHER psum: a cotangent crossing
    # an all_reduce differentiated through (vp_embed's row completion)
    # comes back scaled by the axis size. Modern jax — in both the vma-on
    # and vma-off regimes — transposes psum to an identity pbroadcast,
    # and the strategies are written against that contract. Restore it
    # with a hand-written VJP (sum forward, pass-through backward).
    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def all_reduce(x, axis_name: str):
        return lax.psum(x, axis_name)

    all_reduce.defvjp(lambda x, a: (lax.psum(x, a), None),
                      lambda a, _, dy: (dy,))
else:
    def all_reduce(x, axis_name: str):
        """Sum across the mesh axis — NCCL ``all_reduce(SUM)`` / ``dist.all_reduce``."""
        return lax.psum(x, axis_name)


def all_gather(x, axis_name: str, *, dim: int = 0):
    """Concatenate shards along ``dim`` across the axis — NCCL ``all_gather``.

    ``tiled=True`` matches the reference's ``torch.cat(sharded_ps)``
    re-assembly (``train_ffns.py:209``): output dim = shard dim * axis size.
    """
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def reduce_scatter(x, axis_name: str, *, dim: int = 0):
    """Sum then scatter shards along ``dim`` — NCCL ``reduce_scatter(SUM)``
    (``train_ffns.py:255-256``)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


def grad_reduce(g, axis_name, force: bool = False):
    """Sum a *gradient* across one axis (or a tuple of axes, one fused
    ``psum``) iff it is still a partial sum there.

    Under JAX's varying-manual-axes (vma) typing, a cotangent's provenance
    decides its state: transposes of plain ops auto-reduce cotangents onto
    axis-invariant (replicated) primals — the transpose of the implicit
    ``pvary`` is a ``psum`` — so they arrive already summed (axis absent
    from ``typeof(g).vma``); cotangents built inside hand-written
    ``custom_vjp`` rules (this framework's entire ops layer) arrive still
    partial (axis present). An unconditional ``psum`` would double-reduce
    the former — grads scale by the axis size. The check is static at
    trace time.
    """
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if force:
        # the vma-off contract (launcher ran check_vma=False — the
        # interpret-mode Pallas launches on modern jax, or EVERY launch
        # under the pre-vma compat layer, see vma_erased): typing is
        # erased, transposes do NOT auto-psum, every cotangent arrives
        # partial — the unconditional psum is then the correct single
        # reduction. Non-forced calls no-op in that regime (empty vma),
        # which is also part of the contract: the gates stand down and
        # each strategy's explicit force sweep reduces each leaf once.
        return lax.psum(g, axes)
    pending = tuple(a for a in axes if a in jax.typeof(g).vma)
    return lax.psum(g, pending) if pending else g


def all_to_all(x, axis_name: str, *, split_dim: int, concat_dim: int):
    """Transpose shard ownership of one dimension — NCCL ``all_to_all``
    (absent from the reference, which has no EP/Ulysses paths; SURVEY.md
    section 2.2). Splits ``split_dim`` across the axis and concatenates the
    received blocks on ``concat_dim`` (``tiled``)."""
    return lax.all_to_all(x, axis_name, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ring_shift(x, axis_name: str, *, shift: int = 1):
    """Neighbor exchange on the axis ring via ``ppermute`` — the send/recv
    primitive (used by ring attention and the pipeline path; the reference
    has no p2p, SURVEY.md section 2.2)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    """This shard's coordinate on the axis — the reference's ``local_rank``."""
    return lax.axis_index(axis_name)


def barrier(x, axis_name: str):
    """In-program ordering fence across the axis: a zero-byte-ish psum that
    orders everything before it on every shard before anything after it —
    the SPMD answer to ``mp.Barrier`` (``test_mp_barrier_gpus.py:32-34``)."""
    token = lax.psum(jax.numpy.zeros(()), axis_name)
    return lax.optimization_barrier((x, token))[0]
