"""Expert parallelism: experts sharded over the mesh, all_to_all dispatch.

No reference counterpart (SURVEY.md section 2.2: expert parallelism absent);
this is the framework's EP extension, built the same way as the other
strategies: the per-shard program and its collectives written out by hand
inside ``shard_map``.

Layout (GShard-style, data group == expert group): tokens are sharded over
the ``"expert"`` mesh axis (each shard routes its own ``T/n`` tokens); the
``E`` experts' FFN weights are sharded over the same axis (``E/n`` experts
live on each device); the router is replicated. Per layer:

- each shard routes locally and builds its ``[T_local, E, C]`` dispatch,
- ``all_to_all`` (split experts, concat capacity) carries every shard's
  slots for experts ``e`` onto the device that owns ``e``,
- the local experts run the hand-VJP ``ffn_block`` on their combined
  ``[E_local, n*C, d]`` slot block,
- the reverse ``all_to_all`` returns results for the shard's own tokens,
  and the gate-scaled combine finishes the layer.

Capacity is derived from the **global** token count (``T_local * n``) and
split evenly across source shards (``C_local = ceil(C_global / n)``), so
EP and the dense oracle agree on how many slots each expert exposes. Drop
*order* is grouped (each shard fills only its own ``C_local`` share —
GShard's grouped dispatch): a shard routing unusually many tokens to one
expert drops locally even if another shard left slots free. The oracle
emulates this exactly by routing each shard's tokens independently with
the same per-group capacity (``tests/test_moe.py``).

Gradients: expert-weight grads are complete locally (every token routed to
an expert arrives on its device — the a2a *is* the reduction's data
movement); router grads are per-shard partial sums and get an explicit
``psum`` (SUM, matching the framework's unscaled-LR convention,
``train_ffns.py:165``). The backward through the a2a pair is the transposed
a2a pair, composed by ``jax.vjp`` around the hand-written block rules.
The Switch load-balancing auxiliary loss (``aux_coef > 0``) is computed
per shard on local tokens (GShard's per-group convention) and folds into
the same router psum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import LR
from ..data import batch_from_seed, shard_seeds_strided
from ..models.moe import MoEStackParams
from ..models.ffn_stack import clone_params
from ..ops.ffn import ffn_block
from ..ops.moe import (dispatch_tensor, dispatch_tensor_topk,
                       expert_capacity, moe_stack_fwd_aux, route_flat,
                       route_top1, route_topk, router_aux_loss,
                       scatter_combine, scatter_dispatch)
from ..optim import sgd
from .collectives import all_to_all, grad_reduce, vma_erased
from .launcher import launch, launch_strided
from .mesh import DATA_AXIS, EXPERT_AXIS, require_axes


def _local_capacity(t_local: int, n_shards: int, n_experts: int,
                    capacity_factor: float) -> int:
    """This shard's slice of the global per-expert capacity: derive from
    the global token count, then ceil-split across source shards."""
    cap_global = expert_capacity(t_local * n_shards, n_experts,
                                 capacity_factor)
    return max(1, -(-cap_global // n_shards))


def moe_layer_ep(wg, w1_local, w2_local, x, capacity_factor: float = 2.0,
                 axis: str = EXPERT_AXIS, k: int = 1,
                 dispatch: str = "dense", comm: str = "psum"):
    """One expert-parallel MoE layer, per-shard view (no residual here —
    the step adds it).

    ``wg [E, d]`` (replicated), ``w1_local [E/n, ffn, d]``,
    ``w2_local [E/n, d, ffn]``, ``x [T_local, d]``. ``dispatch``:
    ``"dense"`` one-hot einsum movement or ``"scatter"`` (O(T*d)
    scatter/gather around the same pair of ``all_to_all``s — identical
    routing/capacity/priority semantics, differential-pinned).
    ``comm="pallas_a2a"`` carries both exchanges (and their backward
    transposes) on the hand-scheduled peer fan-out kernel
    (``ops.pallas_ring.all_to_all_dma_dims``)."""
    if comm == "pallas_a2a":
        from ..ops.pallas_ring import all_to_all_dma_dims
        _a2a = lambda t, sd, cd: all_to_all_dma_dims(  # noqa: E731
            t, axis, sd, cd, None)
    elif comm == "psum":
        _a2a = lambda t, sd, cd: all_to_all(t, axis, split_dim=sd,  # noqa: E731
                                            concat_dim=cd)
    else:
        raise ValueError(f"unknown comm {comm!r} "
                         "(expected 'psum' or 'pallas_a2a')")

    def a2a(t, sd, cd):
        with jax.named_scope("comm"):  # dispatch/return -> ep/.../comm
            return _a2a(t, sd, cd)
    n_experts = wg.shape[0]
    t = x.shape[0]
    cap = _local_capacity(t, lax.axis_size(axis), n_experts,
                          capacity_factor)
    if dispatch == "scatter":
        # O(T*d) movement form — the ops.moe scatter helpers (shared
        # slot bookkeeping) around the SAME pair of all_to_alls
        idx_flat, gates = route_flat(wg, x, k)
        xe, dest, keep = scatter_dispatch(idx_flat, x, n_experts, cap)
        xe = a2a(xe, 0, 1)
        ye = jax.vmap(ffn_block)(w1_local, w2_local, xe)
        ye = a2a(ye, 1, 0)
        return scatter_combine(ye, dest, keep, gates, t)
    if dispatch == "gather":
        # gather-only movement (ops.moe custom-VJP permutation gathers,
        # same slot bookkeeping) around the SAME pair of all_to_alls
        from ..ops.moe import (combine_from_slots, gather_metadata,
                               permute_to_slots)
        idx_flat, gates = route_flat(wg, x, k)
        dest, slot_tok, slot_choice, keep = gather_metadata(
            idx_flat, t, n_experts, cap)
        xe = permute_to_slots(x, dest, slot_tok).reshape(
            n_experts, cap, -1)
        xe = a2a(xe, 0, 1)
        ye = jax.vmap(ffn_block)(w1_local, w2_local, xe)
        ye = a2a(ye, 1, 0)
        return combine_from_slots(ye, gates, dest, slot_tok,
                                  slot_choice, keep)
    if dispatch != "dense":
        raise ValueError(f"unknown dispatch {dispatch!r}")
    if k == 1:
        idx, gate = route_top1(wg, x)
        disp = dispatch_tensor(idx, n_experts, cap, x.dtype)  # [T_loc, E, C]
        comb = disp * gate[:, None, None]
    else:
        idx, gates = route_topk(wg, x, k)
        disp_k = dispatch_tensor_topk(idx, n_experts, cap, x.dtype)
        disp = jnp.sum(disp_k, axis=0)
        comb = jnp.einsum("ktec,tk->tec", disp_k, gates)
    xe = jnp.einsum("tec,td->ecd", disp, x)              # [E, C, d]
    # experts -> their owners; slots from all shards stack on the cap axis
    xe = a2a(xe, 0, 1)                                    # [E/n, n*C, d]
    ye = jax.vmap(ffn_block)(w1_local, w2_local, xe)      # [E/n, n*C, d]
    # results return to the tokens' home shards
    ye = a2a(ye, 1, 0)                                    # [E, C, d]
    return jnp.einsum("tec,ecd->td", comb, ye)


def make_step(batch_size: int, model_size: int, lr: float = LR,
              capacity_factor: float = 2.0, axis: str = EXPERT_AXIS,
              k: int = 1, aux_coef: float = 0.0,
              data_axis: str | None = None, dispatch: str = "dense",
              comm: str = "psum"):
    """One EP step for one shard: local fwd (residual per layer),
    ``jax.vjp``-composed backward over the hand-written rules, optional
    load-balancing aux term, explicit router-grad psum, local SGD.

    Fwd and aux come from ONE stack walk returning ``(y, aux)``; the
    combined gradient is a single vjp with cotangents
    ``(dloss_dx, aux_coef)`` — no second forward, no duplicated a2a.

    ``comm="pallas_a2a"`` implies the launcher runs ``check_vma=False``
    (the Mosaic interpreter's vma propagation is incomplete), which
    erases the provenance signal ``grad_reduce`` keys on — so this path
    reduces the router (and 2-D data-axis) grads with an UNCONDITIONAL
    psum. Empirically pinned both ways: the pure-XLA psum path run under
    ``check_vma=False`` reproduces the exact under-reduction this
    corrects (EP's router cotangents arrive partial there — they flow
    through custom_vjp rules, which vma-off leaves unreduced), and the
    corrected path equals the vma-on psum path leaf for leaf
    (``tests/test_pallas_ring.py``) — i.e. no double reduction either.
    """

    axes = (axis,) if data_axis is None else (axis, data_axis)
    reducer = (grad_reduce if comm == "psum" and not vma_erased()
               else (lambda g, ax: lax.psum(g, ax)))

    def fwd_aux(params: MoEStackParams, x):
        aux = jnp.asarray(0.0, jnp.float32)
        for l in range(params.w1.shape[0]):
            aux = aux + router_aux_loss(params.wg[l], x)
            x = x + moe_layer_ep(params.wg[l], params.w1[l], params.w2[l],
                                 x, capacity_factor, axis, k, dispatch,
                                 comm)
        return x, aux

    def step(params: MoEStackParams, seed) -> MoEStackParams:
        # named-scope regions (ep/fwd, ep/bwd, nested comm on the a2a
        # pair and the router psum, ep/optim)
        with jax.named_scope("ep"):
            x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                          params.w1.dtype)
            with jax.named_scope("fwd"):
                _, vjp = jax.vjp(lambda p: fwd_aux(p, x), params)
            # the aux output is shard-varying under shard_map; its cotangent
            # (the constant aux coefficient) must be cast to match — over
            # every axis the aux varies on (a 2-D mesh adds "data")
            coef = lax.pcast(jnp.asarray(aux_coef, jnp.float32), axes,
                             to="varying")
            with jax.named_scope("bwd"):
                grads = vjp((dloss_dx, coef))[0]
            with jax.named_scope("comm"):
                # router is replicated; its per-shard partial grads sum
                # across the expert axis (train_ffns.py:165 semantics) —
                # and across the data axis on a 2-D mesh. Expert grads
                # are complete on their owner shard within an EP group;
                # the data axis replicates the groups, so they too sum
                # over data (grad_reduce is vma-aware: it never touches
                # the expert axis for them).
                grads = grads._replace(wg=reducer(grads.wg, axes))
                if data_axis is not None:
                    grads = grads._replace(
                        w1=reducer(grads.w1, data_axis),
                        w2=reducer(grads.w2, data_axis))
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    return step


def train_moe_ep(params: MoEStackParams, seeds, batch_size: int,
                 model_size: int, mesh, lr: float = LR,
                 capacity_factor: float = 2.0, k: int = 1,
                 aux_coef: float = 0.0,
                 dispatch: str = "dense",
                 comm: str = "psum") -> MoEStackParams:
    """Run the EP schedule; returns fully-assembled final params.

    ``batch_size`` is the *global token count per EP group* per step; each
    shard routes ``batch_size/n`` tokens (data and experts shard over the
    same axis). Seeds shard stride-wise like the DP strategies
    (``train_ffns.py:182``). ``k`` selects top-k routing; ``aux_coef``
    scales the Switch load-balancing loss into the router gradients.

    A 2-D ``(data, expert)`` mesh replicates the EP group ``dp`` times
    (DDP-style): seeds stride over the flattened ``dp x n`` grid, each
    replica routes independently with its own group capacities, and
    router/expert grads take one extra ``psum`` over the data axis.
    Exactly ``train_moe_dense(batch_size*dp, n_groups=dp*n,
    capacity_groups=n)`` — the differential test.
    """
    require_axes(mesh, EXPERT_AXIS)
    n = mesh.shape[EXPERT_AXIS]
    dp = dict(mesh.shape).get(DATA_AXIS, 1)
    if params.n_experts % n != 0:
        raise ValueError(f"n_experts={params.n_experts} not divisible by "
                         f"expert-axis size {n}")
    if batch_size % n != 0:
        raise ValueError(f"batch_size={batch_size} not divisible by "
                         f"expert-axis size {n}")
    step = make_step(batch_size // n, model_size, lr, capacity_factor,
                     k=k, aux_coef=aux_coef,
                     data_axis=DATA_AXIS if dp > 1 else None,
                     dispatch=dispatch, comm=comm)
    specs = MoEStackParams(wg=P(), w1=P(None, EXPERT_AXIS),
                           w2=P(None, EXPERT_AXIS))
    # a2a-kernel outputs are typed shard-varying (see ddp.train_ddp)
    check = comm == "psum"
    if dp > 1:
        # 2-D data x expert: the seed schedule strides over BOTH axes —
        # shard (d, e) of step t consumes seeds[t*dp*n + d*n + e], the
        # flat strided order the grouped dense oracle reproduces with
        # n_groups=dp*n
        cols = shard_seeds_strided(seeds, dp * n).reshape(-1, dp, n)
        return launch(step, clone_params(params), cols, mesh,
                      param_specs=specs,
                      seed_spec=P(None, DATA_AXIS, EXPERT_AXIS),
                      select_local=lambda s: s[:, 0, 0],
                      check_vma=check)
    return launch_strided(step, clone_params(params), seeds, mesh,
                          EXPERT_AXIS, specs, check_vma=check)


def train_moe_dense(params: MoEStackParams, seeds, batch_size: int,
                    model_size: int, lr: float = LR,
                    capacity_factor: float = 2.0, k: int = 1,
                    aux_coef: float = 0.0, n_groups: int = 1,
                    capacity_groups: int | None = None,
                    dispatch: str = "dense") -> MoEStackParams:
    """Single-device dense MoE trainer with EP's exact semantics — no mesh,
    no collectives; the user-facing oracle for ``train_moe_ep``.

    ``n_groups=1`` is plain dense MoE training (capacity from the global
    token count). ``n_groups=n`` emulates the ``n``-shard EP run *exactly*:
    the strided seed split (``train_ffns.py:182``), GShard's grouped
    dispatch (each group routes its ``batch_size/n`` tokens independently
    against its ``ceil(C_global/n)`` capacity share), per-group aux terms,
    and router grads summed across groups (SUM, unscaled LR,
    ``train_ffns.py:165`` semantics) — so
    ``train_moe_ep(p, seeds, B, d, mesh_n) ==
    train_moe_dense(p, seeds, B, d, n_groups=n)`` is the --method 7
    differential check, runnable without a device mesh.

    ``dispatch``: ``"dense"`` one-hot einsum movement, ``"scatter"``
    (``ops.moe.moe_layer_scatter`` — same math, O(T*d) scatter-add
    movement), or ``"gather"`` (``ops.moe.moe_layer_gather`` —
    gather-only movement in both directions; see bench_moe.py for the
    measured verdict).
    """
    if batch_size % n_groups:
        raise ValueError(f"batch_size={batch_size} not divisible by "
                         f"n_groups={n_groups}")
    t_local = batch_size // n_groups
    # capacity_groups: EP derives each group's slot share from its OWN
    # EP-group size (the expert-axis extent) — on a 2-D data x expert
    # mesh that is n_expert_shards, not the total dp*n group count
    cap = _local_capacity(t_local,
                          capacity_groups if capacity_groups is not None
                          else n_groups,
                          params.n_experts, capacity_factor)
    rows = shard_seeds_strided(seeds, n_groups)  # [global_steps, n_groups]

    def fwd_aux(p, xs):  # xs [n_groups, t_local, d]
        y, aux = jax.vmap(
            lambda x: moe_stack_fwd_aux(p, x, capacity_factor, k, cap,
                                        dispatch))(xs)
        return y, jnp.sum(aux)

    def step(p, row):
        xs, dls = jax.vmap(
            lambda s: batch_from_seed(s, t_local, model_size,
                                      p.w1.dtype))(row)
        _, vjp = jax.vjp(lambda p: fwd_aux(p, xs), p)
        grads = vjp((dls, jnp.asarray(aux_coef, jnp.float32)))[0]
        return sgd(p, grads, lr), None

    run = jax.jit(lambda p, rows: lax.scan(step, p, rows)[0],
                  donate_argnums=0)
    return run(clone_params(params), rows)
