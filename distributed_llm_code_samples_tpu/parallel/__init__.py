"""Parallelism strategies, hand-rolled over raw collectives on a device mesh.

Dispatch surface mirrors the reference's ``fns`` table
(``train_ffns.py:373``): single-device, DDP, FSDP, TP — plus the hybrid
DDP x TP mesh the BASELINE adds, pipeline, MoE expert parallelism, and the
transformer trainers. Launchers share the uniform positional signature
``train(params, seeds, batch_size, model_size, mesh, lr) -> params``
(SURVEY.md L4); the transformer-family entries (methods 8 and 10)
additionally require keyword-only ``seq_len``/``n_heads`` (attention
needs real sequence structure), so generic consumers of ``STRATEGIES``
must pass those for them.
"""

from .mesh import (make_mesh, elastic_mesh, guard_multi_device, DATA_AXIS,
                   MODEL_AXIS, SEQ_AXIS, PIPE_AXIS, EXPERT_AXIS)
from . import collectives
from .single import train_single
from .ddp import train_ddp
from .zero1 import train_ddp_zero1
from .fsdp import train_fsdp
from .tp import train_tp, train_tp_sp
from .hybrid import train_hybrid
from .pipeline import train_pp, train_transformer_pp, train_lm_pp
from .sequence import (ring_attention, sequence_parallel_attention,
                       ulysses_attention, ulysses_parallel_attention)
from .expert import train_moe_ep, train_moe_dense, moe_layer_ep
from .moe_transformer import (train_moe_transformer_ep,
                              train_moe_transformer_dense)
from .transformer import (train_transformer_single, train_transformer_ddp,
                          train_transformer_fsdp, train_transformer_tp,
                          train_transformer_hybrid, train_transformer_seq)
from .lm import (train_lm_single, train_lm_ddp, train_lm_fsdp, train_lm_tp,
                 train_lm_hybrid, train_lm_seq, tp_generate, tp_sample,
                 tp_decode_specs, tp_shard_params, vp_embed,
                 vp_xent)
from .moe_lm import train_moe_lm_ep, train_moe_lm_dense

# Method-number parity with the reference CLI (train_ffns.py:6, :373):
# 1=single, 2=DDP, 3=FSDP, 4=TP; 5+ extend with the hybrid mesh and the
# BASELINE's send/recv pipeline path.
STRATEGIES = {
    1: ("train_single", train_single),
    2: ("train_ddp", train_ddp),
    3: ("train_fsdp", train_fsdp),
    4: ("train_tp", train_tp),
    5: ("train_hybrid", train_hybrid),
    6: ("train_pp", train_pp),
    7: ("train_moe_ep", train_moe_ep),
    8: ("train_transformer_tp", train_transformer_tp),
    10: ("train_moe_transformer_ep", train_moe_transformer_ep),
    11: ("train_lm_tp", train_lm_tp),
    12: ("train_moe_lm_ep", train_moe_lm_ep),
    13: ("train_lm_seq", train_lm_seq),
}

__all__ = [
    "make_mesh", "elastic_mesh", "guard_multi_device",
    "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS",
    "collectives",
    "train_single", "train_ddp", "train_ddp_zero1", "train_fsdp",
    "train_tp", "train_tp_sp", "train_hybrid",
    "train_pp", "train_transformer_pp", "train_lm_pp",
    "train_moe_ep", "train_moe_dense", "moe_layer_ep",
    "train_moe_transformer_ep", "train_moe_transformer_dense",
    "train_transformer_single", "train_transformer_ddp",
    "train_transformer_fsdp", "train_transformer_tp",
    "train_transformer_hybrid", "train_transformer_seq",
    "ring_attention", "sequence_parallel_attention",
    "ulysses_attention", "ulysses_parallel_attention",
    "train_lm_single", "train_lm_ddp", "train_lm_fsdp", "train_lm_tp",
    "train_lm_hybrid", "train_lm_seq", "tp_generate", "tp_sample",
    "tp_decode_specs", "tp_shard_params", "vp_embed",
    "vp_xent",
    "train_moe_lm_ep", "train_moe_lm_dense",
    "STRATEGIES",
]
