"""FSDP / ZeRO-3: params sharded, gathered per layer, grads reduce-scattered.

Parity target: ``train_fsdp`` / ``train_process_fsdp``
(``train_ffns.py:195-287``). The reference chunks every param along dim 0
across ranks, then per step:

- forward: all-gathers each layer's two param shards, **prefetching layer
  l+1's gather during layer l's compute** (``gather_layer_params`` closure,
  ``:200-225``; prefetch chain ``:236-241``);
- backward: same gather machinery walking in reverse (``:245-249``), then
  ``reduce_scatter(SUM)`` of each layer's grads back to shards
  (``:255-256``) — which the reference could *not* overlap (its TODO at
  ``:14, :252``);
- SGD on the local shard only (``:258-259``).

TPU translation: params live sharded along their dim 0 on the ``"data"``
axis (``w1: P(None, "data", None)``, ``w2: P(None, "data", None)`` on the
stacked layout). Inside ``shard_map`` the layer loop is unrolled, so each
layer's ``all_gather`` is an independent async HLO that XLA's scheduler
hoists ahead of the previous layer's compute — the reference's hand-built
prefetch, recovered from the dependence structure alone. The backward's
``psum_scatter`` is likewise async-schedulable, closing the reference's
known overlap gap for free (SURVEY.md section 7 step 4). The
all_gather-forward / reduce_scatter-backward correspondence the reference
builds by hand is explicit here: ``grad_hook`` is literally the VJP of the
gather.

Memory property (the reference's README demo: FSDP fits where DDP OOMs):
full layers exist only transiently; persistent state is ``1/n``-th of the
model per shard. Verified by compiled memory analysis in the test suite.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, reshard_copy
from ..optim import Optimizer, check_state_args, sgd
from ..ops.ffn import ffn_bwd, ffn_bwd_mixed, ffn_fwd, ffn_fwd_mixed
from ..ops.stack import stack_fwd, stack_bwd
from .collectives import all_gather, reduce_scatter
from .launcher import launch_strided
from .mesh import DATA_AXIS, require_axes

# Stacked-layout shard specs: per-layer dim 0 == stacked axis 1.
PARAM_SPECS = FFNStackParams(w1=P(None, DATA_AXIS, None),
                             w2=P(None, DATA_AXIS, None))


def state_spec(leaf) -> P:
    """Optimizer-state leaf -> its ZeRO-3 spec: param-shaped moments
    (stacked ``[L, out, in]``) shard with the params, scalar bookkeeping
    (step counts) replicates. One rule shared by the training path and
    ``checkpoint_shardings`` so the run and the restore can't drift."""
    return (P(None, DATA_AXIS, None) if getattr(leaf, "ndim", 0) == 3
            else P())


def shard_params(params: FFNStackParams, mesh) -> FFNStackParams:
    """Lay params out sharded — the launcher-side ``chunk_p``
    (``train_ffns.py:265-272``) expressed as a sharding, not list surgery."""
    return reshard_copy(params, FFNStackParams(
        w1=NamedSharding(mesh, PARAM_SPECS.w1),
        w2=NamedSharding(mesh, PARAM_SPECS.w2)))


def checkpoint_shardings(params: FFNStackParams, optimizer: Optimizer,
                         mesh):
    """The ``(params, opt_state)`` sharding tree for
    ``run_with_checkpointing(restore_shardings=...)``: a resume restores
    each leaf straight onto its 1/n mesh layout instead of transiently
    materializing the full replicated params + Adam moments on one
    device (exactly the spike FSDP exists to avoid)."""
    pspec = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), PARAM_SPECS,
        is_leaf=lambda v: isinstance(v, P))
    state_shapes = jax.eval_shape(optimizer.init, params)
    sspec = jax.tree_util.tree_map(
        lambda l: NamedSharding(mesh, state_spec(l)), state_shapes)
    return (pspec, sspec)


def make_step(batch_size: int, model_size: int, lr: float = LR,
              unroll: bool = True, axis: str = DATA_AXIS,
              optimizer: Optimizer | None = None, mixed: bool = False,
              comm: str = "psum", ring_interpret: bool | None = None,
              guard=None, seed_accum: int = 1):
    """One FSDP step for one shard (operates on local shard views).

    With ``optimizer``, its state is created from — and lives as — the
    LOCAL param shards: ZeRO-3's full story (params, grads, AND
    optimizer state all 1/n per device; the state never needs a
    collective because the sharded update is elementwise).

    ``mixed`` is FSDP's best-case precision policy: the per-layer shard
    gathers ride the wire in **bf16** — HALF the all_gather bytes of the
    f32 path, on the collective that dominates FSDP's comm volume — and
    the block math is the bf16-MXU/f32-accumulate rule. Casting before
    the gather is value-identical to gathering then casting (the cast is
    elementwise), master shards and the grad reduce_scatter stay f32, so
    FSDP(mixed) == DDP(mixed) leaf for leaf.

    ``comm="pallas_ring"`` swaps BOTH collectives for the hand-scheduled
    RDMA ring kernels (``ops/pallas_ring.py``): the per-layer param
    gathers ride ``ring_all_gather`` and the grad hook rides
    ``ring_reduce_scatter`` — the full FSDP comm pattern under explicit
    control, pinned == the XLA path.

    ``seed_accum > 1`` (topology-elastic resume): the step takes a
    ``[seed_accum]`` seed vector and sums the per-seed SHARD grads —
    the reduce_scatter runs per seed, and the shard sums equal the
    shard of the summed global batch (SUM commutes), preserving the
    save-time update sequence on fewer devices.

    ``guard``: the in-graph hooks living inside the step math — dynamic
    loss scaling under ``mixed`` (the scaled upstream gradient rides the
    bf16 gathers/blocks; grads unscale in f32 after the
    reduce_scatter) and global-norm clipping with the squared norm
    ``psum``-med over the data axis (the grads the update sees are 1/n
    shards). Skip-select + counters live in the launcher wrap."""
    from ..runtime.guardrails import finalize_grads, require_mixed_for_scaling
    require_mixed_for_scaling(guard, mixed)
    if comm not in ("psum", "pallas_ring"):
        raise ValueError(f"unknown comm {comm!r} "
                         "(expected 'psum' or 'pallas_ring')")
    if comm == "pallas_ring":
        from ..ops.pallas_ring import ring_all_gather, ring_reduce_scatter
        # interpret=None lets the kernels auto-detect (interpreter
        # off-TPU, Mosaic on chip); AOT codegen callers pass False
        _ag = lambda t: ring_all_gather(  # noqa: E731
            t, axis, interpret=ring_interpret)
        _rs = lambda t: ring_reduce_scatter(  # noqa: E731
            t, axis, interpret=ring_interpret)
    else:
        _ag = lambda t: all_gather(t, axis, dim=0)  # noqa: E731
        _rs = lambda t: reduce_scatter(t, axis, dim=0)  # noqa: E731

    def gather(w1_shard, w2_shard):
        # train_ffns.py:200-225 — async all_gather of both params of a layer;
        # tiled concat matches the torch.cat re-assembly (:209). Under
        # `mixed` the shards are cast bf16 BEFORE the gather: half the
        # bytes on the wire, same gathered values.
        with jax.named_scope("comm"):  # -> fsdp/{fwd,bwd}/comm
            if mixed:
                w1_shard = w1_shard.astype(jnp.bfloat16)
                w2_shard = w2_shard.astype(jnp.bfloat16)
            return _ag(w1_shard), _ag(w2_shard)

    fwd = ffn_fwd_mixed if mixed else ffn_fwd
    bwd = ffn_bwd_mixed if mixed else ffn_bwd

    def block_fwd(w1_shard, w2_shard, x):
        w1, w2 = gather(w1_shard, w2_shard)
        return fwd(w1, w2, x)

    def block_bwd(dy, w1_shard, w2_shard, x):
        # Backward re-gathers the layer (train_ffns.py:245-249); the gathered
        # full params are transient, never stored.
        w1, w2 = gather(w1_shard, w2_shard)
        return bwd(dy, w1, w2, x)

    def grad_hook(dw1, dw2):
        # The VJP of all_gather is reduce_scatter: full grads -> summed shard
        # (train_ffns.py:255-256), SUM semantics, unscaled LR.
        with jax.named_scope("comm"):
            return _rs(dw1), _rs(dw2)

    def local_grads_of(params, seed, scale=None):
        x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                      params.w1.dtype)
        if scale is not None:
            dloss_dx = dloss_dx * scale.astype(dloss_dx.dtype)
        _, acts = stack_fwd(params.w1, params.w2, x, block_fwd=block_fwd,
                            unroll=unroll)
        _, (g1, g2) = stack_bwd(dloss_dx, params.w1, params.w2, acts,
                                block_bwd=block_bwd, grad_hook=grad_hook,
                                unroll=unroll)
        return FFNStackParams(g1, g2)

    def grads_of(params, seed, scale=None):
        if seed_accum > 1:
            # elastic: per-seed shard grads sum to the shard of the
            # summed global batch (reduce_scatter is linear)
            grads = local_grads_of(params, seed[0], scale)
            for j in range(1, seed_accum):
                grads = jax.tree_util.tree_map(
                    jnp.add, grads, local_grads_of(params, seed[j], scale))
        else:
            grads = local_grads_of(params, seed, scale)
        # the update sees 1/n grad shards: the true global norm needs
        # the squared norm psum-med over the shard axis
        return finalize_grads(grads, scale, guard, axis=axis)

    def step(params: FFNStackParams, seed, scale=None) -> FFNStackParams:
        # named-scope regions (fsdp/fwd, fsdp/bwd, nested comm on every
        # gather/scatter, fsdp/optim) — utils/trace_analysis.SCOPES
        with jax.named_scope("fsdp"):
            grads = grads_of(params, seed, scale)
            with jax.named_scope("optim"):
                # Sharded SGD on the local chunk only (train_ffns.py:258-259).
                return sgd(params, grads, lr)

    def step_opt(carry, seed, scale=None):
        params, state = carry
        with jax.named_scope("fsdp"):
            grads = grads_of(params, seed, scale)
            with jax.named_scope("optim"):
                return optimizer.update(grads, state, params, lr)

    return step if optimizer is None else step_opt


def train_fsdp(params: FFNStackParams, seeds, batch_size: int,
               model_size: int, mesh, lr: float = LR, unroll: bool = True,
               optimizer: Optimizer | None = None, opt_state=None,
               return_state: bool = False, mixed: bool = False,
               comm: str = "psum", guard=None, guard_state=None,
               return_guard: bool = False, seed_accum: int = 1):
    """Run the full FSDP schedule; returns final params as a global array
    (re-assembly is implicit in the output sharding — no host-side concat
    like ``train_ffns.py:284-287`` is needed). ``optimizer`` runs a
    stateful update on the local shards — the optimizer state inherits
    the 1/n param sharding (full ZeRO-3). ``opt_state``/``return_state``
    thread the state through the program boundary (same checkpoint
    surface as ``train_ddp``); state leaves must be params-like (they
    take the param sharding) or scalars (replicated) — true of every
    optimizer in ``optim.py``."""
    require_axes(mesh, DATA_AXIS)
    from ..runtime.guardrails import check_guard_args
    check_guard_args(guard, guard_state, return_guard)
    n = mesh.shape[DATA_AXIS]
    if params.w1.shape[1] % n or params.w2.shape[1] % n:
        raise ValueError(
            f"param dims {params.w1.shape[1]}x{params.w2.shape[1]} not "
            f"divisible by {n} shards (the reference's chunk() had the same "
            "implicit requirement)")
    params = shard_params(params, mesh)
    step = make_step(batch_size, model_size, lr, unroll,
                     optimizer=optimizer, mixed=mixed, comm=comm,
                     guard=guard, seed_accum=seed_accum)

    # ring-kernel outputs are typed shard-varying (see ddp.train_ddp)
    check = comm == "psum"
    check_state_args(optimizer, opt_state, return_state)
    gkw = {}
    if guard is not None:
        gkw = dict(guard=guard, guard_state=guard_state,
                   guard_scale=guard.scaling)
    if optimizer is None:
        out = launch_strided(step, params, seeds, mesh, DATA_AXIS,
                             PARAM_SPECS, accum=seed_accum,
                             check_vma=check, **gkw)
    else:
        # zeros_like of the sharded params keeps their sharding, so the
        # state enters shard_map already 1/n per device; scalar leaves
        # replicate
        state = optimizer.init(params) if opt_state is None else opt_state
        state_specs = jax.tree_util.tree_map(state_spec, state)
        out = launch_strided(step, params, seeds, mesh, DATA_AXIS,
                             PARAM_SPECS, accum=seed_accum, state=state,
                             state_specs=state_specs,
                             return_state=return_state, check_vma=check,
                             **gkw)
    if guard is not None and not return_guard:
        out = out[0]
    return out
