"""ZeRO-1: DDP with the optimizer state sharded across the data axis.

The reference stops at ZeRO-3-style param sharding for SGD
(``train_ffns.py:195-287``) — with no optimizer state, stage 1 has nothing
to shard there. This framework's stateful optimizers (``optim.momentum``,
``optim.adam``) change that: replicated Adam state costs 2x params per
device; ZeRO-1 cuts it to 2x/n while keeping DDP's compute and comms
shape.

Hand-rolled over raw collectives, like every other strategy here:

- params stay **replicated** (DDP layout); each shard computes local
  grads for its own data column.
- grads are **reduce_scattered** along the layer axis (SUM — the same
  total bytes on the wire as DDP's all_reduce, but each rank ends up
  owning only its ``L/n`` layers' summed grads: ZeRO's observation that
  the reduction and the partition can be the same collective).
- each rank updates only its ``L/n``-layer param slice with its local
  optimizer-state shard — the only place state exists.
- updated slices are **all_gathered** back to full replicated params for
  the next step's forward.

Per-step comms: 1 reduce_scatter + 1 all_gather per param tensor vs
DDP's 1 all_reduce — identical bandwidth on a ring (an all_reduce *is*
reduce_scatter + all_gather), so the state sharding is free. The
partition unit is whole layers (leading axis of the stacked params),
which requires ``L % n == 0``; matching the strategy-wide convention
(e.g. ``pipeline.py``).

Differential guarantees (tests/test_optim.py): with SGD, ZeRO-1 equals
plain DDP exactly (stateless update commutes with the partition); with
momentum/Adam it equals DDP running the same optimizer with replicated
state — sharding the state changes where it lives, never the math.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import LR
from ..models.ffn_stack import FFNStackParams, clone_params
from ..optim import Optimizer, adam
from .collectives import all_gather, axis_index, reduce_scatter
from .ddp import local_grads
from .launcher import launch_strided
from .mesh import DATA_AXIS, require_axes


def make_step(batch_size: int, model_size: int, n_shards: int,
              lr: float = LR, unroll: bool = True, axis: str = DATA_AXIS,
              optimizer: Optimizer | None = None, accum: int = 1,
              mixed: bool = False):
    """One ZeRO-1 step for one shard: ``((params, state), seed) ->
    (params, state)`` with ``state`` covering only this rank's layers.
    ``accum`` gradient-accumulates local grads over token chunks before
    the single reduce_scatter (``ops.stack.accumulated_grads``)."""
    opt = adam() if optimizer is None else optimizer

    def shard_of(tree):
        """This rank's ``L/n``-layer slice of a stacked-leaf pytree."""
        r = axis_index(axis)
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_slice_in_dim(
                a, r * (a.shape[0] // n_shards), a.shape[0] // n_shards, 0),
            tree)

    def step(carry, seed):
        # named-scope regions (zero1/fwd, zero1/bwd, zero1/comm,
        # zero1/optim) — utils/trace_analysis.SCOPES
        with jax.named_scope("zero1"):
            params, state = carry
            grads = local_grads(params, seed, batch_size, model_size,
                                unroll, accum=accum, mixed=mixed)
            with jax.named_scope("comm"):
                # SUM-reduce AND partition in one collective: rank r
                # receives the summed grads of its own layers only
                # (train_ffns.py:165 SUM semantics; ZeRO's
                # reduce-scatter observation)
                gshard = jax.tree_util.tree_map(
                    lambda g: reduce_scatter(g, axis, dim=0), grads)
            with jax.named_scope("optim"):
                pshard, state = opt.update(gshard, state,
                                           shard_of(params), lr)
            with jax.named_scope("comm"):
                # re-assemble replicated params for the next forward
                params = jax.tree_util.tree_map(
                    lambda p: all_gather(p, axis, dim=0), pshard)
            return params, state

    return step, shard_of, opt


def train_ddp_zero1(params: FFNStackParams, seeds, batch_size: int,
                    model_size: int, mesh, lr: float = LR,
                    unroll: bool = True,
                    optimizer: Optimizer | None = None,
                    accum: int = 1,
                    mixed: bool = False) -> FFNStackParams:
    """Run the ZeRO-1 schedule; returns the (replicated) final params.

    ``optimizer`` defaults to ``optim.adam()`` — the state-heavy case
    ZeRO-1 exists for. Data sharding matches DDP (strided seed columns,
    ``train_ffns.py:182``), so ``train_ddp_zero1(optimizer=o)`` ==
    ``train_ddp(optimizer=o)`` leaf-for-leaf.
    """
    require_axes(mesh, DATA_AXIS)
    n = mesh.shape[DATA_AXIS]
    n_layers = params.w1.shape[0]
    if n_layers % n:
        raise ValueError(
            f"{n_layers} layers not divisible across {n} ranks: ZeRO-1 "
            "partitions optimizer state in whole-layer units")
    step, shard_of, opt = make_step(batch_size, model_size, n, lr, unroll,
                                    optimizer=optimizer, accum=accum,
                                    mixed=mixed)

    # check_vma off: the re-assembled params are replicated by construction
    # (every rank all_gathers the same disjoint slices) but typed varying —
    # see launcher.launch
    return launch_strided(step, clone_params(params), seeds, mesh,
                          DATA_AXIS, P(),
                          make_carry=lambda p: (p, opt.init(shard_of(p))),
                          check_vma=False)
