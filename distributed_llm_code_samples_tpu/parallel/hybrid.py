"""Hybrid DDP x TP on a 2-D device mesh — beyond the reference.

The reference never composes strategies (every run is a flat world,
``train_ffns.py:25``), but the driver's north star adds a hybrid
DDP x MP mesh (BASELINE.md config 4). Composition here is free because each
strategy is just a set of collectives bound to a mesh *axis name*:

- params are TP-sharded over ``"model"`` and replicated over ``"data"``;
- data is strided over ``"data"`` ranks and replicated over ``"model"``;
- backward: per-layer ``psum`` of the input grad over ``"model"`` (the TP
  f/g trick) and per-layer ``psum`` of the *weight* grads over ``"data"``
  (the DDP hook) — two independent orthogonal reductions.

With ``model=1`` this degenerates to DDP; with ``data=1`` to TP. The
differential tests assert both degeneracies plus DDP(d) == hybrid(d x m).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import LR
from ..data import batch_from_seed
from ..models.ffn_stack import FFNStackParams, reshard_copy
from ..optim import sgd
from ..ops.ffn import ffn_bwd, ffn_bwd_mixed, ffn_fwd, ffn_fwd_mixed
from ..ops.stack import stack_fwd, stack_bwd
from .collectives import all_reduce
from .launcher import launch_strided
from .mesh import DATA_AXIS, MODEL_AXIS, require_axes

PARAM_SPECS = FFNStackParams(w1=P(None, MODEL_AXIS, None),
                             w2=P(None, None, MODEL_AXIS))


def shard_params(params: FFNStackParams, mesh) -> FFNStackParams:
    return reshard_copy(params, FFNStackParams(
        w1=NamedSharding(mesh, PARAM_SPECS.w1),
        w2=NamedSharding(mesh, PARAM_SPECS.w2)))


def make_step(batch_size: int, model_size: int, lr: float = LR,
              unroll: bool = True, mixed: bool = False):
    fwd = ffn_fwd_mixed if mixed else ffn_fwd
    bwd = ffn_bwd_mixed if mixed else ffn_bwd

    def block_fwd(w1_shard, w2_shard, x):
        y = fwd(w1_shard, w2_shard, x)
        with jax.named_scope("comm"):  # TP psum -> hybrid/fwd/comm
            return all_reduce(y, MODEL_AXIS)

    def block_bwd(dy, w1_shard, w2_shard, x):
        dx, grads = bwd(dy, w1_shard, w2_shard, x)
        with jax.named_scope("comm"):
            return all_reduce(dx, MODEL_AXIS), grads

    def grad_hook(dw1, dw2):
        # DDP reduction of the TP-local weight-grad shards across replicas.
        with jax.named_scope("comm"):
            return (all_reduce(dw1, DATA_AXIS), all_reduce(dw2, DATA_AXIS))

    def step(params: FFNStackParams, seed) -> FFNStackParams:
        # named-scope regions (hybrid/fwd, hybrid/bwd, nested comm on
        # both axes' collectives, hybrid/optim)
        with jax.named_scope("hybrid"):
            x, dloss_dx = batch_from_seed(seed, batch_size, model_size,
                                          params.w1.dtype)
            _, acts = stack_fwd(params.w1, params.w2, x,
                                block_fwd=block_fwd, unroll=unroll)
            _, (g1, g2) = stack_bwd(dloss_dx, params.w1, params.w2, acts,
                                    block_bwd=block_bwd,
                                    grad_hook=grad_hook, unroll=unroll)
            with jax.named_scope("optim"):
                return sgd(params, FFNStackParams(g1, g2), lr)

    return step


def train_hybrid(params: FFNStackParams, seeds, batch_size: int,
                 model_size: int, mesh, lr: float = LR,
                 unroll: bool = True, mixed: bool = False) -> FFNStackParams:
    """Run the full hybrid schedule on a mesh with ``"data"`` and ``"model"``
    axes. Seeds are strided across ``"data"`` only. ``mixed`` selects the
    bf16-MXU block rule on both axes' composition."""
    require_axes(mesh, DATA_AXIS, MODEL_AXIS)
    tp = mesh.shape[MODEL_AXIS]
    if params.w1.shape[1] % tp:
        raise ValueError(f"ffn_dim {params.w1.shape[1]} not divisible by "
                         f"{tp} model shards")
    params = shard_params(params, mesh)
    step = make_step(batch_size, model_size, lr, unroll, mixed=mixed)

    return launch_strided(step, params, seeds, mesh, DATA_AXIS,
                          PARAM_SPECS)
