"""Device-mesh bootstrap — the TPU replacement for the reference's process
runtime (``train_ffns.py:121-127, :184-191``).

The reference spawns one OS process per GPU and rendezvous over
``MASTER_ADDR/PORT`` + NCCL. On TPU the whole pattern collapses into SPMD:
one process per host, an explicit ``jax.sharding.Mesh`` over ICI (and DCN
across hosts), and collectives addressed by mesh axis *name* instead of
process-group handles. Axis names used across the framework:

- ``"data"``   — data parallelism (DDP and FSDP both shard over it)
- ``"model"``  — tensor parallelism (Megatron-style)
- ``"seq"``    — sequence/context parallelism (long-context extensions)
- ``"pipe"``   — pipeline parallelism (layers staged, ppermute send/recv)
- ``"expert"`` — expert parallelism (MoE experts, all_to_all dispatch)

Multi-chip without hardware: ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
with ``JAX_PLATFORMS=cpu`` gives N fake devices, so every strategy and every
collective test runs on a dev box — this replaces the reference's hard
dependency on physical multi-GPU (SURVEY.md section 4).
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def make_mesh(axes: Mapping[str, int] | None = None,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a mesh with named axes from the first ``prod(axes)`` devices.

    ``axes=None`` uses every visible device on a 1-D ``("data",)`` mesh —
    the analogue of the reference's flat ``world_size = nGPUs``
    (``train_ffns.py:25, :125``).
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    n = math.prod(axes.values())
    if n > len(devices):
        raise ValueError(f"mesh {dict(axes)} needs {n} devices, "
                         f"only {len(devices)} visible")
    arr = np.asarray(devices[:n]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes.keys()))


def require_axes(mesh: Mesh, *axes: str) -> None:
    """Fail with a readable message when a strategy is handed a mesh without
    the axis names it shards over."""
    missing = [a for a in axes if a not in mesh.shape]
    if missing:
        raise ValueError(
            f"mesh has axes {dict(mesh.shape)} but this strategy needs "
            f"{missing} — build it with make_mesh({{'"
            + "': n, '".join(axes) + "': n})")


def elastic_mesh(axes: Mapping[str, int],
                 devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Rebuild a mesh over the devices that survived — the degraded-mode
    path for ``runtime.failure.device_healthcheck(allow_degraded=True)``
    reporting fewer devices than the mesh was built with.

    The ``"data"`` axis is the elastic one: it shrinks (or grows) to
    whatever the survivors support, while every other axis (model, pipe,
    seq, expert — all of which shard *structure*, not batch) keeps its
    requested size; a survivor count that can't host the rigid axes
    fails loudly. Resuming a checkpoint on the shrunken mesh is the
    checkpoint layer's elastic-resume contract
    (``checkpoint.run_with_checkpointing``): the remaining seed schedule
    is restrided so the save-time global batch — and hence the loss
    trajectory — is preserved.
    """
    devices = list(devices if devices is not None else jax.devices())
    rigid = math.prod(n for a, n in axes.items() if a != DATA_AXIS)
    if DATA_AXIS not in axes:
        return make_mesh(axes, devices)
    data = len(devices) // rigid
    if data < 1:
        raise ValueError(
            f"{len(devices)} surviving device(s) cannot host the rigid "
            f"axes {[(a, n) for a, n in axes.items() if a != DATA_AXIS]} "
            f"(need {rigid} per data shard)")
    return make_mesh({**axes, DATA_AXIS: data}, devices)


def guard_multi_device(min_devices: int = 2) -> None:
    """Startup guard mirroring the reference's 1-GPU refusal
    (``train_ffns.py:25-27``) — but also guarding 0, which it didn't."""
    n = jax.device_count()
    if n < min_devices:
        raise RuntimeError(
            f"Only {n} device(s) available; multi-device strategies need "
            f">= {min_devices}. For a fake multi-chip mesh set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "JAX_PLATFORMS=cpu before importing jax.")
