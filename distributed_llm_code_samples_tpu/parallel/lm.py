"""Language-model trainers: single-device, DDP, FSDP/ZeRO-3, Megatron TP.

The LM family (``models.lm``) is the transformer stack plus the pieces the
reference mocked away — embeddings, a real cross-entropy objective
(``ops.xent``), a tied head — so the strategies here are the transformer
trainers (``parallel/transformer.py``) extended over that surface:

- **DDP**: replicated params, strided seed shards, one grad ``psum`` per
  step (SUM, unscaled LR — ``train_ffns.py:165`` semantics).
- **FSDP/ZeRO-3**: every leaf sharded over the data axis (blocks on their
  stacked layer dim, ``wte``/``wpe`` on rows, ``ln_f`` on features),
  gathered transiently; grads return pre-scattered through the gathers'
  ``psum_scatter`` transposes.
- **TP (Megatron-LM)**: the block stack shards as in
  ``parallel/transformer.py`` (heads column-, ``wo``/``w2`` row-parallel);
  the embedding and the tied head shard the **vocab** dim — each shard owns
  ``V/n`` rows of ``wte``, looks up / scores only its own slice, and the
  cross-entropy runs **vocab-parallel**: max, normalizer, and target-logit
  terms each complete with one collective over the model axis
  (``vp_xent``), so the full ``[N, V]`` logits never exist on any device —
  the memory-critical piece at real vocab sizes, where the logits would
  dwarf every activation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import LR
from ..data import lm_batch_from_seed
from ..models.ffn_stack import clone_params
from ..models.lm import LMParams, lm_loss
from ..models.transformer import transformer_block, transformer_fwd
from ..ops.norm import layernorm
from ..ops.xent import xent_loss
from ..optim import check_state_args, sgd
from .collectives import (all_gather, all_reduce, axis_index,
                          grad_reduce, vma_erased)
from .launcher import launch, launch_strided
from .mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS, require_axes
from .transformer import (TP_SPECS, _f_gate, _shard, _validate_shapes,
                          _validate_tp, resolve_attn, tp_block)

def _lm_fsdp_specs() -> LMParams:
    from .transformer import FSDP_SPECS
    return LMParams(wte=P(DATA_AXIS, None), wpe=P(DATA_AXIS, None),
                    blocks=FSDP_SPECS, ln_f=P(DATA_AXIS))


def _lm_tp_specs() -> LMParams:
    return LMParams(wte=P(MODEL_AXIS, None), wpe=P(), blocks=TP_SPECS,
                    ln_f=P())


def _validate_lm(batch_size: int, seq_len: int, model_size: int,
                 n_heads: int, params: LMParams) -> None:
    _validate_shapes(batch_size, seq_len, model_size, n_heads)
    if seq_len > params.max_seq_len:
        raise ValueError(f"seq_len={seq_len} exceeds the model's "
                         f"max_seq_len={params.max_seq_len}")


def resolve_head(head_impl: str | None):
    """Map a ``head_impl`` name to the LM head+loss op ``models.lm.lm_loss``
    plugs in: None/"oracle" = materialized logits + hand-VJP xent
    (``ops/xent.py``); "fused" = the fused Pallas head
    (``ops.pallas_xent.head_xent`` — online logsumexp over vocab tiles,
    no ``[N, V]`` array in either direction; interpret mode
    automatically off-TPU)."""
    if head_impl in (None, "oracle"):
        return None
    if head_impl == "fused":
        from ..ops.pallas_xent import head_xent
        interpret = jax.default_backend() != "tpu"
        return lambda h, w, t: head_xent(h, w, t, interpret)
    raise ValueError(f"unknown head_impl {head_impl!r} "
                     "(expected 'oracle' or 'fused')")


def _make_step(batch_size: int, model_size: int, seq_len: int,
               n_heads: int, lr: float, attn=None, reduce_axes=(),
               optimizer=None, batch_fn=None, head=None,
               force_reduce: bool = False, mixed: bool = False):
    """One update step on the real LM objective; ``batch_size`` is
    tokens/step (seq folded, CLI convention ``train_ffns.py:379``).
    Without ``optimizer`` it's the reference's stateless inline SGD
    (``(params, seed) -> params``); with one, the carry is ``(params,
    opt_state)`` — the full LLM loop (AdamW + clipping + schedules all
    compose through ``optim.py``). ``batch_fn(seed) -> (tokens,
    targets)`` overrides the synthetic seeds-as-dataset source — the hook
    real-text training plugs into (``data.text_batch_from_seed``)."""
    b = batch_size // seq_len

    def grads_of(params, seed):
        tokens, targets = (batch_fn(seed) if batch_fn is not None else
                           lm_batch_from_seed(seed, b, seq_len,
                                              params.vocab))
        with jax.named_scope("fwd"):
            # autodiff strategy: jax.grad traces forward and transpose in
            # one call, so the "fwd" region also tags the backward ops
            # (the naming-map caveat, utils/trace_analysis.py)
            grads = jax.grad(lm_loss)(params, tokens, targets, n_heads,
                                      attn, head, mixed)
        if reduce_axes:
            with jax.named_scope("comm"):
                # force_reduce: the launcher runs check_vma=False
                # (interpret-mode multi-tile Pallas kernels can't
                # type-check), which erases the provenance signal
                # grad_reduce keys on AND stops the transpose machinery's
                # auto-psum — cotangents of replicated params arrive
                # partial. Unconditional psum is then the correct (single)
                # reduction — the expert.py pallas_a2a contract, pinned
                # there both ways.
                grads = jax.tree_util.tree_map(
                    lambda g: grad_reduce(g, reduce_axes,
                                          force=force_reduce), grads)
        return grads

    def step(params: LMParams, seed) -> LMParams:
        # named-scope regions (lm/fwd, lm/comm on DDP meshes, lm/optim)
        with jax.named_scope("lm"):
            grads = grads_of(params, seed)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    def step_opt(carry, seed):
        params, state = carry
        with jax.named_scope("lm"):
            grads = grads_of(params, seed)
            with jax.named_scope("optim"):
                return optimizer.update(grads, state, params, lr)

    return step if optimizer is None else step_opt


def train_lm_single(params: LMParams, seeds, batch_size: int,
                    model_size: int, mesh=None, lr: float = LR, *,
                    seq_len: int, n_heads: int,
                    attn_impl: str | None = None, optimizer=None,
                    opt_state=None, return_state: bool = False,
                    batch_fn=None, head_impl: str | None = None,
                    mixed: bool = False):
    """Single-device LM trainer — the oracle the parallel forms are pinned
    to. ``optimizer``/``opt_state``/``return_state`` follow the DDP
    contract (``ddp.py``): stateful rules thread ``(params, state)``
    through the scan and segments resume exactly. ``batch_fn(seed) ->
    (tokens, targets)`` swaps the synthetic data source for a real one
    (e.g. ``data.text_batch_from_seed`` windows over the embedded
    corpus). ``mixed`` runs the bf16-trunk / f32-head-and-master policy
    (``models.lm.lm_loss(mixed=True)``).

    Compile-cache caveat: ``optimizer`` and ``batch_fn`` are STATIC jit
    arguments hashed by identity — reuse the SAME objects across calls
    (segmented runs, checkpoint resume, bench loops). A fresh lambda or
    optimizer per call silently recompiles every call and grows the jit
    cache."""
    _validate_lm(batch_size, seq_len, model_size, n_heads, params)
    check_state_args(optimizer, opt_state, return_state)

    if optimizer is None:
        return _run_lm_single(clone_params(params), jnp.asarray(seeds),
                              batch_size, model_size, lr, seq_len,
                              n_heads, attn_impl, batch_fn, head_impl,
                              mixed)

    state = optimizer.init(params) if opt_state is None else opt_state
    out, state = _run_lm_single_opt(
        (clone_params(params), state), jnp.asarray(seeds), batch_size,
        model_size, lr, seq_len, n_heads, attn_impl, optimizer, batch_fn,
        head_impl, mixed)
    return (out, state) if return_state else out


@functools.partial(jax.jit, static_argnums=tuple(range(2, 11)),
                   donate_argnums=0)
def _run_lm_single(params, seeds, batch_size, model_size, lr, seq_len,
                   n_heads, attn_impl, batch_fn, head_impl,
                   mixed=False):
    """Module-level jit (the ``single.py`` pattern): repeat calls with
    the same static config — including the same ``optimizer``/``batch_fn``
    *objects*, which hash by identity — reuse the compiled program.
    Segmented runs (checkpointing, bench best-of-N loops,
    ``train_real_text.py``) pay one compile instead of one per call."""
    step = _make_step(batch_size, model_size, seq_len, n_heads, lr,
                      resolve_attn(attn_impl), batch_fn=batch_fn,
                      head=resolve_head(head_impl), mixed=mixed)
    return lax.scan(lambda p, s: (step(p, s), None), params, seeds)[0]


@functools.partial(jax.jit, static_argnums=tuple(range(2, 12)))
def _run_lm_single_opt(carry, seeds, batch_size, model_size, lr, seq_len,
                       n_heads, attn_impl, optimizer, batch_fn, head_impl,
                       mixed=False):
    # no donation: callers may hold/reuse the opt_state they passed in
    step = _make_step(batch_size, model_size, seq_len, n_heads, lr,
                      resolve_attn(attn_impl), optimizer=optimizer,
                      batch_fn=batch_fn, head=resolve_head(head_impl),
                      mixed=mixed)
    return lax.scan(lambda c, s: (step(c, s), None), carry, seeds)[0]


def _vma_check(attn_impl, head_impl=None) -> bool:
    """Whether the launcher may run shard_map's vma typing.

    Flash attention: off only in interpret mode (the Pallas
    interpreter's vma propagation is incomplete — jax's own error
    suggests check_vma=False); the compiled TPU kernels pass full
    checking (the AOT tests pin it).

    The fused head: off on EVERY backend. Under vma-on, the tied
    ``wte``'s cotangent has MIXED provenance — the embedding-gather
    contribution arrives auto-psummed (plain-op transpose) while the
    kernel's hand-written ``dw`` arrives partial — and their sum is
    typed varying, so any downstream psum double-counts the
    already-reduced embedding part (scaled by the axis size). The
    vma-off force-reduce contract (``grad_reduce(force=True)``) keeps
    every cotangent partial and reduces exactly once; the oracle head
    never hits this because both of its wte uses are plain ops.

    Under the pre-vma jax compat layer there is no vma typing at all,
    so EVERY launch takes the vma-off path (``collectives.vma_erased``)."""
    if vma_erased():
        return False
    if head_impl == "fused":
        return False
    return not (attn_impl == "flash"
                and jax.default_backend() != "tpu")


def train_lm_ddp(params: LMParams, seeds, batch_size: int, model_size: int,
                 mesh, lr: float = LR, *, seq_len: int, n_heads: int,
                 attn_impl: str | None = None, optimizer=None,
                 opt_state=None, return_state: bool = False,
                 head_impl: str | None = None, mixed: bool = False,
                 guard=None, guard_state=None, return_guard: bool = False):
    """DDP: replicated params, strided seeds, grads summed per step.
    ``optimizer`` threads replicated state (the ``ddp.py`` contract).
    ``head_impl="fused"`` swaps the tied head + xent for the fused
    Pallas kernels (``ops/pallas_xent.py``) per shard. ``mixed`` runs
    each shard's step under the LM bf16 policy (bf16 trunk, f32
    head/grads — grads stay f32, so the psum semantics are unchanged
    and the DDP==FSDP==single differentials hold in mixed mode).
    ``guard``/``guard_state``/``return_guard``: the launcher-level
    in-graph skip-step guardrail (``runtime/guardrails.py``)."""
    require_axes(mesh, DATA_AXIS)
    _validate_lm(batch_size, seq_len, model_size, n_heads, params)
    check_state_args(optimizer, opt_state, return_state)
    from ..runtime.guardrails import check_guard_args
    check_guard_args(guard, guard_state, return_guard)
    check = _vma_check(attn_impl, head_impl)
    # force_reduce under vma-off: the unconditional-psum reduction
    # contract (see _make_step)
    step = _make_step(batch_size, model_size, seq_len, n_heads, lr,
                      resolve_attn(attn_impl), reduce_axes=(DATA_AXIS,),
                      optimizer=optimizer, head=resolve_head(head_impl),
                      force_reduce=not check, mixed=mixed)
    gkw = ({} if guard is None
           else dict(guard=guard, guard_state=guard_state))
    if optimizer is None:
        out = launch_strided(step, clone_params(params), seeds, mesh,
                             DATA_AXIS, P(), check_vma=check, **gkw)
    else:
        state = optimizer.init(params) if opt_state is None else opt_state
        out = launch_strided(step, clone_params(params), seeds, mesh,
                             DATA_AXIS, P(), state=state, state_specs=P(),
                             return_state=return_state, check_vma=check,
                             **gkw)
    if guard is not None and not return_guard:
        out = out[0]
    return out


def train_lm_fsdp(params: LMParams, seeds, batch_size: int, model_size: int,
                  mesh, lr: float = LR, *, seq_len: int, n_heads: int,
                  attn_impl: str | None = None, optimizer=None,
                  opt_state=None, return_state: bool = False,
                  head_impl: str | None = None, mixed: bool = False):
    """FSDP/ZeRO-3 over the whole LM surface: block stacks gathered layer
    by layer (the transformer FSDP loop), the embedding/head table and
    positions gathered once per step — transiently, so peak param memory
    stays ``O(|params|/n + one layer)``. All grads come back pre-scattered
    through the gathers' ``psum_scatter`` transposes; sharded update.

    With ``optimizer``, its state is created from — and lives as — the
    LOCAL param shards: full ZeRO-3 on the LM (params, grads, AND
    optimizer state all 1/n per device; the elementwise update needs no
    collective).

    ``mixed`` (the LM bf16 policy): block shards are cast to bf16
    BEFORE their per-layer gathers — half the collective bytes, the
    FFN-FSDP mixed stance — and the trunk runs bf16; ``wte`` gathers
    once in f32 (it serves the f32 head) with the embedding lookup cast
    after, so the math matches ``lm_loss(mixed=True)`` leaf for leaf
    and the FSDP==DDP==single differentials keep their power."""
    require_axes(mesh, DATA_AXIS)
    n = mesh.shape[DATA_AXIS]
    _validate_lm(batch_size, seq_len, model_size, n_heads, params)
    check_state_args(optimizer, opt_state, return_state)
    for name, leaf in [("wte", params.wte), ("wpe", params.wpe),
                       ("ln_f", params.ln_f)]:
        if leaf.shape[0] % n:
            raise ValueError(f"{name} dim {leaf.shape[0]} not divisible by "
                             f"{n} shards")
    for name, leaf in zip(params.blocks._fields, params.blocks):
        if leaf.shape[1] % n:
            raise ValueError(f"blocks.{name} dim {leaf.shape[1]} not "
                             f"divisible by {n} shards")
    attn = resolve_attn(attn_impl)
    head = resolve_head(head_impl)
    b = batch_size // seq_len
    vocab = params.vocab  # the global count — p.wte is a shard inside step

    def grads_of(params: LMParams, seed):
        tokens, targets = lm_batch_from_seed(seed, b, seq_len, vocab)

        def loss_fn(p: LMParams):
            bf16 = jnp.bfloat16
            with jax.named_scope("comm"):
                wte = all_gather(p.wte, DATA_AXIS, dim=0)
                wpe = all_gather(p.wpe, DATA_AXIS, dim=0)
                ln_f = all_gather(p.ln_f, DATA_AXIS, dim=0)
            if mixed:
                # trunk in bf16 (embedding lookup + positions cast
                # after the f32 wte gather — wte also serves the f32
                # head); ln_f cast matches lm_loss(mixed=True)
                x = wte.astype(bf16)[tokens] + wpe[:seq_len].astype(bf16)
                ln_f = ln_f.astype(bf16)
            else:
                x = wte[tokens] + wpe[:seq_len]
            for l in range(p.blocks.w1.shape[0]):
                # mixed: shards cast BEFORE the gather — half the
                # collective bytes (the FFN-FSDP mixed stance); cast of
                # the shard then concat == concat then cast, so the
                # values equal the single-device bf16 trunk's
                with jax.named_scope("comm"):
                    full = [all_gather(leaf[l].astype(bf16) if mixed
                                       else leaf[l], DATA_AXIS, dim=0)
                            for leaf in p.blocks]
                x = transformer_block(*full, x, n_heads, causal=True,
                                      attn=attn)
            h = layernorm(ln_f, x)
            if mixed:
                h = h.astype(jnp.float32)
            if head is not None:
                return head(h.reshape(-1, h.shape[-1]), wte,
                            targets.reshape(-1))
            logits = h.reshape(-1, h.shape[-1]) @ wte.T
            return xent_loss(logits.reshape(-1, wte.shape[0]),
                             targets.reshape(-1))

        with jax.named_scope("fwd"):
            return jax.grad(loss_fn)(params)

    def step(params: LMParams, seed) -> LMParams:
        with jax.named_scope("lm"):
            grads = grads_of(params, seed)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    def step_opt(carry, seed):
        params, state = carry
        with jax.named_scope("lm"):
            grads = grads_of(params, seed)
            with jax.named_scope("optim"):
                return optimizer.update(grads, state, params, lr)

    sharded = _shard(params, mesh, _lm_fsdp_specs())
    check = _vma_check(attn_impl, head_impl)
    if optimizer is None:
        return launch_strided(step, sharded, seeds, mesh, DATA_AXIS,
                              _lm_fsdp_specs(), check_vma=check)
    # zeros_like of the sharded params keeps their shardings: the state
    # enters shard_map already 1/n per device; scalars replicate
    state = optimizer.init(sharded) if opt_state is None else opt_state
    return launch_strided(step_opt, sharded, seeds, mesh, DATA_AXIS,
                          _lm_fsdp_specs(), state=state,
                          state_specs=_lm_state_specs(
                              state, _lm_fsdp_specs()),
                          return_state=return_state, check_vma=check)


# ---------------------------------------------------------------------------
# Vocab-parallel pieces (Megatron-LM): embedding + cross-entropy over the
# model axis, hand-differentiated where nonlinear.


def vp_embed(wte_local: jax.Array, tokens: jax.Array,
             axis: str = MODEL_AXIS) -> jax.Array:
    """Vocab-parallel embedding lookup: each shard resolves only tokens in
    its ``[offset, offset + V/n)`` row range (zeros elsewhere) and one
    ``psum`` completes the rows. Linear, so ``jax.vjp``'s exact transposes
    (psum -> identity, gather -> scatter-add) give each shard the complete
    gradient for its own rows."""
    v_local = wte_local.shape[0]
    offset = axis_index(axis) * v_local
    local = tokens - offset
    in_range = (local >= 0) & (local < v_local)
    rows = wte_local[jnp.clip(local, 0, v_local - 1)]
    return all_reduce(jnp.where(in_range[..., None], rows, 0), axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def vp_xent(logits_local: jax.Array, targets: jax.Array,
            axis: str = MODEL_AXIS) -> jax.Array:
    """Vocab-parallel cross-entropy: ``logits_local [N, V/n]`` is this
    shard's slice of the row; the row max (``pmax``), normalizer
    (``psum`` of local sum-exp), and target logit (``psum`` of the
    in-range pick) each complete with one collective — no shard ever holds
    a full ``[N, V]`` row. Backward is the hand-written
    ``(softmax - onehot) * dy / N`` restricted to the local slice, with no
    collective at all (the residuals are already local)."""
    loss, _ = _vp_xent_fwd(logits_local, targets, axis)
    return loss


def _vp_xent_fwd(logits_local, targets, axis):
    v_local = logits_local.shape[-1]
    offset = axis_index(axis) * v_local
    m = lax.pmax(jnp.max(logits_local, axis=-1, keepdims=True), axis)
    e = jnp.exp(logits_local - m)
    sumexp = all_reduce(jnp.sum(e, axis=-1, keepdims=True), axis)
    lse = jnp.log(sumexp) + m                                   # [N, 1]
    local_t = targets - offset
    in_range = (local_t >= 0) & (local_t < v_local)
    picked = jnp.take_along_axis(
        logits_local, jnp.clip(local_t, 0, v_local - 1)[:, None],
        axis=-1)[:, 0]
    z_t = all_reduce(jnp.where(in_range, picked, 0.0), axis)
    loss = jnp.mean(lse[:, 0] - z_t)
    return loss, (e / sumexp, jnp.clip(local_t, 0, v_local - 1), in_range)


def _vp_xent_bwd(axis, res, dy):
    probs_local, local_t, in_range = res
    n = probs_local.shape[0]
    dz = probs_local * (dy / n)
    dz = dz.at[jnp.arange(n), local_t].add(
        jnp.where(in_range, -dy / n, 0.0))
    return dz, None


vp_xent.defvjp(_vp_xent_fwd, _vp_xent_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def vp_head_xent(h: jax.Array, wte_local: jax.Array, targets: jax.Array,
                 axis: str = MODEL_AXIS,
                 interpret: bool = False) -> jax.Array:
    """Vocab-parallel FUSED head + cross-entropy: ``vp_xent``'s
    collective structure with ``ops.pallas_xent``'s kernels underneath —
    no shard ever materializes even its LOCAL ``[N, V/n]`` logits (the
    oracle path builds and residual-saves them; ~400 MB/shard at the
    bench family shape). Each shard's kernel pass produces merge-ready
    ``(lse_local, tz_local)`` statistics over its own vocab rows; one
    ``pmax`` + two ``psum``s complete the row max, normalizer, and
    target pick — the same three collectives as ``vp_xent``. Backward
    recomputes logit tiles per shard: ``dw`` is shard-complete (its own
    vocab rows), ``dh`` comes back PARTIAL over the model axis — the
    caller's ``_f_gate`` completes it, exactly like the materialized
    path's ``h @ wte_local.T`` transpose."""
    loss, _ = _vp_head_xent_fwd(h, wte_local, targets, axis, interpret)
    return loss


def _vp_head_xent_fwd(h, wte_local, targets, axis, interpret):
    from ..ops.pallas_xent import head_xent_stats
    v_local = wte_local.shape[0]
    t_local = targets - axis_index(axis) * v_local
    lse_l, tz_l = head_xent_stats(h, wte_local, t_local,
                                  interpret=interpret)
    # stable cross-shard logsumexp merge: lse_g = M + log(sum exp(lse-M))
    m = lax.pmax(lse_l, axis)
    lse_g = m + jnp.log(all_reduce(jnp.exp(lse_l - m), axis))
    z_t = all_reduce(tz_l, axis)  # the target lives in exactly one slice
    loss = jnp.mean(lse_g - z_t)
    return loss, (h, wte_local, t_local, lse_g)


def _vp_head_xent_bwd(axis, interpret, res, dy):
    from ..ops.pallas_xent import head_xent_bwd
    h, wte_local, t_local, lse_g = res
    # the kernels compute dz = (exp(z - lse_g) - onehot) / N on this
    # shard's slice: dw complete for its rows, dh a partial sum
    dh, dw = head_xent_bwd(dy, h, wte_local, t_local, lse_g,
                           interpret=interpret)
    return dh, dw, None


vp_head_xent.defvjp(_vp_head_xent_fwd, _vp_head_xent_bwd)


def _make_tp_step(batch_size: int, model_size: int, seq_len: int,
                  h_local: int, vocab: int, lr: float, attn=None,
                  data_axes=(), optimizer=None,
                  head_impl: str | None = None,
                  force_reduce: bool = False,
                  interpret: bool | None = None):
    """One vocab-parallel TP step for one model shard; ``data_axes`` adds
    the orthogonal DDP reduction for the hybrid 2-D mesh (every leaf is a
    partial sum over those axes; LN/positions additionally over the model
    axis — one fused psum per leaf, ``grad_reduce`` on an axis tuple).
    With ``optimizer``, the carry is ``(params, opt_state)`` and the state
    shards exactly like the params (elementwise update — no collective)."""
    b = batch_size // seq_len

    def grads_of(params: LMParams, seed):
        tokens, targets = lm_batch_from_seed(seed, b, seq_len, vocab)
        f = _f_gate(MODEL_AXIS)

        def loss_fn(p: LMParams):
            x = vp_embed(p.wte, tokens) + p.wpe[:seq_len]
            for l in range(p.blocks.w1.shape[0]):
                blk = p.blocks
                x = tp_block(blk.ln1[l], blk.wq[l], blk.wk[l], blk.wv[l],
                             blk.wo[l], blk.ln2[l], blk.w1[l], blk.w2[l],
                             x, h_local, causal=True, attn=attn)
            h = f(layernorm(p.ln_f, x))       # dx from the head: psum
            if head_impl == "fused":
                interp = (jax.default_backend() != "tpu"
                          if interpret is None else interpret)
                return vp_head_xent(
                    h.reshape(-1, model_size), p.wte,
                    targets.reshape(-1), MODEL_AXIS, interp)
            logits_local = h.reshape(-1, model_size) @ p.wte.T
            return vp_xent(logits_local, targets.reshape(-1))

        with jax.named_scope("fwd"):
            grads = jax.grad(loss_fn)(params)
        with jax.named_scope("comm"):
            # wpe and the LN gains saw complete, replicated dx — but the
            # cotangents produced inside the hand-written rules come back
            # typed varying; grad_reduce psums exactly the pending ones.
            # Head/projection/FFN grads are shard-complete on the model
            # axis and reduce only over the data axes (hybrid).
            # force_reduce: vma-off launch (interpret-mode fused head) —
            # unconditional psum, the _make_step contract.
            model_and_data = (MODEL_AXIS,) + data_axes
            grads = grads._replace(
                wpe=grad_reduce(grads.wpe, model_and_data,
                                force=force_reduce),
                ln_f=grad_reduce(grads.ln_f, model_and_data,
                                 force=force_reduce),
                blocks=grads.blocks._replace(
                    ln1=grad_reduce(grads.blocks.ln1, model_and_data,
                                    force=force_reduce),
                    ln2=grad_reduce(grads.blocks.ln2, model_and_data,
                                    force=force_reduce)))
            if data_axes:
                # the four leaves above are already fully reduced (their
                # psum covered the data axes too); under force their
                # second psum would NOT no-op — restore them after the
                # sweep
                done = (grads.wpe, grads.ln_f, grads.blocks.ln1,
                        grads.blocks.ln2)
                grads = jax.tree_util.tree_map(
                    lambda g: grad_reduce(g, data_axes,
                                          force=force_reduce), grads)
                grads = grads._replace(
                    wpe=done[0], ln_f=done[1],
                    blocks=grads.blocks._replace(ln1=done[2],
                                                 ln2=done[3]))
        return grads

    def step(params: LMParams, seed) -> LMParams:
        with jax.named_scope("lm"):
            grads = grads_of(params, seed)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)

    def step_opt(carry, seed):
        params, state = carry
        with jax.named_scope("lm"):
            grads = grads_of(params, seed)
            with jax.named_scope("optim"):
                return optimizer.update(grads, state, params, lr)

    return step if optimizer is None else step_opt


def train_lm_tp(params: LMParams, seeds, batch_size: int, model_size: int,
                mesh, lr: float = LR, *, seq_len: int, n_heads: int,
                attn_impl: str | None = None, optimizer=None,
                opt_state=None, return_state: bool = False,
                head_impl: str | None = None, guard=None,
                guard_state=None, return_guard: bool = False):
    """Megatron-LM TP over the model axis: blocks shard heads/features
    (``tp_block``), ``wte`` shards vocab rows serving both the parallel
    embedding and the tied parallel head, and the loss runs vocab-parallel
    (``vp_xent``). ``wpe``/LN grads replicate (complete ``dx`` on every
    shard, the ``_f_gate`` discipline); ``wte``/block grads are
    shard-complete. Data replicated, as in ``train_transformer_tp``.

    ``optimizer`` threads state sharded exactly like the params
    (``zeros_like`` of the sharded leaves; the elementwise update needs
    no collective) — Megatron's optimizer layout."""
    require_axes(mesh, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    h_local = _validate_tp(params.blocks, n_heads, n)
    _validate_lm(batch_size, seq_len, model_size, n_heads, params)
    check_state_args(optimizer, opt_state, return_state)
    if params.vocab % n:
        raise ValueError(f"vocab={params.vocab} not divisible by "
                         f"model-axis size {n}")
    resolve_head(head_impl)  # shared validation (one accepted set)
    check = _vma_check(attn_impl, head_impl)
    # check_vma/force_reduce follow _vma_check (the fused head runs the
    # vma-off reduction contract on EVERY backend); interpret is a
    # separate, backend-only decision — the fused head must still run
    # the COMPILED kernels on TPU. interpret=None lets _make_tp_step's
    # backend fallback decide (ADVICE r4: tying it to `not check` ran
    # the Pallas head in interpret mode on real TPU).
    step = _make_tp_step(batch_size, model_size, seq_len, h_local,
                         params.vocab, lr, resolve_attn(attn_impl),
                         optimizer=optimizer, head_impl=head_impl,
                         force_reduce=not check, interpret=None)
    from ..runtime.guardrails import check_guard_args
    check_guard_args(guard, guard_state, return_guard)
    gkw = ({} if guard is None
           else dict(guard=guard, guard_state=guard_state))
    sharded = _shard(params, mesh, _lm_tp_specs())
    if optimizer is None:
        out = launch(step, sharded, jnp.asarray(seeds), mesh,
                     param_specs=_lm_tp_specs(), seed_spec=P(),
                     check_vma=check, **gkw)
    else:
        # zeros_like of sharded params keeps their shardings; scalar
        # bookkeeping (step counts) replicates
        state = optimizer.init(sharded) if opt_state is None else opt_state
        out = launch(step, sharded, jnp.asarray(seeds), mesh,
                     param_specs=_lm_tp_specs(), seed_spec=P(),
                     state=state,
                     state_specs=_lm_state_specs(state, _lm_tp_specs()),
                     return_state=return_state, check_vma=check, **gkw)
    if guard is not None and not return_guard:
        out = out[0]
    return out


def tp_generate(params: LMParams, prompt, n_new: int, mesh, *,
                n_heads: int, use_rope: bool = False) -> jax.Array:
    """Megatron-sharded greedy decode: the KV cache shards over **heads**
    on the model axis (each shard caches and attends its own ``H/n``
    heads — the inference memory win: cache bytes per chip drop 1/n),
    the tied head scores **vocab-parallel** (each shard's ``V/n``
    columns), and the global argmax completes with one tiny
    ``all_gather`` of per-shard ``(max, index)`` pairs per position.
    One jitted ``shard_map`` scan decodes the whole batch; the result is
    replicated. Differential-pinned to the single-device ``generate``.
    GQA models compose: the cache is sized by each shard's LOCAL kv
    heads (``KV % n`` validated), so the inference memory win multiplies
    with the group factor. The compiled program is cached on the static
    decode config (``_tp_decode_program``), so repeat decodes don't
    re-trace."""
    return _tp_decode(params, prompt, n_new, mesh, n_heads, use_rope,
                      temperature=0.0, seed=0)


def tp_sample(params: LMParams, prompt, n_new: int, mesh, *,
              n_heads: int, temperature: float = 1.0, seed: int = 0,
              use_rope: bool = False) -> jax.Array:
    """Stochastic Megatron-sharded decode: ``tp_generate``'s program with
    the pick swapped for a Gumbel-max categorical draw from
    ``softmax(logits / temperature)`` — an EXACT sample computed without
    ever materializing softmax probabilities across the vocab-parallel
    shards (each shard perturbs its local logits with iid Gumbel noise
    keyed on ``(seed, position, shard)``; the greedy path's tiny
    ``(max, index)`` all_gather completes the draw). Deterministic given
    ``seed``; draws differ from the single-device ``sample``'s (a
    different noise stream), but the DISTRIBUTION is identical."""
    if temperature <= 0:
        raise ValueError(f"temperature must be > 0, got {temperature} "
                         "(use tp_generate for greedy decode)")
    return _tp_decode(params, prompt, n_new, mesh, n_heads, use_rope,
                      temperature=float(temperature), seed=seed)


def tp_decode_specs() -> LMParams:
    """The Megatron decode layout's partition specs (vocab-sharded
    ``wte``, head-sharded blocks, replicated positions/LNs) — one
    definition shared by ``tp_generate``/``tp_sample`` and the serving
    engine (``decode/engine.py``), so the two decode paths can never
    drift onto different layouts."""
    return _lm_tp_specs()


def tp_shard_params(params: LMParams, mesh) -> LMParams:
    """Lay the LM params out in the Megatron decode layout (vocab/head
    sharded) ONCE. ``tp_generate``/``tp_sample`` and the decode engine
    detect the layout and skip their per-call reshard copy, so repeat
    decodes (serving loops, ``bench_decode``) pay neither a retrace
    (the program is cached) nor a per-call host-side param copy."""
    require_axes(mesh, MODEL_AXIS)
    if _tp_sharded_already(params, mesh):
        return params
    return _shard(params, mesh, _lm_tp_specs())


def _tp_sharded_already(params: LMParams, mesh) -> bool:
    """True iff every param leaf already carries the exact decode
    NamedSharding (as produced by ``tp_shard_params``)."""
    specs = jax.tree_util.tree_leaves(
        _lm_tp_specs(), is_leaf=lambda v: isinstance(v, P))
    leaves = jax.tree_util.tree_leaves(params)
    return len(leaves) == len(specs) and all(
        getattr(a, "sharding", None) == NamedSharding(mesh, s)
        for a, s in zip(leaves, specs))


def _tp_decode(params, prompt, n_new, mesh, n_heads, use_rope,
               temperature, seed):
    """Shared validate-and-launch for the TP decode pair; the seed is a
    RUNTIME operand (new seeds draw new continuations from the SAME
    compiled program — no retrace, no cache thrash). Params already in
    the ``tp_shard_params`` layout skip the reshard copy."""
    require_axes(mesh, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    _validate_tp(params.blocks, n_heads, n)  # heads/kv/ffn divisibility
    if params.vocab % n:
        raise ValueError(f"vocab={params.vocab} not divisible by "
                         f"model-axis size {n}")
    fn = _tp_decode_program(mesh, n_new, n_heads, params.vocab // n,
                            params.max_seq_len,
                            params.d_model // n_heads, use_rope,
                            temperature=temperature)
    sharded = (params if _tp_sharded_already(params, mesh)
               else _shard(params, mesh, _lm_tp_specs()))
    return fn(sharded, jnp.asarray(prompt), jnp.int32(seed))


@functools.lru_cache(maxsize=16)
def _tp_decode_program(mesh, n_new: int, n_heads: int, v_local: int,
                       max_t: int, dh: int, use_rope: bool,
                       temperature: float = 0.0):
    """Build (once per static decode config) the jitted shard_map decode
    program ``(sharded_params, prompt) -> tokens``. jax.jit's own cache
    then handles shape-polymorphic re-traces; callers timing repeat
    decodes (bench_decode) hit the compiled program directly.
    ``temperature > 0`` switches the pick from greedy to an EXACT
    categorical sample via the Gumbel-max trick: each shard perturbs its
    local ``logits/T`` with iid Gumbel noise (key folded on
    ``(seed, position, shard)``) and the SAME tiny ``(max, index)``
    all_gather that completes the greedy argmax then completes the
    sample — softmax probabilities never materialize, sharded or not."""
    from ..models.lm import KVCache, decode_loop

    def decode_step_tp(p: LMParams, cache: KVCache, token, pos):
        from ..models.lm import cached_attn_step
        blk = p.blocks
        x = vp_embed(p.wte, token) + p.wpe[pos]             # [B, d]
        new_k, new_v = cache.k, cache.v
        for l in range(blk.w1.shape[0]):
            y, new_k, new_v = cached_attn_step(
                blk.ln1[l], blk.wq[l], blk.wk[l], blk.wv[l], blk.wo[l],
                new_k, new_v, l, x, pos, use_rope)          # local heads
            x = x + all_reduce(y, MODEL_AXIS)                # Megatron g
            h = layernorm(blk.ln2[l], x)
            x = x + all_reduce(
                jnp.maximum(h @ blk.w1[l].T, 0.0) @ blk.w2[l].T,
                MODEL_AXIS)                                  # Megatron g
        h = layernorm(p.ln_f, x)
        logits_local = h @ p.wte.T                           # [B, V/n]
        return logits_local, KVCache(new_k, new_v)

    def pick_global(logits_local, pos, seed):
        """argmax over the sharded vocab: each shard offers its local
        ``(max value, global index)`` pair, packed into ONE tiny
        ``[2, B]`` all_gather per position. The pack rides in f32
        regardless of the params' dtype: a bf16 lane would round the
        index (8-bit mantissa); f32 is exact while vocab < 2^24.
        With ``temperature > 0`` the local values are Gumbel-perturbed
        first (iid per global vocab index: the key folds in the shard),
        so the global argmax IS a categorical draw from softmax(z/T)."""
        z = logits_local
        if temperature > 0.0:
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), pos),
                axis_index(MODEL_AXIS))
            z = (z.astype(jnp.float32) / temperature
                 + jax.random.gumbel(key, z.shape, jnp.float32))
        local_best = jnp.argmax(z, axis=-1)                  # [B]
        local_val = jnp.take_along_axis(
            z, local_best[:, None], axis=-1)[:, 0]
        offset = axis_index(MODEL_AXIS) * v_local
        packed = jnp.stack([
            local_val.astype(jnp.float32),
            (local_best + offset).astype(jnp.float32)])      # [2, B]
        g = all_gather(packed[None], MODEL_AXIS, dim=0)      # [n, 2, B]
        win = jnp.argmax(g[:, 0, :], axis=0)                 # [B]
        return jnp.take_along_axis(
            g[:, 1, :], win[None], axis=0)[0].astype(jnp.int32)

    def run(p: LMParams, prompt, seed):
        b = prompt.shape[0]
        # cache sized by the shard's LOCAL kv heads (wk's sharded row
        # count / dh): GQA shrinks it by the group factor, exactly as in
        # the single-device decode; contiguous head sharding keeps each
        # shard's q heads grouped with its own kv heads (KV % n == 0,
        # validated by _validate_tp)
        kv_local = p.blocks.wk.shape[1] // dh
        cache = KVCache(
            k=jnp.zeros((p.blocks.w1.shape[0], b, kv_local, max_t, dh),
                        p.wpe.dtype),
            v=jnp.zeros((p.blocks.w1.shape[0], b, kv_local, max_t, dh),
                        p.wpe.dtype))
        return decode_loop(
            lambda cache, token, pos: decode_step_tp(p, cache, token, pos),
            cache, prompt, n_new, max_t,
            lambda z, pos: pick_global(z, pos, seed))

    return jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(_lm_tp_specs(), P(), P()),
        out_specs=P(), check_vma=False))


def _lm_state_specs(state, specs):
    """Optimizer-state specs for a sharded-param layout: param-shaped
    subtrees (momentum velocities, Adam moments — ``LMParams`` instances)
    shard like the params (``specs`` — pass the caller's own layout);
    scalar bookkeeping (step counters) replicates."""

    def rec(s):
        if isinstance(s, LMParams):
            return specs
        if hasattr(s, "_fields"):                 # e.g. AdamState
            return type(s)(*(rec(x) for x in s))
        if isinstance(s, tuple):                  # scheduled-wrapper pairs
            return tuple(rec(x) for x in s)
        return P()

    return rec(state)


def train_lm_hybrid(params: LMParams, seeds, batch_size: int,
                    model_size: int, mesh, lr: float = LR, *, seq_len: int,
                    n_heads: int, attn_impl: str | None = None) -> LMParams:
    """Hybrid DDP x vocab-parallel TP on a 2-D ``(data, model)`` mesh:
    TP's per-block and vocab collectives ride the ``"model"`` axis inside
    each replica, DDP's weight-grad psum rides the orthogonal ``"data"``
    axis once per step (strided seeds, SUM, unscaled LR —
    ``train_ffns.py:182, :165`` semantics)."""
    require_axes(mesh, DATA_AXIS, MODEL_AXIS)
    n = mesh.shape[MODEL_AXIS]
    h_local = _validate_tp(params.blocks, n_heads, n)
    _validate_lm(batch_size, seq_len, model_size, n_heads, params)
    if params.vocab % n:
        raise ValueError(f"vocab={params.vocab} not divisible by "
                         f"model-axis size {n}")
    step = _make_tp_step(batch_size, model_size, seq_len, h_local,
                         params.vocab, lr, resolve_attn(attn_impl),
                         data_axes=(DATA_AXIS,))
    return launch_strided(step, _shard(params, mesh, _lm_tp_specs()),
                          seeds, mesh, DATA_AXIS, _lm_tp_specs())


def train_lm_seq(params: LMParams, seeds, batch_size: int, model_size: int,
                 mesh, lr: float = LR, *, seq_len: int, n_heads: int,
                 seq_impl: str = "ring",
                 attn_impl: str | None = None,
                 head_impl: str | None = None) -> LMParams:
    """Long-context LM training: the sequence dim sharded over the
    ``"seq"`` axis, attention crossing shards via the hand-written ring
    (or Ulysses), the real objective computed per token block.

    Everything token-pointwise — embedding lookup, positions, LNs,
    projections, FFN, the tied head, and the cross-entropy itself — runs
    on the shard's own ``T/n`` tokens. The global loss is the mean over
    all tokens, i.e. the mean of the (equal-sized) shard means scaled by
    ``1/n``; scaling each shard's local loss by ``1/n`` before ``psum``-ing
    the weight grads reproduces the single-device gradient exactly
    (pinned by the differential test). On a 2-D ``(data, seq)`` mesh the
    seed schedule additionally shards strided over ``data`` and the same
    psum rides both axes.

    ``attn_impl="flash"`` fuses the block compute (per ring hop / per
    Ulysses-local head) onto the Pallas flash kernels — the long-context
    path end to end: ICI ring across chips, online-softmax tiling in
    VMEM within each. ``head_impl="fused"`` does the same for the tied
    head + xent on the shard's own token block
    (``ops/pallas_xent.py``)."""
    from .sequence import resolve_seq_attn
    require_axes(mesh, SEQ_AXIS)
    n = mesh.shape[SEQ_AXIS]
    dp = dict(mesh.shape).get(DATA_AXIS, 1)
    _validate_lm(batch_size, seq_len, model_size, n_heads, params)
    attn = resolve_seq_attn(seq_impl, n, n_heads, seq_len,
                            attn_impl=attn_impl,
                            interpret=jax.default_backend() != "tpu")
    t_local = seq_len // n
    b = batch_size // seq_len
    vocab = params.vocab
    head = resolve_head(head_impl)
    check = _vma_check(attn_impl, head_impl)

    def step(params: LMParams, seed) -> LMParams:
        tokens, targets = lm_batch_from_seed(seed, b, seq_len, vocab)
        r = axis_index(SEQ_AXIS)
        # this shard's token block (full batch regenerated from the seed,
        # so ring causality over global positions stays exact)
        tokens, targets = (
            lax.dynamic_slice_in_dim(t, r * t_local, t_local, 1)
            for t in (tokens, targets))

        def loss_fn(p: LMParams):
            x = p.wte[tokens] + lax.dynamic_slice_in_dim(
                p.wpe, r * t_local, t_local, 0)
            x = transformer_fwd(p.blocks, x, n_heads, causal=True,
                                attn=attn)
            h = layernorm(p.ln_f, x)
            if head is not None:
                # local mean / n == this shard's share of the global mean
                return head(h.reshape(-1, h.shape[-1]), p.wte,
                            targets.reshape(-1)) / n
            logits = h @ p.wte.T
            # local mean / n == this shard's share of the global mean
            return xent_loss(logits.reshape(-1, vocab),
                             targets.reshape(-1)) / n

        with jax.named_scope("lm"):
            with jax.named_scope("fwd"):
                grads = jax.grad(loss_fn)(params)
            axes = (SEQ_AXIS, DATA_AXIS) if dp > 1 else (SEQ_AXIS,)
            with jax.named_scope("comm"):
                # vma-off (interpret-mode flash/fused head): force the
                # psum — grad_reduce would silently no-op on the partial
                # cotangents
                grads = jax.tree_util.tree_map(
                    lambda g: grad_reduce(g, axes, force=not check), grads)
            with jax.named_scope("optim"):
                return sgd(params, grads, lr)
    if dp > 1:
        return launch_strided(step, clone_params(params), seeds, mesh,
                              DATA_AXIS, P(), check_vma=check)
    return launch(step, clone_params(params), jnp.asarray(seeds), mesh,
                  param_specs=P(), seed_spec=P(), check_vma=check)
