"""HLO introspection: verify that the hand-rolled communication is exactly
what we wrote.

The reference's pedagogical point is *which* collectives fire *where*
(per-layer async all-reduce in DDP, gather/scatter pairs in FSDP, one
all-reduce per direction in TP). On TPU the program is compiled, so the
ground truth is the lowered IR: these helpers count collective ops in a
jitted function's StableHLO so tests can pin the communication schedule —
the comms-count analogue of the reference's printed-handle discipline. The
optimized-HLO variants detect the async ``-start``/``-done`` split that
realizes compute/comm overlap (the role of ``async_op=True`` +
``handle.wait()``, ``train_ffns.py:165-170``; overlap the reference never
achieved for reduce-scatter, ``:14``).
"""

from __future__ import annotations

import re
from collections import Counter

import jax

# StableHLO op names for the collectives we hand-roll
COLLECTIVE_OPS = ("all_reduce", "all_gather", "reduce_scatter",
                  "collective_permute", "all_to_all")


def lowered_text(fn, *args, **kwargs) -> str:
    """StableHLO of ``fn`` lowered (pre-optimization) for the given args."""
    return jax.jit(fn).lower(*args, **kwargs).as_text()


def count_collectives(fn, *args, **kwargs) -> Counter:
    """Occurrences of each collective op in the lowered StableHLO."""
    text = lowered_text(fn, *args, **kwargs)
    return Counter({op: len(re.findall(rf"stablehlo\.{op}\b|\"{op}", text))
                    for op in COLLECTIVE_OPS})


def compiled_text(fn, *args, **kwargs) -> str:
    """Optimized backend HLO (post-XLA-passes)."""
    return jax.jit(fn).lower(*args, **kwargs).compile().as_text()


def async_collective_pairs(fn, *args, **kwargs) -> Counter:
    """Counts of async-split collectives in the optimized HLO — nonzero
    means XLA split the collective for compute/comm overlap.

    Two spellings exist: dedicated opcodes (``all-reduce-start``,
    ``all-gather-start``, ``collective-permute-start``) and the generic
    wrapper ``async-start`` whose operand names the collective (the only
    form reduce-scatter gets — XLA has no ``reduce-scatter-start`` opcode).
    Both are counted."""
    text = compiled_text(fn, *args, **kwargs)
    counts = Counter()
    for op in COLLECTIVE_OPS:
        dashed = op.replace("_", "-")
        n = 0
        for line in text.splitlines():
            # count *defining* start lines only. The `-done` line names the
            # `-start` value as its operand (and would double-count), so it
            # is excluded first; result types may be tuples, so the opcode
            # is matched by its trailing `(` rather than by line position.
            if "-done(" in line:
                continue
            if (re.search(rf"{dashed}-start\(", line)
                    or ("async-start(" in line and dashed in line)):
                n += 1
        counts[op] = n
    return counts
