"""HLO introspection: verify that the hand-rolled communication is exactly
what we wrote.

The reference's pedagogical point is *which* collectives fire *where*
(per-layer async all-reduce in DDP, gather/scatter pairs in FSDP, one
all-reduce per direction in TP). On TPU the program is compiled, so the
ground truth is the lowered IR: these helpers count collective ops in a
jitted function's StableHLO so tests can pin the communication schedule —
the comms-count analogue of the reference's printed-handle discipline. The
optimized-HLO variants detect the async ``-start``/``-done`` split that
realizes compute/comm overlap (the role of ``async_op=True`` +
``handle.wait()``, ``train_ffns.py:165-170``; overlap the reference never
achieved for reduce-scatter, ``:14``).
"""

from __future__ import annotations

import re
from collections import Counter

import jax

# StableHLO op names for the collectives we hand-roll
COLLECTIVE_OPS = ("all_reduce", "all_gather", "reduce_scatter",
                  "collective_permute", "all_to_all")


def lowered_text(fn, *args, **kwargs) -> str:
    """StableHLO of ``fn`` lowered (pre-optimization) for the given args."""
    return jax.jit(fn).lower(*args, **kwargs).as_text()


def count_collectives_text(text: str) -> Counter:
    """Occurrences of each collective op in already-lowered StableHLO
    text — the text-level core of ``count_collectives``, shared with
    callers that hold a lowering already (``runtime.telemetry.StepReport``
    lowers once and feeds both this count and the compile)."""
    return Counter({op: len(re.findall(rf"stablehlo\.{op}\b|\"{op}", text))
                    for op in COLLECTIVE_OPS})


def count_collectives(fn, *args, **kwargs) -> Counter:
    """Occurrences of each collective op in the lowered StableHLO."""
    return count_collectives_text(lowered_text(fn, *args, **kwargs))


def compiled_text(fn, *args, **kwargs) -> str:
    """Optimized backend HLO (post-XLA-passes)."""
    return jax.jit(fn).lower(*args, **kwargs).compile().as_text()


def count_async_pairs(text: str) -> Counter:
    """Counts of async-split collectives in optimized HLO text — nonzero
    means XLA split the collective for compute/comm overlap.

    Three spellings exist across backends/generations: dedicated opcodes
    (``all-reduce-start``, ``all-gather-start``,
    ``collective-permute-start``), the generic wrapper ``async-start``
    whose operand names the collective, and the TPU codegen form
    ``async-collective-start`` (counted under the ``async_collective``
    key — the wrapped op is a custom-call whose kind isn't named on the
    defining line). Only *defining* lines are counted: the ``-done`` line
    names the ``-start`` value as its operand and would double-count."""
    counts = Counter()
    for line in text.splitlines():
        if "=" not in line:
            continue
        lhs, rhs = line.split("=", 1)
        # TPU codegen form: the async start is a *fusion* whose VALUE NAME
        # is %async-collective-start[.N] — there is no dedicated opcode on
        # the line, so this one is detected by name (suffixes allowed)
        if re.search(r"%async-collective-start[.\w]*\s*$", lhs.strip()):
            counts["async_collective"] += 1
            continue
        # dedicated / generic opcodes: match the OPCODE token (directly
        # followed by "(") on the right-hand side — rename-proof, and a
        # `-done` line references the `-start` value only paren-free
        if re.search(r"\basync-start\(", rhs):
            for op in COLLECTIVE_OPS:
                if op.replace("_", "-") in rhs:
                    counts[op] += 1
                    break
        else:
            for op in COLLECTIVE_OPS:
                if re.search(rf"\b{op.replace('_', '-')}-start\(", rhs):
                    counts[op] += 1
                    break
    return counts


def async_collective_pairs(fn, *args, **kwargs) -> Counter:
    """``count_async_pairs`` of ``fn``'s optimized HLO on the current
    backend (compile-and-inspect; see ``count_async_pairs`` for keys)."""
    return count_async_pairs(compiled_text(fn, *args, **kwargs))
