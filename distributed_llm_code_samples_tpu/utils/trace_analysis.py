"""Chrome-trace span analysis, keyed on the framework's named scopes.

``bench_trace.py`` grew the first span parser (comm-vs-compute interval
intersection over a captured Perfetto/chrome trace); this module lifts
it into an importable library and extends it with the **named-scope
region map**: every parallel strategy annotates its step with
``jax.named_scope`` regions (see ``SCOPES`` below), those names flow
into XLA op metadata and — on hardware traces — into the span names the
profiler records, so a trace can be folded per region (how long did
``fsdp``'s ``comm`` spend vs its ``fwd``?) with plain substring
matching instead of op-name archaeology.

Naming map (the contract tests/test_telemetry.py pins against compiled
HLO): each strategy wraps its step in a scope named after the strategy,
with nested ``fwd`` / ``bwd`` / ``comm`` / ``optim`` regions. Autodiff
strategies (the LM/MoE families) trace forward and derive the backward,
so their ``fwd`` scope also tags the transposed backward ops — their
region list omits ``bwd`` rather than pretend a boundary exists.
"""

from __future__ import annotations

import glob
import gzip
import json
import os

# strategy -> the named-scope region paths its compiled step carries
# (each appears verbatim in compiled-HLO op metadata; presence is
# contract-tested per strategy against the REAL launched program in
# tests/test_telemetry.py). Nested paths record structure: DDP's grad
# psum fires inside the backward walk (ddp/bwd/comm), FSDP gathers in
# both directions (fsdp/{fwd,bwd}/comm). The pipeline's stage compute
# runs inside lax.cond branches whose sub-computations don't inherit
# the outer pp scope, so its fwd/bwd regions are unprefixed; the ring
# transfers and update are top-level (pp/comm, pp/optim). The LM family
# differentiates with jax.grad (one trace for forward + transpose), so
# its fwd region covers both and no bwd region exists.
SCOPES = {
    "single": ("single/fwd", "single/bwd", "single/optim"),
    "ddp": ("ddp/fwd", "ddp/bwd", "ddp/bwd/comm", "ddp/optim"),
    "fsdp": ("fsdp/fwd", "fsdp/bwd", "fsdp/fwd/comm", "fsdp/bwd/comm",
             "fsdp/optim"),
    "tp": ("tp/fwd", "tp/bwd", "tp/fwd/comm", "tp/bwd/comm", "tp/optim"),
    "hybrid": ("hybrid/fwd", "hybrid/bwd", "hybrid/fwd/comm",
               "hybrid/optim"),
    "zero1": ("zero1/fwd", "zero1/bwd", "zero1/comm", "zero1/optim"),
    "pp": ("pp/", "fwd", "bwd", "pp/comm", "pp/optim"),
    "seq": ("seq/fwd", "seq/bwd", "seq/comm", "seq/optim"),
    "ep": ("ep/fwd", "ep/bwd", "ep/comm", "ep/optim"),
    "tf": ("tf/fwd", "tf/bwd", "tf/optim"),
    "lm": ("lm/fwd", "lm/comm", "lm/optim"),
    "moe_lm": ("moe_lm/fwd", "moe_lm/comm", "moe_lm/optim"),
    "moe_tf": ("moe_tf/fwd", "moe_tf/bwd", "moe_tf/comm",
               "moe_tf/optim"),
    # serving cost attribution (round 11, decode/engine.py): the decode
    # engine's two compiled program kinds, split by the DECODE
    # roofline's own terms — "gather" the paged-KV block read (+int8
    # dequant), "requant" the KV write (the int8 read-modify-requantize
    # proper; at f32/bf16 it tags the plain scatter, so the region
    # reads near zero there), "attn" the score+AV math, "head" the
    # final LN + tied head (+ the TP logits all_gather), "sample" the
    # fused in-graph pick. Serving steps have no optimizer, so these
    # entries carry no "optim" region (the training-side four-role
    # structure does not apply).
    "decode": ("decode/gather", "decode/attn", "decode/head",
               "decode/sample", "decode/requant"),
    "prefill": ("prefill/gather", "prefill/attn", "prefill/head",
                "prefill/sample", "prefill/requant"),
}

# the SCOPES keys that name SERVING programs (no optimizer region; the
# per-strategy four-role contract below applies to the training keys)
SERVING_SCOPES = ("decode", "prefill")

# span-name keywords (lowercased substring match) — the bench_trace.py
# classifiers, shared
COMM_KEYWORDS = ("all-gather", "all_gather", "reduce-scatter",
                 "reduce_scatter", "all-reduce", "all_reduce",
                 "copy-start", "collective-permute", "dma")
COMPUTE_KEYWORDS = ("fusion", "dot", "convolution", "matmul")


def load_spans(trace_dir: str):
    """``(trace_file, spans)``: all complete ("X"-phase, named) events
    from the NEWEST chrome trace under ``trace_dir`` (recursive;
    ``jax.profiler.trace`` nests runs in timestamped subdirs).
    ``(None, [])`` when no trace exists."""
    files = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not files:
        return None, []
    with gzip.open(files[-1], "rt") as f:
        events = json.load(f).get("traceEvents", [])
    return files[-1], [e for e in events
                       if e.get("ph") == "X" and e.get("name")]


def classify_span(name: str) -> str | None:
    """"comm" / "compute" / None for one span name."""
    low = name.lower()
    if any(k in low for k in COMM_KEYWORDS):
        return "comm"
    if any(k in low for k in COMPUTE_KEYWORDS):
        return "compute"
    return None


def comm_compute_overlap(spans) -> tuple[int, int, float]:
    """``(n_comm, n_compute, overlap_us)``: per-lane comm-vs-compute
    interval intersection — observed overlap is the measured form of
    the async-pair proof (``utils/hlo.count_async_pairs``).

    ``overlap_us`` sums the intersection of every (comm, compute) pair
    in the same lane — pair multiplicity included, like the original
    bench_trace fold. Computed by an event sweep (the integral of
    ``active_comm(t) * active_compute(t)`` equals the pairwise sum), so
    real hardware traces with 1e4-1e5 spans fold in O(n log n) instead
    of the lifted loop's O(n_comm * n_compute)."""
    from collections import defaultdict

    events: dict = defaultdict(list)  # pid -> (t, which, +-1)
    n_comm = n_compute = 0
    for e in spans:
        cls = classify_span(e["name"])
        if cls is None:
            continue
        t0, t1 = e["ts"], e["ts"] + e.get("dur", 0)
        which = 0 if cls == "comm" else 1
        n_comm += which == 0
        n_compute += which == 1
        events[e.get("pid")].append((t0, which, 1))
        events[e.get("pid")].append((t1, which, -1))
    overlap_us = 0.0
    for evs in events.values():
        evs.sort()
        active = [0, 0]
        prev_t = None
        for t, which, d in evs:
            if prev_t is not None and t > prev_t:
                overlap_us += (t - prev_t) * active[0] * active[1]
            active[which] += d
            prev_t = t
    return n_comm, n_compute, overlap_us


def strategy_scope_key(trainer_name: str | None) -> str | None:
    """Map a trainer function name (the ``strategy`` field run meta
    records carry, e.g. ``train_lm_tp``) to its ``SCOPES`` key, or None
    when unknown."""
    if not trainer_name:
        return None
    name = trainer_name.removeprefix("train_")
    if name in SCOPES:
        return name
    # longest/most-specific prefixes first: *_seq trainers scope "seq"
    # (transformer_seq) or "lm" (lm_seq — the LM wraps its own step),
    # *_pp trainers all scope "pp"
    for prefix, key in (("moe_lm", "moe_lm"), ("moe_transformer", "moe_tf"),
                        ("moe", "ep"), ("lm_pp", "pp"),
                        ("transformer_pp", "pp"), ("pp", "pp"),
                        ("transformer_seq", "seq"),
                        ("lm", "lm"), ("transformer", "tf"),
                        ("ddp_zero1", "zero1"), ("tp", "tp")):
        if name.startswith(prefix):
            return key
    return None


def scope_totals(spans, strategy: str | None = None) -> dict[str, float]:
    """Total span time (us) per named-scope region.

    With ``strategy`` given, buckets are that strategy's ``SCOPES``
    entries; otherwise every strategy's PREFIXED regions are scanned —
    the pipeline's unprefixed ``fwd``/``bwd`` (a lax.cond scoping
    artifact, see SCOPES) are excluded there because they substring-
    match every strategy's scoped spans and would double-count. A span
    counts toward a region when the region name appears in the span
    name (XLA op metadata carries the full scope path; profilers that
    surface ``tf_op``/op_name annotations put it in the span name)."""
    regions = (SCOPES.get(strategy, ()) if strategy is not None
               else tuple({r for rs in SCOPES.values() for r in rs
                           if "/" in r}))
    totals = {r: 0.0 for r in regions}
    for e in spans:
        name = e["name"]
        args = e.get("args") or {}
        # profilers stash the op path under args too (tf_op / long_name)
        haystack = " ".join([name, str(args.get("tf_op", "")),
                             str(args.get("long_name", ""))])
        for r in regions:
            if r in haystack:
                totals[r] += e.get("dur", 0)
    return totals


def overlap_payload(spans, trace_file: str | None = None) -> dict:
    """The shared span-inventory + overlap fold (bench_trace's artifact
    core and the report tool's profile section). Takes already-loaded
    ``spans`` so callers that also need ``scope_totals`` parse the
    (potentially hundreds-of-MB) trace exactly once."""
    n_comm, n_compute, overlap_us = comm_compute_overlap(spans)
    return {
        "trace_file": trace_file,
        "n_spans": len(spans),
        "comm_spans": n_comm,
        "compute_spans": n_compute,
        "overlap_us": round(overlap_us, 1),
    }
