"""Cross-cutting utilities: profiling/tracing, HLO comms introspection,
per-device memory accounting, chrome-trace span analysis keyed on the
framework's named scopes."""

from .profiling import trace, profile_rank_0, timed
from .hlo import (lowered_text, count_collectives, count_collectives_text,
                  compiled_text, async_collective_pairs, count_async_pairs,
                  COLLECTIVE_OPS)
from .memory import compiled_memory, params_bytes_per_device
from .trace_analysis import (SCOPES, comm_compute_overlap, load_spans,
                             overlap_payload, scope_totals)

__all__ = [
    "trace", "profile_rank_0", "timed",
    "lowered_text", "count_collectives", "count_collectives_text",
    "compiled_text", "async_collective_pairs", "count_async_pairs",
    "COLLECTIVE_OPS",
    "compiled_memory", "params_bytes_per_device",
    "SCOPES", "comm_compute_overlap", "load_spans", "overlap_payload",
    "scope_totals",
]
