"""Cross-cutting utilities: profiling/tracing, HLO comms introspection,
per-device memory accounting."""

from .profiling import trace, profile_rank_0, timed
from .hlo import (lowered_text, count_collectives, compiled_text,
                  async_collective_pairs, count_async_pairs,
                  COLLECTIVE_OPS)
from .memory import compiled_memory, params_bytes_per_device

__all__ = [
    "trace", "profile_rank_0", "timed",
    "lowered_text", "count_collectives", "compiled_text",
    "async_collective_pairs", "count_async_pairs", "COLLECTIVE_OPS",
    "compiled_memory", "params_bytes_per_device",
]
