"""Per-device memory accounting — the sharding-actually-shards check.

The reference's only memory tooling is a param-count/GB printout
(``train_ffns.py:363-366``) plus a falsifiable capability demo: a ~4.3B
fp32 model must OOM under DDP and train under FSDP (``README.md``,
``train_ffns.py:8-10``). On TPU the compiler knows the per-device
footprint *before* running: these helpers read the compiled memory
analysis so the DDP-vs-FSDP capability claim becomes a unit test instead
of a 4-GPU OOM experiment (v5e budget: 16 GB HBM/chip).
"""

from __future__ import annotations

from typing import Any

import jax


def compiled_memory(fn, *args, **kwargs) -> dict[str, Any] | None:
    """Compiled memory analysis (bytes, per device) of jitted ``fn``.

    Returns None when the backend doesn't implement memory analysis.
    """
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        m = compiled.memory_analysis()
    except Exception:
        return None
    if m is None:
        return None
    return {
        "argument_bytes": m.argument_size_in_bytes,
        "output_bytes": m.output_size_in_bytes,
        "temp_bytes": m.temp_size_in_bytes,
        "peak_bytes": getattr(m, "peak_memory_in_bytes", None),
    }


def params_bytes_per_device(params) -> int:
    """Actual bytes this process's devices hold for a (possibly sharded)
    param pytree, using the largest per-device sum across devices."""
    per_device: dict[Any, int] = {}
    for leaf in jax.tree_util.tree_leaves(params):
        for shard in leaf.addressable_shards:
            per_device[shard.device] = (per_device.get(shard.device, 0) +
                                        shard.data.nbytes)
    return max(per_device.values()) if per_device else 0
