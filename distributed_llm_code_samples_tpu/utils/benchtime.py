"""Shared on-chip timing discipline for the bench scripts.

Load-bearing on this hardware (measured, round 2): the axon relay does
NOT make ``block_until_ready`` wait for chained per-step dispatches, so
every bench (a) runs its whole schedule as ONE compiled program
(``lax.scan`` over steps) and (b) forces completion through a dependent
scalar readback. All outputs of a program materialize together, so
reading any ONE leaf fences the program — and one readback keeps the
~70ms relay round-trip out of the comparison. This module is the single
home of that methodology so bench.py / bench_moe.py / bench_decode.py
cannot drift apart.
"""

from __future__ import annotations

import time

import jax


def sync(tree) -> float:
    """Force completion of ``tree``'s program via ONE scalar readback."""
    return float(jax.tree_util.tree_leaves(tree)[0].sum())


def steps_per_sec(run_fn, p0, warm, timed, reps: int, steps: int) -> float:
    """Best-of-``reps`` steps/sec of ``run_fn(params, seeds)``: one warm
    call (compile) on the ``warm`` schedule, then ``reps`` timed calls on
    ``timed`` (same length — the jitted run caches on the scan trip
    count), each fenced by ``sync``. Best-of because the relay adds
    run-to-run jitter (~±1.5%)."""
    out = run_fn(p0, warm)
    sync(out)
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run_fn(out, timed)
        sync(out)
        best = max(best, steps / (time.perf_counter() - t0))
    return best
