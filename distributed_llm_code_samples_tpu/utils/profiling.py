"""Tracing / profiling — parity with ``torch_profile_rank_0``.

The reference wraps a worker in ``torch.profiler.profile`` and exports a
chrome trace on rank 0 (``train_ffns.py:129-141``), with a noted pickling
hack to survive ``mp.spawn``. The TPU equivalent is ``jax.profiler.trace``
(Perfetto/TensorBoard format) — and SPMD removes the pickling problem
entirely: the decorator below is an ordinary closure because there is no
per-GPU process spawn to serialize through.
"""

from __future__ import annotations

import functools
import os
import time
from contextlib import contextmanager

import jax


@contextmanager
def trace(log_dir: str):
    """Profile a region to ``log_dir`` (Perfetto/TensorBoard format)."""
    with jax.profiler.trace(log_dir):
        yield


def profile_rank_0(log_dir: str = "trace_profiler"):
    """Decorator: profile the wrapped call, exporting only on process 0 —
    the ``torch_profile_rank_0`` surface (``train_ffns.py:129-141``)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if jax.process_index() != 0:
                return fn(*args, **kwargs)
            os.makedirs(log_dir, exist_ok=True)
            with jax.profiler.trace(log_dir):
                out = fn(*args, **kwargs)
                jax.block_until_ready(out)
            return out

        return wrapper

    return deco


def timed(fn, *args, sync_scalar: bool = True, **kwargs):
    """``(result, seconds)`` with completion forced through a dependent
    scalar readback — ``block_until_ready`` alone under-reports on remote
    backends (see bench.py); per-method wall-clock is the reference's
    timing surface (``train_ffns.py:378-382``)."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    if sync_scalar:
        # every leaf needs its own readback: leaves may come from separate
        # dispatches, and forcing only one chain would stop the clock with
        # the others still in flight. Dispatch all sums before reading any
        # back, so only the readbacks serialize (each blocking round-trip
        # costs ~70ms on the relay, see bench.py).
        sums = [leaf.sum() for leaf in jax.tree_util.tree_leaves(out)]
        for s in sums:
            float(s)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0
