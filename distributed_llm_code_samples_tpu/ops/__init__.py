"""Numerical core: hand-written forward/backward kernels (no autograd).

TPU-native counterpart of the reference's ops layer (``train_ffns.py:33-94``).
"""

from .linear import init_linear, linear_fwd, linear_bwd
from .activations import relu_fwd, relu_bwd
from .ffn import (ffn_fwd, ffn_bwd, ffn_block, ffn_bwd_saved,
                  ffn_block_saved, ffn_block_mixed, ffn_fwd_mixed,
                  ffn_bwd_mixed)
from .stack import stack_fwd, stack_bwd, stack_grads
from .moe import (expert_capacity, route_top1, dispatch_tensor, moe_layer,
                  moe_stack_fwd)
from .norm import ln_fwd, ln_bwd, layernorm
from .xent import xent_fwd, xent_bwd, xent_loss
# Pallas modules (pallas_ffn, pallas_attention, pallas_ring) stay off the
# eager import path — import them at call sites like parallel/single.py
# does.

__all__ = [
    "init_linear", "linear_fwd", "linear_bwd",
    "relu_fwd", "relu_bwd",
    "ffn_fwd", "ffn_bwd", "ffn_block", "ffn_bwd_saved", "ffn_block_saved",
    "ffn_block_mixed", "ffn_fwd_mixed", "ffn_bwd_mixed",
    "stack_fwd", "stack_bwd", "stack_grads",
    "expert_capacity", "route_top1", "dispatch_tensor", "moe_layer",
    "moe_stack_fwd",
    "ln_fwd", "ln_bwd", "layernorm",
    "xent_fwd", "xent_bwd", "xent_loss",
]
