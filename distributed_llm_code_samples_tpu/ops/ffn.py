"""Transformer FFN sublayer: linear -> ReLU -> linear, hand-differentiated.

Parity target: ``train_ffns.py:54-70``. Two properties of the reference are
preserved deliberately:

- **Only block inputs are checkpointed.** The backward *recomputes* the
  ffn1 pre-activation (``train_ffns.py:63``) instead of saving it — built-in
  activation rematerialization. On TPU this trades one extra ``[tokens, ffn]``
  matmul for not keeping a ``4*d_model``-wide activation in HBM.
- **The backward math is written out by hand** (no autograd). ``ffn_block``
  wraps the pair in ``jax.custom_vjp`` so that even if a caller *does* run
  ``jax.grad`` over the stack, the rule that fires is this manual VJP —
  and the test suite verifies the manual math against JAX autograd, an
  oracle the reference never had.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .linear import linear_fwd, linear_bwd
from .activations import relu_fwd, relu_bwd


def ffn_fwd(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """linear -> ReLU -> linear (``train_ffns.py:54-58``).

    Shapes: ``w1 [ffn, d]``, ``w2 [d, ffn]``, ``x [tokens, d]`` -> ``[tokens, d]``.
    """
    h = linear_fwd(w1, x)
    a = relu_fwd(h)
    return linear_fwd(w2, a)


def ffn_bwd(dy: jax.Array, w1: jax.Array, w2: jax.Array, x: jax.Array):
    """Full-block manual VJP with pre-activation recompute (``train_ffns.py:61-70``).

    Args:
      dy: upstream gradient ``[tokens, d]``.
      x: the *block input* saved by the forward (the only checkpointed value).

    Returns ``(dx, (dw1, dw2))``.
    """
    h = linear_fwd(w1, x)  # recompute ffn1 pre-activation instead of saving it
    dw2, da = linear_bwd(dy, w2, relu_fwd(h))
    dh = relu_bwd(da, h)
    dw1, dx = linear_bwd(dh, w1, x)
    return dx, (dw1, dw2)


@jax.custom_vjp
def ffn_block(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """FFN block whose differentiation rule is the hand-written VJP above."""
    return ffn_fwd(w1, w2, x)


def _ffn_block_fwd(w1, w2, x):
    # Residuals: params + block input only — matches the reference's
    # checkpoint-block-inputs-only policy (train_ffns.py:77, :63).
    return ffn_fwd(w1, w2, x), (w1, w2, x)


def _ffn_block_bwd(res, dy):
    w1, w2, x = res
    dx, (dw1, dw2) = ffn_bwd(dy, w1, w2, x)
    return dw1, dw2, dx


ffn_block.defvjp(_ffn_block_fwd, _ffn_block_bwd)
