"""Transformer FFN sublayer: linear -> ReLU -> linear, hand-differentiated.

Parity target: ``train_ffns.py:54-70``. Two properties of the reference are
preserved deliberately:

- **Only block inputs are checkpointed.** The backward *recomputes* the
  ffn1 pre-activation (``train_ffns.py:63``) instead of saving it — built-in
  activation rematerialization. On TPU this trades one extra ``[tokens, ffn]``
  matmul for not keeping a ``4*d_model``-wide activation in HBM.
- **The backward math is written out by hand** (no autograd). ``ffn_block``
  wraps the pair in ``jax.custom_vjp`` so that even if a caller *does* run
  ``jax.grad`` over the stack, the rule that fires is this manual VJP —
  and the test suite verifies the manual math against JAX autograd, an
  oracle the reference never had.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .linear import linear_fwd, linear_bwd
from .activations import relu_fwd, relu_bwd


def ffn_fwd(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """linear -> ReLU -> linear (``train_ffns.py:54-58``).

    Shapes: ``w1 [ffn, d]``, ``w2 [d, ffn]``, ``x [tokens, d]`` -> ``[tokens, d]``.
    """
    h = linear_fwd(w1, x)
    a = relu_fwd(h)
    return linear_fwd(w2, a)


def ffn_bwd(dy: jax.Array, w1: jax.Array, w2: jax.Array, x: jax.Array):
    """Full-block manual VJP with pre-activation recompute (``train_ffns.py:61-70``).

    Args:
      dy: upstream gradient ``[tokens, d]``.
      x: the *block input* saved by the forward (the only checkpointed value).

    Returns ``(dx, (dw1, dw2))``.
    """
    h = linear_fwd(w1, x)  # recompute ffn1 pre-activation instead of saving it
    dw2, da = linear_bwd(dy, w2, relu_fwd(h))
    dh = relu_bwd(da, h)
    dw1, dx = linear_bwd(dh, w1, x)
    return dx, (dw1, dw2)


def ffn_bwd_saved(dy: jax.Array, w1: jax.Array, w2: jax.Array, x: jax.Array,
                  a: jax.Array):
    """Manual block VJP using the **saved** post-ReLU activation ``a``.

    Identical math to ``ffn_bwd`` — ``a = relu(h)`` so the ReLU mask
    ``h > 0`` equals ``a > 0`` — but skips the pre-activation recompute
    (``train_ffns.py:63``), trading one ``[tokens, ffn]`` residual in HBM
    for one fewer matmul per block backward. Measured throughput-equal to
    the recompute policy on the v5e-class bench chip (the extra residual
    traffic costs what the extra matmul costs), so ``ffn_block`` (remat)
    stays the default for its memory profile; this variant exists for
    HBM-rich parts where the trade tips the other way.

    Returns ``(dx, (dw1, dw2))``.
    """
    dw2, da = linear_bwd(dy, w2, a)
    dh = relu_bwd(da, a)  # mask a > 0 == h > 0
    dw1, dx = linear_bwd(dh, w1, x)
    return dx, (dw1, dw2)


@jax.custom_vjp
def ffn_block(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """FFN block whose differentiation rule is the hand-written VJP above."""
    return ffn_fwd(w1, w2, x)


def _ffn_block_fwd(w1, w2, x):
    # Residuals: params + block input only — matches the reference's
    # checkpoint-block-inputs-only policy (train_ffns.py:77, :63).
    return ffn_fwd(w1, w2, x), (w1, w2, x)


def _ffn_block_bwd(res, dy):
    w1, w2, x = res
    dx, (dw1, dw2) = ffn_bwd(dy, w1, w2, x)
    return dw1, dw2, dx


ffn_block.defvjp(_ffn_block_fwd, _ffn_block_bwd)


@jax.custom_vjp
def ffn_block_saved(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """FFN block differentiated by ``ffn_bwd_saved`` — the no-recompute
    fast path. Same forward, same gradients (the mask identity makes the
    two rules produce identical values)."""
    return ffn_fwd(w1, w2, x)


def _ffn_block_saved_fwd(w1, w2, x):
    h = linear_fwd(w1, x)
    a = relu_fwd(h)
    return linear_fwd(w2, a), (w1, w2, x, a)


def _ffn_block_saved_bwd(res, dy):
    w1, w2, x, a = res
    dx, (dw1, dw2) = ffn_bwd_saved(dy, w1, w2, x, a)
    return dw1, dw2, dx


ffn_block_saved.defvjp(_ffn_block_saved_fwd, _ffn_block_saved_bwd)


# --- Mixed-precision block: bf16 on the MXU, fp32 params/accumulation -----
#
# The TPU-first precision policy (absent from the fp32 reference): matmul
# *inputs* are cast to bfloat16 — the MXU's native format — while params,
# gradients, and every accumulation stay float32 (`preferred_element_type`).
# Residuals are saved in bf16, halving activation HBM traffic. The backward
# is still the hand-written rule, not autograd.

def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


@jax.custom_vjp
def ffn_block_mixed(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """linear -> ReLU -> linear with bf16 MXU compute, fp32 accumulate."""
    y, _ = _ffn_block_mixed_fwd(w1, w2, x)
    return y


def _ffn_block_mixed_fwd(w1, w2, x):
    bf = jnp.bfloat16
    xb, w1b, w2b = x.astype(bf), w1.astype(bf), w2.astype(bf)
    h = _dot(xb, w1b, (((1,), (1,))))          # [T,d]@[ffn,d]^T -> [T,ffn] f32
    ab = jnp.maximum(h, 0.0).astype(bf)        # saved post-ReLU, bf16
    y = _dot(ab, w2b, (((1,), (1,))))          # [T,ffn]@[d,ffn]^T -> [T,d] f32
    return y, (w1b, w2b, xb, ab)


def _mixed_bwd_core(dy, w1b, w2b, xb, ab):
    """The one copy of the mixed backward math, shared by the custom_vjp
    block and the pair-form dialect below — bit-identity between the two
    is BY CONSTRUCTION, not by parallel maintenance. All inputs except
    ``dy`` are bf16; returns f32 ``(dx, dw1, dw2)``."""
    bf = jnp.bfloat16
    dyb = dy.astype(bf)
    dw2 = _dot(dyb, ab, (((0,), (0,))))        # dy^T a   -> [d,ffn] f32
    da = _dot(dyb, w2b, (((1,), (0,))))        # dy  w2   -> [T,ffn] f32
    dhb = jnp.where(ab > 0, da, jnp.zeros((), jnp.float32)).astype(bf)
    dw1 = _dot(dhb, xb, (((0,), (0,))))        # dh^T x   -> [ffn,d] f32
    dx = _dot(dhb, w1b, (((1,), (0,))))        # dh  w1   -> [T,d]   f32
    return dx, dw1, dw2


def _ffn_block_mixed_bwd(res, dy):
    w1b, w2b, xb, ab = res
    dx, dw1, dw2 = _mixed_bwd_core(dy, w1b, w2b, xb, ab)
    return dw1, dw2, dx


ffn_block_mixed.defvjp(_ffn_block_mixed_fwd, _ffn_block_mixed_bwd)


@jax.custom_vjp
def ffn_block_mixed_remat(w1: jax.Array, w2: jax.Array,
                          x: jax.Array) -> jax.Array:
    """``ffn_block_mixed``'s math under the remat residual policy: the
    backward recomputes the pre-activation from the BLOCK INPUT (the
    reference's checkpoint stance, ``train_ffns.py:63``) and the stashed
    input is bf16 — the saved-bytes half of the mixed policy applied to
    the recompute policy's only residual. On an MXU-saturated shape the
    matmul time is identical to f32 (default-precision f32 matmuls are
    single bf16 passes anyway); the bf16 stash is the one lever that can
    move the single-chip headline."""
    y, _ = _ffn_block_mixed_remat_fwd(w1, w2, x)
    return y


def _ffn_block_mixed_remat_fwd(w1, w2, x):
    return ffn_fwd_mixed(w1, w2, x), (w1, w2, x.astype(jnp.bfloat16))


def _ffn_block_mixed_remat_bwd(res, dy):
    w1, w2, xb = res
    dx, (dw1, dw2) = ffn_bwd_mixed(dy, w1, w2, xb)
    return dw1, dw2, dx


# --- Pair-form mixed blocks: the hook-surface dialect ---------------------
#
# The distributed strategies (ddp/fsdp/tp/hybrid) inject collectives
# through ``ops.stack``'s ``block_fwd``/``block_bwd`` pair interface, where
# the backward RECOMPUTES from the saved block input (the reference's
# checkpoint policy, ``train_ffns.py:63``). These are ``ffn_block_mixed``'s
# math in that dialect: bf16 matmul inputs on the MXU, fp32
# params/grads/accumulation — the TPU-first precision policy threaded to
# every strategy (VERDICT r3 #3). Weights already in bf16 (e.g. FSDP's
# half-width gathered shards) pass through the casts unchanged.

def ffn_fwd_mixed(w1: jax.Array, w2: jax.Array, x: jax.Array) -> jax.Array:
    """linear -> ReLU -> linear, bf16 MXU inputs, f32 accumulate/output."""
    bf = jnp.bfloat16
    h = _dot(x.astype(bf), w1.astype(bf), ((1,), (1,)))   # [T, ffn] f32
    ab = jnp.maximum(h, 0.0).astype(bf)
    return _dot(ab, w2.astype(bf), ((1,), (1,)))          # [T, d] f32


def ffn_bwd_mixed(dy: jax.Array, w1: jax.Array, w2: jax.Array,
                  x: jax.Array):
    """Manual block VJP, bf16 compute, f32 accumulation, pre-activation
    recomputed from the block input (never saved). The ReLU mask uses the
    bf16 post-activation (``ab > 0``) so the recompute path produces
    bit-identical gradients to ``ffn_block_mixed``'s saved-residual rule.

    Returns ``(dx, (dw1, dw2))`` — all f32."""
    bf = jnp.bfloat16
    xb, w1b, w2b = x.astype(bf), w1.astype(bf), w2.astype(bf)
    h = _dot(xb, w1b, ((1,), (1,)))                       # recompute, f32
    ab = jnp.maximum(h, 0.0).astype(bf)
    dx, dw1, dw2 = _mixed_bwd_core(dy, w1b, w2b, xb, ab)
    return dx, (dw1, dw2)


ffn_block_mixed_remat.defvjp(_ffn_block_mixed_remat_fwd,
                             _ffn_block_mixed_remat_bwd)
