"""Fused Pallas TPU flash-attention kernels — the long-context hot op.

The plain attention op (``models.attention``) materializes the full
``[T, T]`` score/probability matrices; fine as a correctness oracle,
quadratic in HBM. These kernels are the hand-scheduled TPU form: the
online-softmax tiling (running row-max ``m``, denominator ``l``, f32 VMEM
accumulator) that ``parallel.sequence.ring_attention`` runs *across chips*,
here applied *within* a chip so no ``[T, T]`` block ever reaches HBM.

Forward saves only ``(y, lse)`` — the flash-attention residual policy,
matching the framework's checkpoint-block-inputs-only stance
(``train_ffns.py:63``): the backward recomputes score tiles from
``q, k, lse`` instead of saving probabilities.

Layout notes (guide: Tiling Constraints): per-row statistics (``lse``,
``D``) are carried lane-broadcast as ``[1, T]`` arrays blocked ``(1, bq)``
so every ref keeps a 128-friendly trailing dim; scratch stats are
``(bq, 128)`` with the value in every lane. Fully-masked causal tiles are
neutralized by zeroing ``p`` *after* the exp (an ``exp(-inf - -inf) = 1``
row would otherwise poison the accumulator). All kernels run under
``interpret=True`` on CPU for the hardware-free suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the shared tile/precision helpers live in pallas_ffn (the canonical
# module; imports flow attention -> ffn only, so there is no cycle) —
# _env_block reads tile envs at TRACE time so on-chip sweeps can vary
# them between jax.clear_caches() points without re-execing
from .pallas_ffn import _env_block, _mxu, _pick_block
from .pallas_ffn import _resolve_mxu_bf16 as _resolve_mxu_bf16_base

_NEG = -1e30
_LANES = 128
_Q_QUANTUM = 8


# Default tile sizes, env-overridable for on-chip sweeps. r04 swept on
# the v5e chip (T=8192, H8, dh64): 128x128 tiles ran the whole step at
# ~7 TFLOP/s — the online-softmax VPU work (exp, rescale, stats) per
# tile was unamortized against dh=64 matmuls. 1024x1024 forward tiles
# reach 49.6 TF/s; the backward peaks near 512x512 (53.6 TF/s) and
# larger tiles only add VMEM pressure (2048x1024 fails to compile).
# `_pick_block` caps every block at the actual T, so small/test shapes
# are unaffected.
def _DEF_BQ():
    return _env_block("FLASH_BLOCK_Q", 1024)


def _DEF_BK():
    return _env_block("FLASH_BLOCK_K", 1024)


def _DEF_BWD_BQ():
    return _env_block("FLASH_BWD_BLOCK_Q", 512)


def _DEF_BWD_BK():
    return _env_block("FLASH_BWD_BLOCK_K", 512)


def _resolve_mxu_bf16(mxu_bf16, interpret: bool) -> bool:
    """The flash kernels' bf16-MXU policy default: the shared rule
    (``pallas_ffn._resolve_mxu_bf16``) bound to the ``FLASH_MXU_BF16``
    env override. Callers who train flash under a full-f32 precision
    requirement pass ``mxu_bf16=False`` explicitly (or set
    ``FLASH_MXU_BF16=0``) — the policy is a parameter, not a hardwired
    consequence of running on hardware. Casting matmul operands (never
    the f32 accumulators or softmax stats) to bf16 puts the kernels in
    the same numerics class as the XLA oracle's default-precision
    matmuls and was worth ~3x on the r04 chip measurements."""
    return _resolve_mxu_bf16_base(mxu_bf16, interpret,
                                  env_var="FLASH_MXU_BF16")


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-manual-axes type, so
    the kernels can be called from inside ``shard_map`` bodies (Ulysses /
    TP / hybrid trainers) under JAX's ``check_vma`` typing."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _positions(i, j, bq, bk):
    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos, k_pos


def _tile_needed(i, j, bq, bk, causal):
    """False only for tiles the causal mask kills entirely (every key
    position past every query position) — those are skipped, the standard
    flash-attention FLOP saving (~2x on the quadratic hot path)."""
    if not causal:
        return True
    return j * bk <= i * bq + bq - 1


def _flash_fwd_kernel(q_ref, k_ref, v_ref, y_ref, lse_ref, m_ref, l_ref,
                      acc_ref, *, scale, causal, bq, bk, mxu_bf16):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_tile_needed(i, j, bq, bk, causal))
    def _():
        s = jnp.dot(_mxu(q_ref[:], mxu_bf16), _mxu(k_ref[:], mxu_bf16).T,
                    preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos, k_pos = _positions(i, j, bq, bk)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG)

        m_prev = m_ref[:, :1]                                    # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)  # a masked-out row would give p == 1
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv_dtype = jnp.bfloat16 if mxu_bf16 else v_ref.dtype
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p.astype(pv_dtype), v_ref[:].astype(pv_dtype),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        l = l_ref[:, :1]
        y_ref[:] = (acc_ref[:] / l).astype(y_ref.dtype)
        lse = (m_ref[:, :1] + jnp.log(l)).T                   # [1, bq]
        lse_ref[:] = lse.astype(lse_ref.dtype)


def flash_attention_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True, block_q: int | None = None,
                        block_k: int | None = None,
                        interpret: bool = False,
                        mxu_bf16: bool | None = None):
    """Fused attention forward. ``q, k, v [T, dh]`` -> ``(y [T, dh],
    lse [T])`` with only the log-sum-exp saved for the backward."""
    T, dh = q.shape
    scale = 1.0 / (dh ** 0.5)
    _mxu_bf16 = _resolve_mxu_bf16(mxu_bf16, interpret)
    bq = _pick_block(T, block_q or _DEF_BQ(), _Q_QUANTUM)
    bk = _pick_block(k.shape[0], block_k or _DEF_BK(), _Q_QUANTUM)
    grid = (T // bq, k.shape[0] // bk)
    y, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, mxu_bf16=_mxu_bf16),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bq, dh), lambda i, j: (i, 0)),
            pl.BlockSpec((bk, dh), lambda i, j: (j, 0)),
            pl.BlockSpec((bk, dh), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bq, dh), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bq), lambda i, j: (0, i)),
        ],
        out_shape=[_sds((T, dh), q.dtype, q),
                   _sds((1, T), jnp.float32, q)],
        scratch_shapes=[pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return y, lse[0]


def _recompute_p_ds(q_ref, k_ref, v_ref, dy_ref, lse_ref, d_ref, i, j,
                    scale, causal, mxu_bf16):
    """Shared backward tile math: probability tile from the saved lse,
    ``p = exp(q k^T * scale - lse)`` (zeroed where causally masked), and
    the softmax-VJP tile ``ds = p * (dy v^T - D)``."""
    s = jnp.dot(_mxu(q_ref[:], mxu_bf16), _mxu(k_ref[:], mxu_bf16).T,
                preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse_ref[0, :][:, None])
    if causal:
        q_pos, k_pos = _positions(i, j, *s.shape)
        p = jnp.where(q_pos >= k_pos, p, 0.0)
    dp = jnp.dot(_mxu(dy_ref[:], mxu_bf16), _mxu(v_ref[:], mxu_bf16).T,
                 preferred_element_type=jnp.float32)
    ds = p * (dp - d_ref[0, :][:, None])
    return p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, dy_ref, lse_ref, d_ref,
                         dq_ref, acc_ref, *, scale, causal, bq, bk,
                         mxu_bf16):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(_tile_needed(i, j, bq, bk, causal))
    def _():
        _, ds = _recompute_p_ds(q_ref, k_ref, v_ref, dy_ref, lse_ref, d_ref,
                                i, j, scale, causal, mxu_bf16)
        ds_dtype = jnp.bfloat16 if mxu_bf16 else k_ref.dtype
        acc_ref[:] += jnp.dot(ds.astype(ds_dtype), _mxu(k_ref[:], mxu_bf16),
                              preferred_element_type=jnp.float32) * scale

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        dq_ref[:] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, dy_ref, lse_ref, d_ref,
                          dk_ref, dv_ref, acck_ref, accv_ref, *, scale,
                          causal, bq, bk, mxu_bf16):
    jblk, t = pl.program_id(0), pl.program_id(1)

    @pl.when(t == 0)
    def _():
        acck_ref[:] = jnp.zeros_like(acck_ref)
        accv_ref[:] = jnp.zeros_like(accv_ref)

    @pl.when(_tile_needed(t, jblk, bq, bk, causal))
    def _():
        p, ds = _recompute_p_ds(q_ref, k_ref, v_ref, dy_ref, lse_ref, d_ref,
                                t, jblk, scale, causal, mxu_bf16)
        lhs_dtype = jnp.bfloat16 if mxu_bf16 else dy_ref.dtype
        accv_ref[:] += jnp.dot(p.T.astype(lhs_dtype),
                               _mxu(dy_ref[:], mxu_bf16),
                               preferred_element_type=jnp.float32)
        acck_ref[:] += jnp.dot(ds.T.astype(lhs_dtype),
                               _mxu(q_ref[:], mxu_bf16),
                               preferred_element_type=jnp.float32) * scale

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        dk_ref[:] = acck_ref[:].astype(dk_ref.dtype)
        dv_ref[:] = accv_ref[:].astype(dv_ref.dtype)


def flash_attention_bwd(dy: jax.Array, q, k, v, y, lse, *,
                        causal: bool = True, block_q: int | None = None,
                        block_k: int | None = None,
                        interpret: bool = False,
                        mxu_bf16: bool | None = None):
    """Flash backward from ``(q, k, v, y, lse)`` — score tiles recomputed,
    never stored. Returns ``(dq, dk, dv)``."""
    T, dh = q.shape
    Tk = k.shape[0]
    scale = 1.0 / (dh ** 0.5)
    _mxu_bf16 = _resolve_mxu_bf16(mxu_bf16, interpret)
    bq = _pick_block(T, block_q or _DEF_BWD_BQ(), _Q_QUANTUM)
    bk = _pick_block(Tk, block_k or _DEF_BWD_BK(), _Q_QUANTUM)
    # D_i = rowsum(dy * y): the only softmax statistic the tiles can't
    # rebuild locally; elementwise, computed once outside the kernels
    d = jnp.sum(dy.astype(jnp.float32) * y.astype(jnp.float32),
                axis=-1)[None, :]                              # [1, T]
    lse2 = lse[None, :]

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, mxu_bf16=_mxu_bf16),
        grid=(T // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((bq, dh), lambda i, j: (i, 0)),   # q
            pl.BlockSpec((bk, dh), lambda i, j: (j, 0)),   # k
            pl.BlockSpec((bk, dh), lambda i, j: (j, 0)),   # v
            pl.BlockSpec((bq, dh), lambda i, j: (i, 0)),   # dy
            pl.BlockSpec((1, bq), lambda i, j: (0, i)),    # lse
            pl.BlockSpec((1, bq), lambda i, j: (0, i)),    # D
        ],
        out_specs=pl.BlockSpec((bq, dh), lambda i, j: (i, 0)),
        out_shape=_sds((T, dh), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, dy, lse2, d)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, mxu_bf16=_mxu_bf16),
        grid=(Tk // bk, T // bq),
        in_specs=[
            pl.BlockSpec((bq, dh), lambda j, t: (t, 0)),   # q
            pl.BlockSpec((bk, dh), lambda j, t: (j, 0)),   # k
            pl.BlockSpec((bk, dh), lambda j, t: (j, 0)),   # v
            pl.BlockSpec((bq, dh), lambda j, t: (t, 0)),   # dy
            pl.BlockSpec((1, bq), lambda j, t: (0, t)),    # lse
            pl.BlockSpec((1, bq), lambda j, t: (0, t)),    # D
        ],
        out_specs=[
            pl.BlockSpec((bk, dh), lambda j, t: (j, 0)),
            pl.BlockSpec((bk, dh), lambda j, t: (j, 0)),
        ],
        out_shape=[_sds((Tk, dh), k.dtype, k),
                   _sds((Tk, dh), v.dtype, v)],
        scratch_shapes=[pltpu.VMEM((bk, dh), jnp.float32),
                        pltpu.VMEM((bk, dh), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, dy, lse2, d)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=True, interpret=False, mxu_bf16=None):
    """Attention computed by the fused kernels and differentiated by them
    (flash residuals: ``y`` + ``lse`` only). Single head ``[T, dh]``;
    multi-head/batch via ``jax.vmap``, like ``models.attention.mha``."""
    y, _ = flash_attention_fwd(q, k, v, causal=causal, interpret=interpret,
                               mxu_bf16=mxu_bf16)
    return y


def _flash_fwd_rule(q, k, v, causal, interpret, mxu_bf16):
    y, lse = flash_attention_fwd(q, k, v, causal=causal, interpret=interpret,
                                 mxu_bf16=mxu_bf16)
    return y, (q, k, v, y, lse)


def _flash_bwd_rule(causal, interpret, mxu_bf16, res, dy):
    q, k, v, y, lse = res
    return flash_attention_bwd(dy, q, k, v, y, lse, causal=causal,
                               interpret=interpret, mxu_bf16=mxu_bf16)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_mha(q, k, v, causal: bool = True, interpret: bool = False,
              mxu_bf16: bool | None = None):
    """Multi-head convenience: vmap over a leading heads axis
    (``[H, T, dh] -> [H, T, dh]``). Grouped-query shapes (``k/v
    [H_kv, T, dh]`` with ``H % H_kv == 0``, ``models.attention.gqa``)
    fan each KV head out to its query group — the kernel streams K/V
    blocks per query head either way, so the repeat adds no extra HBM
    traffic inside the kernel (one [H, T, dh] staging copy outside
    it)."""
    hq, hkv = q.shape[0], k.shape[0]
    if hq != hkv:
        if hq % hkv:
            raise ValueError(f"query heads {hq} not divisible by kv "
                             f"heads {hkv}")
        k = jnp.repeat(k, hq // hkv, axis=0)
        v = jnp.repeat(v, hq // hkv, axis=0)
    return jax.vmap(lambda q, k, v: flash_attention(
        q, k, v, causal, interpret, mxu_bf16))(q, k, v)


flash_mha.supports_gqa = True  # repeat-KV fan-out (see docstring)
