"""Bias-free linear layer with hand-written forward and backward.

Functional parity with the reference's numerical core
(``train_ffns.py:35-45``): weights are stored transposed ``[out, in]``,
there is no bias ("as simplification"), and the backward pass is the
manually-derived VJP written as two einsums — autograd is never used for
the model math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_linear(key: jax.Array, in_dim: int, out_dim: int,
                scale: float = 2e-2, dtype=jnp.float32) -> jax.Array:
    """``scale * normal([out_dim, in_dim])`` — reference ``train_ffns.py:35-36``."""
    return (scale * jax.random.normal(key, (out_dim, in_dim))).astype(dtype)


def linear_fwd(w: jax.Array, x: jax.Array) -> jax.Array:
    """``y = x @ w.T`` on ``[tokens, in_dim]`` inputs (``train_ffns.py:41-42``)."""
    return jnp.matmul(x, w.T)


def linear_bwd(dy: jax.Array, w: jax.Array, x: jax.Array):
    """Manual linear VJP (``train_ffns.py:44-45``).

    Returns ``(dw, dx)`` with ``dw = dy^T x`` and ``dx = dy w`` — the two
    einsum contractions the reference writes out by hand.
    """
    dw = jnp.einsum("bc,bd->cd", dy, x)
    dx = jnp.einsum("bc,cd->bd", dy, w)
    return dw, dx
