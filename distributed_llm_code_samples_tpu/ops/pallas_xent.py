"""Fused LM-head + softmax-cross-entropy Pallas kernels.

The oracle path (``models.lm.lm_loss``) materializes ``[N, V]`` logits
in HBM, and the hand-VJP xent (``ops/xent.py``) additionally saves the
full ``[N, V]`` softmax as its residual — at the bench family shape
(N=8192 tokens, V=50304) that is ~1.65 GB per tensor per direction of
pure HBM traffic around a head matmul whose FLOPs are cheap. The fused
kernels apply the flash-attention treatment to the vocabulary axis:
tile ``z = h @ W_chunk^T`` in VMEM, reduce it into online logsumexp
statistics, and pick the target logit with an iota==targets match — no
``[N, V]`` array ever reaches HBM, in either direction.

Forward residuals are ``(h, w, targets, lse)`` — O(N*d + V*d + N) —
and the backward recomputes logit tiles exactly like the flash
backward recomputes score tiles (the framework's
checkpoint-block-inputs recompute stance, ``train_ffns.py:63``,
applied to the head). Backward math, hand-derived as in ``ops/xent.py``:
``dz = (softmax(z) - onehot(t)) * dy / N``, split into a dh pass
(``dz @ W``) and a dw pass (``dz^T @ h``).

MXU operands follow the same bf16 single-pass policy as the flash
kernels (``pallas_attention._resolve_mxu_bf16``): on by default on the
compiled TPU path — the numerics class of the XLA oracle's
default-precision matmuls — full f32 in interpret mode so the CPU
suite's differentials vs ``xent_loss(h @ w.T, t)`` stay tight. The
f32 softmax statistics and accumulators are never cast.

Reference capability covered: the reference has no loss at all
(``train_ffns.py:12,:30`` mock it); this is the LM family's real
objective made TPU-first.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import _LANES, _NEG, _mxu, _resolve_mxu_bf16
from .pallas_ffn import _pick_block

_N_QUANTUM = 8


def _vma_of(x):
    return getattr(jax.typeof(x), "vma", None) or frozenset()


def _pvary_group(*xs):
    """Promote every operand to the JOIN of the group's varying manual
    axes (``lax.pvary``) — inside ``shard_map`` a kernel mixing a
    data-varying ``h`` with a replicated ``wte`` in one dot needs the
    replicated side explicitly marked varying, the promotion JAX inserts
    automatically for ordinary primitives but not across a
    ``pallas_call`` boundary."""
    join = frozenset().union(*[_vma_of(x) for x in xs])
    if not join:
        return xs
    return tuple(
        jax.lax.pcast(x, tuple(sorted(join - _vma_of(x))), to="varying")
        if join - _vma_of(x) else x for x in xs)


def _sds_join(shape, dtype, *likes):
    """ShapeDtypeStruct whose varying-manual-axes type is the JOIN of
    the inputs' vmas. ``_sds`` takes one exemplar, which is wrong here:
    under DDP the wte operand is replicated (empty vma) while ``h``
    varies over the data axis — every kernel output depends on both, so
    its vma is the union. Empty union (no shard_map) stays untyped."""
    vma = frozenset().union(*[_vma_of(x) for x in likes])
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _round_up(x: int, q: int) -> int:
    return -(-x // q) * q


def _blocks(n: int, v: int, block_n, block_v):
    """Token blocks must divide N (``_pick_block``); the vocab axis
    instead always gets its PREFERRED lane-aligned block and the weight
    matrix is zero-padded up to a multiple of it — real vocabularies
    (GPT-2's 50257 is prime) rarely have a lane-multiple divisor, and
    falling back to ``bv = V`` would put the whole ``[V, d]`` matrix in
    one VMEM block. Padded columns are neutralized in-kernel by the
    ``cols < V`` mask (logits -> -inf forward, dz -> 0 backward)."""
    bn = _pick_block(n, block_n or 256, _N_QUANTUM)
    bv = min(block_v or 512, _round_up(v, _LANES))
    return bn, bv, _round_up(v, bv)


def _fwd_kernel(h_ref, w_ref, t_ref, lse_ref, tz_ref, m_ref, se_ref,
                tzacc_ref, *, bn, bv, v_total, mxu_bf16):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        se_ref[:] = jnp.zeros_like(se_ref)
        tzacc_ref[:] = jnp.zeros_like(tzacc_ref)

    z = jnp.dot(_mxu(h_ref[:], mxu_bf16), _mxu(w_ref[:], mxu_bf16).T,
                preferred_element_type=jnp.float32)          # [bn, bv]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    valid = cols < v_total
    z = jnp.where(valid, z, _NEG)  # padded vocab columns
    # a target index landing in the padded range [V, vp) (possible for
    # vp_head_xent's shifted out-of-slice targets) must NOT pick up the
    # -1e30 sentinel — only true vocab columns can match
    match = (cols == t_ref[0, :][:, None]) & valid
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(z, axis=1, keepdims=True))
    se_new = (se_ref[:, :1] * jnp.exp(m_prev - m_new)
              + jnp.sum(jnp.exp(z - m_new), axis=1, keepdims=True))
    # the target column appears in exactly one vocab tile; accumulate its
    # raw logit (no rescale — it is a value, not an exp-sum)
    tzacc_ref[:] += jnp.broadcast_to(
        jnp.sum(jnp.where(match, z, 0.0), axis=1, keepdims=True),
        tzacc_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    se_ref[:] = jnp.broadcast_to(se_new, se_ref.shape)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse = (m_ref[:, :1] + jnp.log(se_ref[:, :1])).T       # [1, bn]
        lse_ref[:] = lse
        tz_ref[:] = tzacc_ref[:, :1].T


def _recompute_dz(h_ref, w_ref, t_ref, lse_ref, vblk, bn, bv, v_total,
                  inv_n, mxu_bf16):
    """The one copy of the backward tile math (the _mixed_bwd_core
    pattern): recompute the logit tile for vocab block ``vblk``, then
    ``dz = (softmax - onehot) * 1/N`` with padded columns zeroed. Shared
    by the dh and dw kernels so the two passes cannot desynchronize."""
    z = jnp.dot(_mxu(h_ref[:], mxu_bf16), _mxu(w_ref[:], mxu_bf16).T,
                preferred_element_type=jnp.float32)
    p = jnp.exp(z - lse_ref[0, :][:, None])
    cols = vblk * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    dz = (p - jnp.where(cols == t_ref[0, :][:, None], 1.0, 0.0))
    return jnp.where(cols < v_total, dz, 0.0) * inv_n


def _bwd_dh_kernel(h_ref, w_ref, t_ref, lse_ref, dh_ref,
                   acc_ref, *, bn, bv, v_total, inv_n, mxu_bf16):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    dz = _recompute_dz(h_ref, w_ref, t_ref, lse_ref, j, bn, bv, v_total,
                       inv_n, mxu_bf16)
    dz_dtype = jnp.bfloat16 if mxu_bf16 else w_ref.dtype
    acc_ref[:] += jnp.dot(dz.astype(dz_dtype), _mxu(w_ref[:], mxu_bf16),
                          preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        dh_ref[:] = acc_ref[:].astype(dh_ref.dtype)


def _bwd_dw_kernel(h_ref, w_ref, t_ref, lse_ref, dw_ref,
                   acc_ref, *, bn, bv, v_total, inv_n, mxu_bf16):
    jblk, t = pl.program_id(0), pl.program_id(1)

    @pl.when(t == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    dz = _recompute_dz(h_ref, w_ref, t_ref, lse_ref, jblk, bn, bv,
                       v_total, inv_n, mxu_bf16)
    dz_dtype = jnp.bfloat16 if mxu_bf16 else h_ref.dtype
    acc_ref[:] += jnp.dot(dz.T.astype(dz_dtype), _mxu(h_ref[:], mxu_bf16),
                          preferred_element_type=jnp.float32)

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


def head_xent_stats(h: jax.Array, w: jax.Array, targets: jax.Array, *,
                    block_n: int | None = None,
                    block_v: int | None = None,
                    interpret: bool = False,
                    mxu_bf16: bool | None = None):
    """The fused forward's raw per-slice statistics:
    ``(lse [N], tz [N])`` where ``lse = logsumexp(h W^T)`` over THIS
    ``w``'s rows and ``tz`` is the target logit if the (0-based) target
    falls in ``[0, V)``, else 0. This is the merge-ready form the
    vocab-parallel head (``parallel.lm.vp_head_xent``) combines across
    model shards — out-of-slice targets (negative or >= V after the
    caller's offset shift) simply match no column."""
    N, d = h.shape
    V = w.shape[0]
    mx = _resolve_mxu_bf16(mxu_bf16, interpret)
    bn, bv, vp = _blocks(N, V, block_n, block_v)
    if vp != V:
        w = jnp.pad(w, ((0, vp - V), (0, 0)))
    t2 = targets.astype(jnp.int32)[None, :]                   # [1, N]
    h, w, t2 = _pvary_group(h, w, t2)
    lse, tz = pl.pallas_call(
        functools.partial(_fwd_kernel, bn=bn, bv=bv, v_total=V,
                          mxu_bf16=mx),
        grid=(N // bn, vp // bv),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),       # h
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),       # w
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),       # targets
        ],
        out_specs=[
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),       # lse
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),       # target z
        ],
        out_shape=[_sds_join((1, N), jnp.float32, h, w, targets),
                   _sds_join((1, N), jnp.float32, h, w, targets)],
        scratch_shapes=[pltpu.VMEM((bn, _LANES), jnp.float32),
                        pltpu.VMEM((bn, _LANES), jnp.float32),
                        pltpu.VMEM((bn, _LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(h, w, t2)
    return lse[0], tz[0]


def head_xent_fwd(h: jax.Array, w: jax.Array, targets: jax.Array, *,
                  block_n: int | None = None, block_v: int | None = None,
                  interpret: bool = False, mxu_bf16: bool | None = None):
    """Fused ``mean_i(logsumexp(h_i W^T) - (h_i W^T)[t_i])``.

    ``h [N, d]`` float, ``w [V, d]`` float, ``targets [N]`` int.
    Returns ``(loss, lse [N])`` — lse is the backward's only softmax
    residual."""
    lse, tz = head_xent_stats(h, w, targets, block_n=block_n,
                              block_v=block_v, interpret=interpret,
                              mxu_bf16=mxu_bf16)
    return jnp.mean(lse - tz), lse


def head_xent_bwd(dy: jax.Array, h, w, targets, lse, *,
                  block_n: int | None = None, block_v: int | None = None,
                  interpret: bool = False, mxu_bf16: bool | None = None):
    """Hand backward from ``(h, w, targets, lse)`` — logit tiles
    recomputed, never stored. Returns ``(dh, dw)``."""
    N, d = h.shape
    V = w.shape[0]
    mx = _resolve_mxu_bf16(mxu_bf16, interpret)
    bn, bv, vp = _blocks(N, V, block_n, block_v)
    if vp != V:
        w = jnp.pad(w, ((0, vp - V), (0, 0)))
    t2 = targets.astype(jnp.int32)[None, :]
    lse2 = lse[None, :]
    h, w, t2, lse2 = _pvary_group(h, w, t2, lse2)

    # dz is linear in the scalar cotangent dy, so the kernels bake in the
    # static 1/N mean factor and dy multiplies the outputs outside (an
    # elementwise scale XLA fuses into the surrounding graph) — no
    # scalar operand plumbing needed.
    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, bn=bn, bv=bv, v_total=V,
                          inv_n=1.0 / N, mxu_bf16=mx),
        grid=(N // bn, vp // bv),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),       # h
            pl.BlockSpec((bv, d), lambda i, j: (j, 0)),       # w
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),       # targets
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),       # lse
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        out_shape=_sds_join((N, d), h.dtype, h, w, targets, lse),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(h, w, t2, lse2)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, bn=bn, bv=bv, v_total=V,
                          inv_n=1.0 / N, mxu_bf16=mx),
        grid=(vp // bv, N // bn),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, t: (t, 0)),       # h
            pl.BlockSpec((bv, d), lambda j, t: (j, 0)),       # w
            pl.BlockSpec((1, bn), lambda j, t: (0, t)),       # targets
            pl.BlockSpec((1, bn), lambda j, t: (0, t)),       # lse
        ],
        out_specs=pl.BlockSpec((bv, d), lambda j, t: (j, 0)),
        out_shape=_sds_join((vp, d), w.dtype, h, w, targets, lse),
        scratch_shapes=[pltpu.VMEM((bv, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(h, w, t2, lse2)
    return dy * dh, dy * dw[:V]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def head_xent(h, w, targets, interpret=False, mxu_bf16=None):
    """Row-mean cross-entropy of the tied LM head, computed and
    differentiated by the fused kernels. ``targets`` is
    non-differentiable."""
    loss, _ = head_xent_fwd(h, w, targets, interpret=interpret,
                            mxu_bf16=mxu_bf16)
    return loss


def _head_xent_fwd_rule(h, w, targets, interpret, mxu_bf16):
    loss, lse = head_xent_fwd(h, w, targets, interpret=interpret,
                              mxu_bf16=mxu_bf16)
    return loss, (h, w, targets, lse)


def _head_xent_bwd_rule(interpret, mxu_bf16, res, dy):
    h, w, targets, lse = res
    dh, dw = head_xent_bwd(dy, h, w, targets, lse, interpret=interpret,
                           mxu_bf16=mxu_bf16)
    return dh, dw, None


head_xent.defvjp(_head_xent_fwd_rule, _head_xent_bwd_rule)
