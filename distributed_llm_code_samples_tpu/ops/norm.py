"""LayerNorm, hand-differentiated (no autograd), gain-only.

The reference has no normalization (FFN sublayers only, ``README.md:6``);
the transformer model family adds pre-LN blocks, so the norm gets the same
first-principles treatment as the linear/ReLU core (``train_ffns.py:33-52``):
forward written out, backward derived by hand, installed via ``custom_vjp``
and checked against ``jax.grad`` in the tests. No bias/offset parameter —
the framework keeps the reference's no-bias simplification
(``train_ffns.py:35``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

EPS = 1e-5


def ln_fwd(g: jax.Array, x: jax.Array, eps: float = EPS):
    """Row-wise LayerNorm over the last dim. ``g [d]``, ``x [..., d]``.

    Returns ``(y, (xhat, rstd))`` with the normalized input and reciprocal
    std saved for the manual backward.
    """
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    return g * xhat, (xhat, rstd)


def ln_bwd(dy: jax.Array, g: jax.Array, xhat: jax.Array, rstd: jax.Array):
    """Manual LayerNorm VJP.

    With ``y = g * xhat``, ``xhat = (x - mu) * rstd``:
    ``dg = sum_rows(dy * xhat)``;
    ``dx = rstd * (dxh - mean(dxh) - xhat * mean(dxh * xhat))`` where
    ``dxh = dy * g`` — the standard three-term row formula (the two mean
    terms are the VJPs through mu and var).
    """
    dg = jnp.sum((dy * xhat).reshape(-1, g.shape[-1]), axis=0)
    dxh = dy * g
    m1 = jnp.mean(dxh, axis=-1, keepdims=True)
    m2 = jnp.mean(dxh * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxh - m1 - xhat * m2)
    return dg, dx


@jax.custom_vjp
def layernorm(g: jax.Array, x: jax.Array) -> jax.Array:
    """LayerNorm whose differentiation rule is the hand-written VJP."""
    y, _ = ln_fwd(g, x)
    return y


def _layernorm_fwd(g, x):
    y, (xhat, rstd) = ln_fwd(g, x)
    return y, (g, xhat, rstd)


def _layernorm_bwd(res, dy):
    g, xhat, rstd = res
    dg, dx = ln_bwd(dy, g, xhat, rstd)
    return dg, dx


layernorm.defvjp(_layernorm_fwd, _layernorm_bwd)
