"""Hand-scheduled ICI collectives on Pallas remote DMA — the explicit-
control escape hatch (SURVEY.md §2.7, last ledger row).

Everywhere else this framework lets XLA schedule communication: the
strategies emit ``psum``/``all_gather``/``ppermute`` and the compiler's
latency-hiding scheduler splits them into async pairs (proven in
``tests/test_observability.py``). That recovers what the reference
hand-builds with ``async_op=True`` + ``handle.wait()``
(``train_ffns.py:164-172``) — but it is trust-the-compiler control. This
module is the OTHER answer, the one the reference's stream experiment
(``test_torch_cuda_stream.py:31-37``) was reaching for: communication as
explicitly issued, explicitly awaited inter-chip DMA, scheduled by us.

The COMPLETE collective family — every op in SURVEY §2.7's ledger — as
hand-scheduled kernels, each pinned against its XLA counterpart and
AOT-compiled for v5e-8:

- ``ppermute_dma``: one ring hop — each device RDMAs its block to its
  right neighbor (``pltpu.make_async_remote_copy``), with the neighbor
  barrier that makes a raw remote write safe. Equality-pinned against
  ``lax.ppermute``.
- ``ring_all_reduce``: the full classic 2(n-1)-step ring — reduce-
  scatter phase then all-gather phase — inside ONE kernel launch:
  double-buffered communication slots, DMA-completion semaphores,
  explicit capacity handshaking (a receiver frees a slot back to its
  sender), and a pairwise phase handoff. Each step's accumulate overlaps
  the next chunk's DMA — the comm/compute overlap the reference wanted,
  hand-scheduled. Equality-pinned against ``lax.psum`` (identical
  summation order per chunk: partials accumulate in ring order on both
  paths only if n is the ring size — values agree to f32 reduction-order
  tolerance).
- ``ring_reduce_scatter`` / ``ring_all_gather``: the two phases as
  standalone kernels in the ``psum_scatter``/``all_gather`` conventions
  — the exact pattern FSDP consumes (``train_fsdp(comm="pallas_ring")``
  runs its whole comm schedule through them).
- ``all_to_all_dma``: the dense peer fan-out (EP-dispatch / Ulysses
  transport) — every (src, dst) block pair is a direct RDMA with
  per-peer semaphore slots; all n-1 transfers in flight at once, no
  slot reuse, no backpressure needed.

Algorithm notes (device ``r`` of ``n``, chunks = leading-dim n-split):

- reduce-scatter step ``s``: send chunk ``(r - s) % n`` right, receive
  chunk ``(r - s - 1) % n`` from the left into comm slot ``s % 2``, add
  it to the local copy. After ``n-1`` steps device ``r`` owns the fully
  reduced chunk ``(r + 1) % n``.
- all-gather step ``s``: send chunk ``(r + 1 - s) % n`` right, directly
  into the receiver's output at the SAME global chunk index (all-gather
  writes chunk c to slot c everywhere); receive chunk ``(r - s) % n``.
  Every received chunk is immediately the next step's send — the ring
  dependency is the only synchronization needed.
- hazards handled explicitly: slot-reuse backpressure (capacity
  semaphore, signaled sender-ward on consumption), phase handoff (a
  device may only write a neighbor's output region after that neighbor
  left the reduce-scatter phase — pairwise REGULAR semaphore, no global
  barrier), and kernel-entry (neighbor barrier semaphore: no DMA may
  target a chip that has not entered the kernel).

Off-TPU the kernels run under the Mosaic TPU *interpreter*
(``pltpu.InterpretParams`` — NOT the generic ``interpret=True``, which
has no remote-DMA model), so the 8-device CPU mesh exercises the real
semaphore/DMA semantics. On-chip compilation is pinned by the v5e-8 AOT
codegen test (the Mosaic custom call replaces the XLA collective in the
lowered module).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def interpret_collectives_supported() -> bool:
    """Can these kernels run OFF-chip (interpret mode) on this jax?

    The dedicated TPU interpreter (``pltpu.InterpretParams``) models
    semaphores and remote DMA; it arrived with the graduated (>= 0.5)
    pallas surface. The pre-graduation interpreter has no discharge
    rules for them ("Remote signal not implemented" at trace time), so
    off-TPU callers must degrade gracefully — skip the Mosaic transport
    and keep the XLA one — instead of dying mid-run. Same graduation
    marker the parallel compat layer keys on (``collectives.vma_erased``
    — the compat shims install ``jax.typeof``/``InterpretParams``
    stand-ins on old jax, so hasattr alone would lie; the ``erased_vma``
    flag those shims carry is the truth). On-chip Mosaic compilation is
    unaffected either way."""
    return (hasattr(jax, "typeof")
            and not getattr(jax.typeof, "erased_vma", False))


def _interpret_arg(interpret: bool | None):
    # the TPU interpreter models semaphores + remote DMA; the generic
    # pallas interpreter does not. None = auto: interpreter off-TPU,
    # Mosaic on chip (AOT codegen callers pass False explicitly).
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return pltpu.InterpretParams() if interpret else False


_LANES = 128


def _legalize_2d(x2, n: int):
    """Mosaic slices a 2-D VMEM ref along dim 0 only if dim 1 is
    lane-aligned (128). A narrow operand (e.g. FSDP's per-layer
    ``[rows, 64]`` shards) is re-flattened so each ring CHUNK becomes
    ``[elems/128, 128]`` — pure reshape, chunk boundaries preserved
    (chunks are contiguous in row-major), values untouched. Returns the
    legalized array; the caller reshapes the result back."""
    rows, cols = x2.shape
    if cols % _LANES == 0:
        return x2
    elems = (rows // n) * cols  # per chunk
    if elems % _LANES == 0:
        return x2.reshape(n * (elems // _LANES), _LANES)
    return x2  # narrow fallback: fine in interpret; Mosaic may reject


def _drain_capacity(capacity, n: int):
    """Zero the capacity semaphore's never-waited leftovers (the last
    two steps' consumption signals have no reusing step). SAFETY-
    CRITICAL ledger: a stale count satisfies a later backpressure wait
    without any real consumption and re-opens the ≥2-step-skew DMA/
    semaphore aliasing race (the n=8 corruption bug) — one accounting,
    shared by every phase of every ring kernel."""
    for slot_id in (0, 1):
        sig = len([s for s in range(n - 1) if s % 2 == slot_id])
        wai = len([s for s in range(2, n - 1) if s % 2 == slot_id])
        if sig - wai:
            pltpu.semaphore_wait(capacity.at[slot_id], sig - wai)


def _neighbor_barrier(axis_name: str, n: int):
    """No remote write may target a chip still outside the kernel."""
    r = lax.axis_index(axis_name)
    barrier = pltpu.get_barrier_semaphore()
    left = lax.rem(r - 1 + n, n)
    right = lax.rem(r + 1, n)
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)


def ppermute_dma(x: jax.Array, axis_name: str, *,
                 interpret: bool | None = None) -> jax.Array:
    """One ring hop by explicit RDMA: device r's block lands on device
    ``(r+1) % n`` — ``lax.ppermute(x, perm=[(i, (i+1)%n)])`` with the
    transport hand-issued. Call inside ``shard_map``."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    shape = x.shape
    x2 = x.reshape(shape[0], -1) if x.ndim != 2 else x

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        _neighbor_barrier(axis_name, n)
        r = lax.axis_index(axis_name)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=o_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=lax.rem(r + 1, n),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    out = pl.pallas_call(
        kernel,
        # vma: the landed blocks differ per device (shard-varying under
        # shard_map's vma typing — DESIGN.md §4)
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=7),
        interpret=_interpret_arg(interpret),
    )(x2)
    return out.reshape(shape)


def ring_all_reduce(x: jax.Array, axis_name: str, *,
                    interpret: bool | None = None) -> jax.Array:
    """``lax.psum(x, axis_name)`` as a hand-scheduled 2-phase ring of
    ``pltpu.make_async_remote_copy`` hops. Call inside ``shard_map``;
    ``x.shape[0]`` must divide by the axis size (the chunk unit)."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    shape = x.shape
    if shape[0] % n:
        raise ValueError(f"leading dim {shape[0]} not divisible by ring "
                         f"size {n} (chunk unit of the ring)")
    x2 = x.reshape(shape[0], -1) if x.ndim != 2 else x
    x2 = _legalize_2d(x2, n)
    rows, cols = x2.shape
    rc = rows // n  # rows per chunk

    def chunk(ref, idx):
        return ref.at[pl.ds(idx * rc, rc), :]

    def kernel(x_ref, o_ref, comm_buf, send_sem, recv_sem, capacity,
               phase_sem):
        _neighbor_barrier(axis_name, n)
        r = lax.axis_index(axis_name)
        left = lax.rem(r - 1 + n, n)
        right = lax.rem(r + 1, n)
        o_ref[...] = x_ref[...]

        # ---- phase 1: reduce-scatter (n-1 steps) --------------------
        def rs_step(s, _):
            slot = lax.rem(s, 2)
            send_idx = lax.rem(r - s + n, n)
            recv_idx = lax.rem(r - s - 1 + n, n)
            # backpressure: slot reused every 2 steps — wait until the
            # right neighbor freed it (it signals on consumption)
            @pl.when(s >= 2)
            def _():
                pltpu.semaphore_wait(capacity.at[slot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=chunk(o_ref, send_idx),
                dst_ref=comm_buf.at[slot],
                send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait_recv()  # left's chunk for this step has landed
            o_ref[pl.ds(recv_idx * rc, rc), :] += comm_buf[slot]
            # slot consumed: hand it back to its writer (left neighbor)
            pltpu.semaphore_signal(
                capacity.at[slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.wait_send()
            return 0

        lax.fori_loop(0, n - 1, rs_step, 0)

        # ---- drain phase 1's capacity leftovers ---------------------
        # The last two steps' consumption signals are never waited (no
        # step n/n+1 reuses those slots): +1 leftover per slot. Phase 2
        # REUSES the capacity semaphore — a stale count would satisfy
        # its first backpressure wait without any real consumption,
        # re-opening the ≥2-step-skew DMA/semaphore aliasing race (this
        # exact bug corrupted chunks at n=8). Drain to zero here, so
        # phase 2's waits can only be satisfied by phase-2 signals.
        # (Also the ledger discipline: leftover counts would poison the
        # next kernel sharing the physical semaphores.)
        _drain_capacity(capacity, n)

        # ---- phase handoff ------------------------------------------
        # Phase 2 writes straight into the RIGHT neighbor's output; that
        # is only safe once the neighbor is out of phase 1. Pairwise
        # signal leftward ("I am done reading what you may overwrite"),
        # wait for the right neighbor's.
        pltpu.semaphore_signal(phase_sem, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(phase_sem, 1)

        # ---- phase 2: all-gather (n-1 steps) ------------------------
        # The same ≤2-step skew bound phase 1 gets from its capacity
        # handshake is REQUIRED here too: without backpressure a sender
        # can run ≥2 steps ahead of its receiver, two of its DMAs alias
        # the same mod-2 semaphore slot, and DMA completion order is not
        # guaranteed — the receiver's wait can be satisfied by the LATER
        # chunk's arrival (observed as corrupted chunks at n=8 in the
        # Mosaic interpreter). Signal-after-wait_recv bounds the skew.
        def ag_step(s, _):
            slot = lax.rem(s, 2)
            send_idx = lax.rem(r + 1 - s + n, n)  # global chunk id; the
            # receiver stores chunk c at slot c, so src and dst slices
            # coincide — every received chunk is the next step's send
            @pl.when(s >= 2)
            def _():
                pltpu.semaphore_wait(capacity.at[slot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=chunk(o_ref, send_idx),
                dst_ref=chunk(o_ref, send_idx),
                send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait_recv()  # chunk (r - s) % n landed in place
            pltpu.semaphore_signal(
                capacity.at[slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.wait_send()
            return 0

        lax.fori_loop(0, n - 1, ag_step, 0)

        # ---- drain phase 2's leftovers (same accounting) ------------
        _drain_capacity(capacity, n)

    out = pl.pallas_call(
        kernel,
        # typed shard-varying: the SUM is value-replicated but produced
        # independently per device; callers needing invariant typing
        # pcast (same situation as zero1's re-assembled params)
        out_shape=jax.ShapeDtypeStruct((rows, cols), x2.dtype,
                                       vma=frozenset({axis_name})),
        # VMEM: the kernel reads/accumulates the operand directly (ANY/
        # HBM refs are DMA-only), and resident operands are what lets
        # each step's accumulate overlap the next chunk's DMA
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rc, cols), x2.dtype),   # double-buffered slots
            pltpu.SemaphoreType.DMA((2,)),         # send completion
            pltpu.SemaphoreType.DMA((2,)),         # recv completion
            pltpu.SemaphoreType.REGULAR((2,)),     # slot backpressure
            pltpu.SemaphoreType.REGULAR,           # phase handoff
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=8),
        interpret=_interpret_arg(interpret),
    )(x2)
    return out.reshape(shape)


def ring_reduce_scatter(x: jax.Array, axis_name: str, *,
                        interpret: bool | None = None) -> jax.Array:
    """``collectives.reduce_scatter(x, axis, dim=0)`` hand-scheduled:
    the reduce-scatter phase of the ring alone. ``x [n*rc, ...]`` per
    device; device ``r`` returns the summed chunk ``r`` (``[rc, ...]``).

    Same protocol as ``ring_all_reduce``'s phase 1 with the ring pattern
    shifted one hop (virtual rank ``r-1``), so the finally-owned chunk is
    ``r`` — the ``lax.psum_scatter(tiled=True)`` convention the XLA path
    implements. Accumulation happens on a scratch copy of the input;
    only the owned chunk is written out."""
    n = lax.psum(1, axis_name)
    shape = x.shape
    if shape[0] % n:
        raise ValueError(f"leading dim {shape[0]} not divisible by ring "
                         f"size {n} (chunk unit of the ring)")
    if n == 1:
        return x
    x2 = x.reshape(shape[0], -1) if x.ndim != 2 else x
    x2 = _legalize_2d(x2, n)
    rc = x2.shape[0] // n
    cols = x2.shape[1]

    def kernel(x_ref, o_ref, acc, comm_buf, send_sem, recv_sem, capacity):
        _neighbor_barrier(axis_name, n)
        r = lax.axis_index(axis_name)
        left = lax.rem(r - 1 + n, n)
        right = lax.rem(r + 1, n)
        acc[...] = x_ref[...]
        rv = lax.rem(r - 1 + n, n)  # virtual rank: owned chunk = rv+1 = r

        def rs_step(s, _):
            slot = lax.rem(s, 2)
            send_idx = lax.rem(rv - s + n, n)
            recv_idx = lax.rem(rv - s - 1 + n, n)
            @pl.when(s >= 2)
            def _():
                pltpu.semaphore_wait(capacity.at[slot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc.at[pl.ds(send_idx * rc, rc), :],
                dst_ref=comm_buf.at[slot],
                send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait_recv()
            acc[pl.ds(recv_idx * rc, rc), :] += comm_buf[slot]
            pltpu.semaphore_signal(
                capacity.at[slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.wait_send()
            return 0

        lax.fori_loop(0, n - 1, rs_step, 0)
        o_ref[...] = acc[pl.ds(lax.rem(rv + 1, n) * rc, rc), :]
        # drain the never-waited capacity leftovers (ledger discipline)
        _drain_capacity(capacity, n)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rc, cols), x2.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((n * rc, cols), x2.dtype),  # accumulator copy
            pltpu.VMEM((2, rc, cols), x2.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=9),
        interpret=_interpret_arg(interpret),
    )(x2)
    return out.reshape((shape[0] // n,) + shape[1:])


def ring_all_gather(x: jax.Array, axis_name: str, *,
                    interpret: bool | None = None) -> jax.Array:
    """``collectives.all_gather(x, axis, dim=0)`` hand-scheduled: the
    all-gather phase of the ring alone. ``x [rows, ...]`` per device;
    returns ``[n*rows, ...]`` with chunk ``i`` = device ``i``'s block —
    ``ring_all_reduce``'s phase 2 with the output seeded from the local
    block instead of reduced chunks (owner of chunk ``r`` is ``r``, so
    the send pattern starts one hop later: ``send_idx = (r - s) % n``)."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    shape = x.shape
    x2 = x.reshape(shape[0], -1) if x.ndim != 2 else x
    x2 = _legalize_2d(x2, 1)  # the chunk unit is the WHOLE local block
    rc, cols = x2.shape

    def kernel(x_ref, o_ref, send_sem, recv_sem, capacity):
        _neighbor_barrier(axis_name, n)
        r = lax.axis_index(axis_name)
        left = lax.rem(r - 1 + n, n)
        right = lax.rem(r + 1, n)
        o_ref[pl.ds(r * rc, rc), :] = x_ref[...]

        def ag_step(s, _):
            slot = lax.rem(s, 2)
            send_idx = lax.rem(r - s + n, n)  # own block at s=0, then
            # each received chunk is the next step's send (the ring
            # dependency); receiver stores chunk c at slot c
            @pl.when(s >= 2)
            def _():
                pltpu.semaphore_wait(capacity.at[slot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[pl.ds(send_idx * rc, rc), :],
                dst_ref=o_ref.at[pl.ds(send_idx * rc, rc), :],
                send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait_recv()  # chunk (r - s - 1) % n landed in place
            pltpu.semaphore_signal(
                capacity.at[slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.wait_send()
            return 0

        lax.fori_loop(0, n - 1, ag_step, 0)
        _drain_capacity(capacity, n)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * rc, cols), x2.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=10),
        interpret=_interpret_arg(interpret),
    )(x2)
    return out.reshape((n * shape[0],) + shape[1:])


def _all_peer_barrier(axis_name: str, n: int):
    """All-to-all targets every peer, so kernel-entry safety needs the
    FULL barrier (the neighbor form only covers ring topologies)."""
    r = lax.axis_index(axis_name)
    barrier = pltpu.get_barrier_semaphore()

    def signal(j, _):
        @pl.when(j != r)
        def _():
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=j,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n, signal, 0)
    pltpu.semaphore_wait(barrier, n - 1)


def all_to_all_dma(x: jax.Array, axis_name: str, *,
                   interpret: bool | None = None) -> jax.Array:
    """``collectives.all_to_all(x, axis, split_dim=0, concat_dim=0)``
    hand-scheduled: chunk ``j`` of every device's block RDMAs DIRECTLY to
    device ``j`` (no ring — the dense peer fan-out the EP dispatch and
    Ulysses re-shards ride), landing at chunk position ``r`` of the
    receiver. All ``n-1`` outgoing transfers start before any wait (full
    overlap); per-peer semaphore slots make completion order irrelevant
    (each (src, dst) pair is unique — no slot reuse, no backpressure
    needed, unlike the ring kernels)."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    shape = x.shape
    if shape[0] % n:
        raise ValueError(f"leading dim {shape[0]} not divisible by "
                         f"{n} peers (the split unit of all_to_all)")
    x2 = x.reshape(shape[0], -1) if x.ndim != 2 else x
    x2 = _legalize_2d(x2, n)
    rows, cols = x2.shape
    rc = rows // n

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        _all_peer_barrier(axis_name, n)
        r = lax.axis_index(axis_name)
        o_ref[pl.ds(r * rc, rc), :] = x_ref[pl.ds(r * rc, rc), :]

        def out_desc(j):
            # outgoing r->j: my chunk j lands at the receiver's chunk r;
            # the remote signal slot is MY index (so the receiver can
            # tell sources apart), my send slot is the peer index
            return pltpu.make_async_remote_copy(
                src_ref=x_ref.at[pl.ds(j * rc, rc), :],
                dst_ref=o_ref.at[pl.ds(r * rc, rc), :],
                send_sem=send_sem.at[j], recv_sem=recv_sem.at[r],
                device_id=j,
                device_id_type=pltpu.DeviceIdType.LOGICAL)

        def start(j, _):
            @pl.when(j != r)
            def _():
                out_desc(j).start()
            return 0

        lax.fori_loop(0, n, start, 0)

        def wait(j, _):
            @pl.when(j != r)
            def _():
                # incoming from peer j: wrote my chunk j, signals MY
                # recv slot j — a descriptor with the matching refs
                # (same transfer size) and slot performs the wait
                pltpu.make_async_remote_copy(
                    src_ref=x_ref.at[pl.ds(r * rc, rc), :],
                    dst_ref=o_ref.at[pl.ds(j * rc, rc), :],
                    send_sem=send_sem.at[j], recv_sem=recv_sem.at[j],
                    device_id=j,
                    device_id_type=pltpu.DeviceIdType.LOGICAL).wait_recv()
                out_desc(j).wait_send()
            return 0

        lax.fori_loop(0, n, wait, 0)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, cols), x2.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((n,)),   # per-peer send completion
            pltpu.SemaphoreType.DMA((n,)),   # per-source recv completion
        ],
        compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                             collective_id=11),
        interpret=_interpret_arg(interpret),
    )(x2)
    return out.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def all_to_all_dma_dims(x: jax.Array, axis_name: str, split_dim: int,
                        concat_dim: int,
                        interpret: bool | None = None) -> jax.Array:
    """``collectives.all_to_all(x, axis, split_dim=s, concat_dim=c)``
    (tiled) over the ``all_to_all_dma`` kernel: the split dim moves to
    the front for the dim-0 exchange, and the received blocks
    concatenate back along ``concat_dim`` — the Ulysses re-shard shapes
    (``[H, T, dh]``, 0<->1) ride this form. Differentiable: the VJP of a
    tiled all_to_all is the all_to_all with the dims swapped (the
    exchange is a linear permutation of blocks), so autodiff through a
    strategy's a2a transport runs the transport kernel both ways."""
    return _a2a_dims_fwd(x, axis_name, split_dim, concat_dim,
                         interpret)[0]


def _a2a_dims_fwd(x, axis_name, split_dim, concat_dim, interpret):
    n = lax.psum(1, axis_name)
    if n == 1:
        return x, None
    xm = jnp.moveaxis(x, split_dim, 0)
    k = all_to_all_dma(xm, axis_name, interpret=interpret)
    kb = k.reshape(n, xm.shape[0] // n, *xm.shape[1:])
    blocks = [jnp.moveaxis(kb[j], 0, split_dim) for j in range(n)]
    return jnp.concatenate(blocks, axis=concat_dim), None


def _a2a_dims_bwd(axis_name, split_dim, concat_dim, interpret, _, dy):
    return (all_to_all_dma_dims(dy, axis_name, concat_dim, split_dim,
                                interpret),)


all_to_all_dma_dims.defvjp(_a2a_dims_fwd, _a2a_dims_bwd)


def ring_all_reduce_spmd(x: jax.Array, mesh, axis_name: str, *,
                         interpret: bool = False) -> jax.Array:
    """Convenience launcher: shard a global ``[n*rows, cols]`` array over
    the axis, ring-all-reduce the per-device blocks, return the stacked
    per-device results (each block is the full sum — the differential-
    test harness shape, comparable leaf-for-leaf against the same
    ``shard_map`` wrapping ``lax.psum``)."""
    from jax.sharding import PartitionSpec as P
    f = jax.shard_map(
        functools.partial(ring_all_reduce, axis_name=axis_name,
                          interpret=interpret),
        mesh=mesh, in_specs=P(axis_name, None), out_specs=P(axis_name, None))
    return f(x)
