"""Hand-written activation forward/backward (reference ``train_ffns.py:47-52``).

The reference's ReLU backward is in-place (``masked_fill_``); in a functional
XLA program the same memory behavior comes from XLA buffer reuse — the
``jnp.where`` here fuses into the surrounding matmuls, so no extra HBM
round-trip happens on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def relu_fwd(x: jax.Array) -> jax.Array:
    """``where(x <= 0, 0, x)`` (``train_ffns.py:47-48``)."""
    return jnp.where(x <= 0, jnp.zeros((), dtype=x.dtype), x)


def relu_bwd(dy: jax.Array, x: jax.Array) -> jax.Array:
    """Mask upstream grads where the pre-activation was <= 0 (``train_ffns.py:50-52``)."""
    return jnp.where(x <= 0, jnp.zeros((), dtype=dy.dtype), dy)
