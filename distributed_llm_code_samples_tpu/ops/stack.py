"""FFN-stack forward/backward with communication hook injection points.

Parity target: ``tlayers_ffn_fwd`` / ``tlayers_ffn_bkwd``
(``train_ffns.py:72-94``). The reference threads ``before_comms_hook`` /
``after_comms_hook`` callables through its layer loops so each parallelism
strategy can splice collectives into the right spot. Here the same
architecture is functional: a strategy customizes

- ``block_fwd(w1, w2, x) -> y`` — e.g. TP appends a ``psum`` after the block
  (``train_ffns.py:303``); FSDP all-gathers the layer's param shards first
  (``train_ffns.py:200-225``).
- ``block_bwd(dy, w1, w2, x) -> (dx, (dw1, dw2))`` — e.g. TP ``psum``s the
  returned ``dx`` (``train_ffns.py:309``); FSDP gathers shards before the VJP.
- ``grad_hook(dw1, dw2) -> (dw1, dw2)`` — fires the moment a layer's grads
  exist, exactly like the reference's ``after_comms_hook``
  (``train_ffns.py:90-91``): DDP ``psum``s them (``:164-165``), FSDP
  ``psum_scatter``s them (``:255-256``). XLA's latency-hiding scheduler
  overlaps these collectives with the remaining backward compute — the role
  the reference's async handles + ``wait()`` played by hand.

Only **block inputs** are saved as activations (``train_ffns.py:77``); each
block's backward recomputes its pre-activation (see ``ops.ffn``).

Two loop forms are provided. ``unroll=True`` (default) emits one HLO region
per layer — XLA can software-pipeline collectives of layer ``l+1`` against
compute of layer ``l``, which is how the reference's explicit prefetch
machinery (``train_ffns.py:236-249``) is recovered on TPU. ``unroll=False``
uses ``lax.scan`` for O(1)-in-depth compile time on deep stacks.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .ffn import ffn_fwd, ffn_bwd, ffn_block

BlockFwd = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]
BlockBwd = Callable[..., tuple]
GradHook = Callable[[jax.Array, jax.Array], tuple]


def stack_fwd(w1s: jax.Array, w2s: jax.Array, x: jax.Array, *,
              block_fwd: BlockFwd = ffn_fwd, unroll: bool = True):
    """Run the stack forward; returns ``(y, acts)`` with ``acts`` = block inputs.

    ``w1s [L, ffn, d]`` / ``w2s [L, d, ffn]`` hold the per-layer params
    stacked on a leading layer axis (the reference's list-of-layers,
    ``train_ffns.py:361``, as one array so it can live under one sharding).
    In sharded strategies these are the *local shards*; ``block_fwd`` is
    responsible for any gathering.
    """
    n_layers = w1s.shape[0]
    # the "fwd" named-scope region: every strategy's forward walk carries
    # it (nested under the strategy's own scope — utils/trace_analysis.py
    # documents the naming map; HLO metadata and profiler spans key on it)
    with jax.named_scope("fwd"):
        if unroll:
            acts = []
            y = x
            for l in range(n_layers):
                acts.append(y)
                y = block_fwd(w1s[l], w2s[l], y)
            return y, jnp.stack(acts)

        def body(y, layer):
            w1, w2 = layer
            return block_fwd(w1, w2, y), y

        y, acts = lax.scan(body, x, (w1s, w2s))
        return y, acts


def stack_bwd(dy: jax.Array, w1s: jax.Array, w2s: jax.Array,
              acts: jax.Array, *,
              block_bwd: BlockBwd = ffn_bwd,
              grad_hook: Optional[GradHook] = None,
              unroll: bool = True):
    """Walk the stack backward (reverse layer order, ``train_ffns.py:83-94``).

    Returns ``(dx, (g1s, g2s))`` with grads stacked in layer order. If
    ``grad_hook`` is given it is applied to each layer's ``(dw1, dw2)``
    immediately after they are produced — the gradient-comm/compute overlap
    injection point.
    """
    n_layers = acts.shape[0]
    # the "bwd" named-scope region — the hook's collectives nest inside
    # it (e.g. DDP's grad psum shows as .../bwd/comm)
    with jax.named_scope("bwd"):
        if unroll:
            g1, g2 = [None] * n_layers, [None] * n_layers
            for l in reversed(range(n_layers)):
                dy, (dw1, dw2) = block_bwd(dy, w1s[l], w2s[l], acts[l])
                if grad_hook is not None:
                    dw1, dw2 = grad_hook(dw1, dw2)
                g1[l], g2[l] = dw1, dw2
            return dy, (jnp.stack(g1), jnp.stack(g2))

        def body(dy, xs):
            w1, w2, act = xs
            dy, (dw1, dw2) = block_bwd(dy, w1, w2, act)
            if grad_hook is not None:
                dw1, dw2 = grad_hook(dw1, dw2)
            return dy, (dw1, dw2)

        dx, (g1s, g2s) = lax.scan(body, dy, (w1s, w2s, acts),
                                  reverse=True)
        return dx, (g1s, g2s)


def accumulated_grads(grad_fn, x: jax.Array, dy: jax.Array, accum: int):
    """Sum ``grad_fn(x_chunk, dy_chunk)`` over ``accum`` leading-dim
    chunks via ``lax.scan`` — the shared gradient-accumulation engine of
    the single-device and DDP trainers. Exact under SUM semantics (grads
    are linear in the batch); peak activation memory drops ~1/accum
    because only one chunk's residuals are live at a time."""
    if accum == 1:
        return grad_fn(x, dy)
    tokens = x.shape[0]
    if tokens % accum:
        raise ValueError(f"tokens {tokens} not divisible into "
                         f"{accum} accumulation chunks")
    xc = x.reshape(accum, tokens // accum, *x.shape[1:])
    dc = dy.reshape(accum, tokens // accum, *dy.shape[1:])

    def body(total, xd):
        g = grad_fn(*xd)
        return jax.tree_util.tree_map(jnp.add, total, g), None

    # start from typed zeros so the grad graph is emitted ONCE (inside the
    # scan body) — seeding the carry with grad_fn(chunk 0) would duplicate
    # the whole fwd+bwd HLO. eval_shape carries vma, so the zeros can be
    # pcast to match shard-varying grads under shard_map.
    def zero_of(aval):
        z = jnp.zeros(aval.shape, aval.dtype)
        vma = tuple(getattr(aval, "vma", ()) or ())
        return lax.pcast(z, vma, to="varying") if vma else z

    init = jax.tree_util.tree_map(zero_of,
                                  jax.eval_shape(grad_fn, xc[0], dc[0]))
    return lax.scan(body, init, (xc, dc))[0]


def stack_grads(w1s: jax.Array, w2s: jax.Array, x: jax.Array,
                dy: jax.Array, *, block=ffn_block, unroll: bool = True):
    """Whole-stack gradients with the hand-written VJP as the per-block rule
    but functional composition driving the chain.

    ``stack_fwd``/``stack_bwd`` above mirror the reference's manual loop
    threading (``train_ffns.py:72-94``) literally: block inputs are collected
    into an explicit ``acts`` array and per-layer grads are restacked. That
    materialization is measurably non-free on TPU — profiled on v5e it costs
    ~10% of the step versus letting ``jax.vjp`` compose the chain, because
    XLA then manages residuals itself (it keeps them in the narrow bf16 form
    the MXU pass produces and accumulates grads in place instead of
    re-stacking). The math that runs per block is *still* the hand-written
    rule: ``block`` defaults to ``ffn_block``, whose ``custom_vjp`` is the
    manual backward (``ops.ffn``, reference ``train_ffns.py:61-70``) — JAX
    autograd never differentiates the block itself.

    Returns ``(y, (g1s, g2s))`` with grads stacked on the layer axis.
    """
    n_layers = w1s.shape[0]

    def fwd(w1s, w2s):
        if unroll:
            y = x
            for l in range(n_layers):
                y = block(w1s[l], w2s[l], y)
            return y
        return lax.scan(lambda y, wp: (block(wp[0], wp[1], y), None),
                        x, (w1s, w2s))[0]

    # fwd/bwd named-scope regions: jax.vjp traces the forward here, and
    # calling the vjp traces the transpose — so the two phases carry
    # distinct scope names even though autograd composes the chain
    with jax.named_scope("fwd"):
        y, vjp = jax.vjp(fwd, w1s, w2s)
    with jax.named_scope("bwd"):
        g1s, g2s = vjp(dy)
    return y, (g1s, g2s)
