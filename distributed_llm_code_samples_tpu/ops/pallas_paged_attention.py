"""Fused Pallas paged-attention decode kernel: the block-table walk.

The decode engine's hot loop (``decode/engine.py``) reads the KV cache
in two passes: ``gather_paged_kv`` materializes each slot's contiguous
``[H_kv, T_cap, dh]`` f32 view from the block pool (an HBM round-trip
of the whole gathered layout, dequantized — 4x inflated under int8),
then ``models.lm.decode_attn`` reads it again. This kernel fuses the
two: the grid walks each slot's int32 block table directly (scalar
prefetch drives the BlockSpec index maps, so every grid step DMAs
exactly one physical KV block from the pool), streams the blocks
through VMEM with the per-block int8 dequant folded in, and runs the
single-query attention in-register — the gathered layout never exists
in HBM, and the pool bytes cross the bus once, at the STORAGE dtype.
That is the DECODE roofline's ``B * kv_bytes`` term taken at face
value (decode is KV-bandwidth-bound; see bench_decode.py).

Bit-exactness stance (the repo's differential discipline): the kernel
is engine-selectable (``EngineConfig(kernel="fused")``) with the
gather two-pass kept as the oracle, and at f32 the two are BIT
IDENTICAL under jit by construction — the walk accumulates raw score
tiles (and a running max, which is order-exact) into VMEM scratch, and
the mask / softmax / AV ops on the assembled row replicate
``decode_attn``'s exact op order (divide-by-sqrt, where-mask to -1e30,
softmax, then PV). A streamed rescaling accumulator (the flash-style
``alpha`` fold, ``ops/pallas_attention.py``) would reorder the f32
adds and forfeit the oracle equality; at decode's T_cap (a few K
positions), the assembled row fits VMEM comfortably, so exactness
costs nothing. Blocks entirely past a slot's length are skipped —
their score tiles are pinned to the mask value and their V tiles to
zero, which contribute exactly what the oracle's masked positions
contribute (an exp-underflow zero times a finite byte).

Layout notes: grid is ``(slots, kv_heads, table_slots)`` with the
block walk innermost (scratch accumulates across it); GQA rides as a
``G = H / H_kv`` query-row dimension per kv head. Shapes here are the
engine's test shapes — real-chip runs want lane-aligned ``dh`` and a
length-sorted slot order, which is hardware-window tuning
(``run_hw_artifacts.sh``), not a semantics change. All paths run under
``interpret=True`` on CPU for the hardware-free suite
(tests/test_pallas_paged_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the oracle's mask value (models.lm.decode_attn) — shared so the
# masked tiles stay bit-identical between the two paths
_NEG = -1e30


def interpret_supported() -> bool:
    """Can the kernel run OFF-chip (generic interpret mode) on this
    jax? The block walk needs scalar-prefetch grid specs
    (``pltpu.PrefetchScalarGridSpec``); the capability gate is the
    ``pallas_ring`` stance — degrade to the gather path with a fast
    skip instead of dying mid-suite on an older pallas surface."""
    return hasattr(pltpu, "PrefetchScalarGridSpec")


def _interpret_arg(interpret: bool | None) -> bool:
    # None = auto: interpret off-TPU, Mosaic on chip
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def _walk_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, y_ref,
                 s_ref, v_scr, *, blk, mb, g, dh, tcap):
    """f32/bf16 variant: no per-block scales. See ``_walk_kernel_q8``
    for the int8 twin; the body is shared via ``_tile``."""
    _tile(table_ref, len_ref, q_ref, k_ref, v_ref, y_ref, s_ref, v_scr,
          None, None, blk=blk, mb=mb, g=g, dh=dh, tcap=tcap)


def _walk_kernel_q8(table_ref, len_ref, ksc_ref, vsc_ref, q_ref, k_ref,
                    v_ref, y_ref, s_ref, v_scr, *, blk, mb, g, dh, tcap):
    _tile(table_ref, len_ref, q_ref, k_ref, v_ref, y_ref, s_ref, v_scr,
          ksc_ref, vsc_ref, blk=blk, mb=mb, g=g, dh=dh, tcap=tcap)


def _tile(table_ref, len_ref, q_ref, k_ref, v_ref, y_ref, s_ref, v_scr,
          ksc_ref, vsc_ref, *, blk, mb, g, dh, tcap):
    i, h, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    length = len_ref[i]
    sl = pl.ds(j * blk, blk)

    @pl.when(j * blk < length)
    def _():
        # one physical block, DMA'd straight off the table walk
        # (the index map already selected pool[table[i, j], h]);
        # dequant folds in here — the pool bytes crossed the bus at
        # the storage dtype
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        if ksc_ref is not None:
            kb = kb * ksc_ref[i, j, h]
            vb = vb * vsc_ref[i, j, h]
        v_scr[sl, :] = vb
        # raw scores, the oracle's exact op order: dot then / sqrt(dh)
        s_ref[:, sl] = jax.lax.dot_general(
            q_ref[0, 0], kb, (((1,), (1,)), ((), ()))) / jnp.sqrt(
                jnp.asarray(dh, jnp.float32))

    @pl.when(j * blk >= length)
    def _():
        # a block entirely past the length: every position is masked,
        # so pin the tiles to what the oracle's mask produces (score
        # -> _NEG, V contribution -> exact zero) without reading it
        v_scr[sl, :] = jnp.zeros((blk, dh), jnp.float32)
        s_ref[:, sl] = jnp.full((g, blk), _NEG, jnp.float32)

    @pl.when(j == mb - 1)
    def _():
        # the assembled row: decode_attn's ops verbatim, so fused ==
        # gather+attn bit-for-bit at f32 (tests pin it)
        mask = jax.lax.broadcasted_iota(jnp.int32, (g, tcap), 1) < length
        s = jnp.where(mask, s_ref[:, :], jnp.asarray(_NEG, jnp.float32))
        p = jax.nn.softmax(s, axis=-1)
        y_ref[0, 0] = jax.lax.dot_general(p, v_scr[:, :],
                                          (((1,), (0,)), ((), ())))


def paged_decode_attn(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                      k_scale: jax.Array | None,
                      v_scale: jax.Array | None, tables: jax.Array,
                      lengths: jax.Array, *,
                      interpret: bool | None = None) -> jax.Array:
    """Fused single-query attention against a paged KV pool.

    ``q [B, H, dh]`` f32; ``pool_k/pool_v [n_blocks, H_kv, block, dh]``
    (ONE layer's pool, storage dtype); ``k_scale/v_scale
    [n_blocks, H_kv]`` f32 per-block int8 scales (None for f32/bf16);
    ``tables [B, MB]`` int32 physical block ids; ``lengths [B]`` the
    number of ATTENDABLE positions per slot (callers pass the decode
    convention ``lengths + 1``; must be >= 1 — the engine guarantees
    it, pad rows attend the scratch block's position 0). Returns
    ``y [B, H, dh]`` f32, bit-identical under jit to
    ``decode_attn(q, *gather_layer(...), lengths)``.

    The per-block scales ride as scalar-prefetch operands, pre-gathered
    to ``[B, MB, H_kv]`` outside the kernel — a few hundred f32s next
    to the block payload the walk is there to keep off the bus."""
    b, hq, dh = q.shape
    nb, hkv, blk, dh2 = pool_k.shape
    if dh2 != dh:
        raise ValueError(f"q head dim {dh} != pool head dim {dh2}")
    if hq % hkv:
        raise ValueError(f"query heads {hq} not divisible by kv heads "
                         f"{hkv}")
    g = hq // hkv
    mb = tables.shape[1]
    tcap = mb * blk
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale/v_scale must both be set or both None")
    run_interpret = _interpret_arg(interpret)
    if run_interpret and not interpret_supported():
        raise ValueError(
            "fused paged attention needs pltpu.PrefetchScalarGridSpec "
            "for its off-chip interpret mode; this jax has no scalar-"
            "prefetch surface — use EngineConfig(kernel='gather')")
    qg = q.reshape(b, hkv, g, dh)
    scalar_args = [tables.astype(jnp.int32), lengths.astype(jnp.int32)]
    if k_scale is not None:
        scalar_args += [k_scale[tables], v_scale[tables]]  # [B, MB, Hkv]
        kernel = functools.partial(_walk_kernel_q8, blk=blk, mb=mb, g=g,
                                   dh=dh, tcap=tcap)
    else:
        kernel = functools.partial(_walk_kernel, blk=blk, mb=mb, g=g,
                                   dh=dh, tcap=tcap)

    def _pool_spec():
        # the block walk: grid step (i, h, j) pulls pool[table[i,j], h]
        return pl.BlockSpec((1, 1, blk, dh),
                            lambda i, h, j, tr, *_: (tr[i, j], h, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalar_args),
        grid=(b, hkv, mb),
        in_specs=[
            pl.BlockSpec((1, 1, g, dh),
                         lambda i, h, j, *_: (i, h, 0, 0)),     # q
            _pool_spec(),                                       # k
            _pool_spec(),                                       # v
        ],
        out_specs=pl.BlockSpec((1, 1, g, dh),
                               lambda i, h, j, *_: (i, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((g, tcap), jnp.float32),     # scores
                        pltpu.VMEM((tcap, dh), jnp.float32)],   # V row
    )
    y = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, dh), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=run_interpret,
    )(*scalar_args, qg, pool_k, pool_v)
    return y.reshape(b, hq, dh)
