"""Fused Pallas TPU kernels for the FFN block — the hot-op path.

The FFN sublayer ``y = relu(x @ w1.T) @ w2.T`` decomposes exactly over the
ffn dimension: ``y = sum_k relu(x @ w1_k.T) @ w2_k.T`` (ReLU is elementwise,
so each ffn slice is independent). These kernels exploit that to fuse the
whole block: the ``[tokens, ffn]`` hidden activation never round-trips to
HBM — it lives tile-by-tile in VMEM between the two MXU contractions. The
plain-XLA path (``ops.ffn``) keeps the same math; these kernels are the
hand-scheduled equivalent (the role CUDA kernels played underneath the
reference's torch ops, here first-party).

Three kernels mirror the hand-written VJP structure (``train_ffns.py:54-70``):

- ``ffn_fwd_pallas``    — fused fwd; grid (token tiles x ffn tiles), ffn as
  the reduction axis, f32 VMEM accumulator.
- ``ffn_bwd_dx_pallas`` — input grad with pre-activation *recompute* (the
  block checkpoints only its input, ``train_ffns.py:63``); reduces over ffn.
- ``ffn_bwd_dw_pallas`` — both weight grads; reduces over token tiles.

``pallas_ffn_block`` wires them into ``jax.custom_vjp`` so the kernels ARE
the differentiation rule, exactly like ``ops.ffn.ffn_block``. All kernels
run under ``interpret=True`` on CPU for the hardware-free test suite.

**Measured verdict (r2, v5e-class chip, bench shape d=768/L=24/8k tok):
XLA stays the default training path.** The XLA path runs at 0.92 MFU —
the fused kernels compile and run (26.4 vs 16.0 steps/s, ratio ~0.60)
but cannot win: the 3-kernel VJP split recomputes ``h`` and ``dy·w2`` in
both backward kernels (18·T·d·f total matmul FLOPs vs the XLA path's
14·T·d·f), and a fused dx+dw kernel is blocked by conflicting reduction
axes (dx reduces over ffn, dw over tokens — an output block revisited
non-consecutively across the grid cannot accumulate in VMEM). With XLA
at 92% of the MXU peak there is no headroom for the extra FLOPs to
hide.

**Round-5: the flash recipe applied** (the exact fix that took the
flash kernels 7→41 TF/s on chip in r4): every MXU operand is cast to
bf16 by default on the compiled path (``mxu_bf16`` — f32 operands make
Mosaic emit multi-pass dots, ~3x the single bf16 pass XLA's default f32
precision lowers to), and the block sizes are sweepable
(``bench.py``'s ``BENCH_PALLAS_SWEEP=1`` tries the tile grid on chip
and reports the best). The 18-vs-14 FLOP structure is inherent to the
3-kernel split, so the arithmetic ceiling is 14/18 ≈ 0.78 of an
equally-efficient XLA — ``pallas_vs_xla`` ≥ 0.9 is only reachable if
the kernels beat XLA's per-FLOP efficiency; ``bench.py`` records the
live ratio and this docstring carries the measured verdict either way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _mxu(x, mxu_bf16: bool):
    """Cast an MXU operand to bf16 when the bf16-MXU policy is on (the
    flash recipe). THE canonical definition: ``pallas_attention`` (and,
    through it, ``pallas_xent``) imports this — imports flow
    attention -> ffn only, never back, so there is no cycle."""
    return x.astype(jnp.bfloat16) if mxu_bf16 else x


def _resolve_mxu_bf16(mxu_bf16, interpret: bool,
                      env_var: str | None = None) -> bool:
    """Default the bf16-MXU policy: on for the compiled TPU path (the
    numerics class of the XLA oracle under JAX's default f32 matmul
    precision), off under the interpreter (the CPU suite then checks
    exact f32 math against the oracle). An explicit ``mxu_bf16`` always
    wins; ``env_var`` names an optional env override between the two
    (the flash kernels pass ``FLASH_MXU_BF16``). Canonical definition —
    the other Pallas modules import it from here."""
    if mxu_bf16 is not None:
        return bool(mxu_bf16)
    if env_var is not None:
        env = os.environ.get(env_var)
        if env is not None:
            return env != "0"
    return not interpret


def _pick_block(size: int, preferred: int, quantum: int) -> int:
    """Largest divisor of ``size`` that is <= preferred and a multiple of
    ``quantum`` (falls back to ``size`` itself for tiny shapes)."""
    best = None
    b = quantum
    while b <= min(size, preferred):
        if size % b == 0:
            best = b
        b += quantum
    return best if best is not None else size

# f32 min sublane tile is 8; lanes are 128 (guide: Tiling Constraints)
_TOKEN_QUANTUM = 8
_FFN_QUANTUM = 128


def _env_block(name: str, default: int) -> int:
    """Tile-size default, env-overridable so bench.py's on-chip sweep
    can tune without replumbing the trainers (the sweep calls
    ``jax.clear_caches()`` between points — the envs are read at trace
    time)."""
    v = os.environ.get(name)
    return int(v) if v else default


def _fwd_kernel(x_ref, w1_ref, w2_ref, y_ref, acc_ref, *, mxu_bf16):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    h = jnp.dot(_mxu(x_ref[:], mxu_bf16), _mxu(w1_ref[:], mxu_bf16).T,
                preferred_element_type=jnp.float32)
    a_dtype = jnp.bfloat16 if mxu_bf16 else x_ref.dtype
    a = jnp.maximum(h, 0.0).astype(a_dtype)
    acc_ref[:] += jnp.dot(a, _mxu(w2_ref[:], mxu_bf16).T,
                          preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(1) - 1)
    def _():
        y_ref[:] = acc_ref[:].astype(y_ref.dtype)


def ffn_fwd_pallas(w1: jax.Array, w2: jax.Array, x: jax.Array, *,
                   block_t: int | None = None,
                   block_f: int | None = None,
                   interpret: bool = False,
                   mxu_bf16: bool | None = None) -> jax.Array:
    """Fused linear->ReLU->linear forward. ``w1 [ffn, d]``, ``w2 [d, ffn]``,
    ``x [T, d]`` -> ``[T, d]``; hidden tiles stay in VMEM. ``mxu_bf16``
    defaults on for the compiled TPU path (the flash recipe — f32
    accumulation throughout)."""
    T, d = x.shape
    ffn = w1.shape[0]
    bt = _pick_block(T, block_t or _env_block("PALLAS_FFN_BT", 256),
                     _TOKEN_QUANTUM)
    bf = _pick_block(ffn, block_f or _env_block("PALLAS_FFN_BF", 512),
                     _FFN_QUANTUM)
    grid = (T // bt, ffn // bf)
    return pl.pallas_call(
        functools.partial(_fwd_kernel,
                          mxu_bf16=_resolve_mxu_bf16(mxu_bf16, interpret)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, d), lambda i, k: (i, 0)),   # x tile
            pl.BlockSpec((bf, d), lambda i, k: (k, 0)),   # w1 ffn-slice
            pl.BlockSpec((d, bf), lambda i, k: (0, k)),   # w2 ffn-slice
        ],
        out_specs=pl.BlockSpec((bt, d), lambda i, k: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * T * d * ffn,
            bytes_accessed=(T * d + 2 * d * ffn + T * d) * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, w1, w2)


def _bwd_dx_kernel(x_ref, dy_ref, w1_ref, w2_ref, dx_ref, acc_ref, *,
                   mxu_bf16):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # recompute the pre-activation slice (checkpoint-block-inputs-only)
    h = jnp.dot(_mxu(x_ref[:], mxu_bf16), _mxu(w1_ref[:], mxu_bf16).T,
                preferred_element_type=jnp.float32)
    da = jnp.dot(_mxu(dy_ref[:], mxu_bf16), _mxu(w2_ref[:], mxu_bf16),
                 preferred_element_type=jnp.float32)
    dh_dtype = jnp.bfloat16 if mxu_bf16 else x_ref.dtype
    dh = jnp.where(h <= 0.0, 0.0, da).astype(dh_dtype)
    acc_ref[:] += jnp.dot(dh, _mxu(w1_ref[:], mxu_bf16),
                          preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(1) - 1)
    def _():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def ffn_bwd_dx_pallas(dy: jax.Array, w1: jax.Array, w2: jax.Array,
                      x: jax.Array, *, block_t: int | None = None,
                      block_f: int | None = None,
                      interpret: bool = False,
                      mxu_bf16: bool | None = None) -> jax.Array:
    """Input gradient ``dx = (relu'(x w1^T) * (dy w2)) w1`` fused."""
    T, d = x.shape
    ffn = w1.shape[0]
    bt = _pick_block(T, block_t or _env_block("PALLAS_FFN_BT", 256),
                     _TOKEN_QUANTUM)
    bf = _pick_block(ffn, block_f or _env_block("PALLAS_FFN_BF", 512),
                     _FFN_QUANTUM)
    grid = (T // bt, ffn // bf)
    return pl.pallas_call(
        functools.partial(_bwd_dx_kernel,
                          mxu_bf16=_resolve_mxu_bf16(mxu_bf16, interpret)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, d), lambda i, k: (i, 0)),   # x tile
            pl.BlockSpec((bt, d), lambda i, k: (i, 0)),   # dy tile
            pl.BlockSpec((bf, d), lambda i, k: (k, 0)),   # w1 slice
            pl.BlockSpec((d, bf), lambda i, k: (0, k)),   # w2 slice
        ],
        out_specs=pl.BlockSpec((bt, d), lambda i, k: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, dy, w1, w2)


def _bwd_dw_kernel(x_ref, dy_ref, w1_ref, w2_ref, dw1_ref, dw2_ref,
                   acc1_ref, acc2_ref, *, mxu_bf16):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        acc1_ref[:] = jnp.zeros_like(acc1_ref)
        acc2_ref[:] = jnp.zeros_like(acc2_ref)

    x_m = _mxu(x_ref[:], mxu_bf16)
    dy_m = _mxu(dy_ref[:], mxu_bf16)
    h = jnp.dot(x_m, _mxu(w1_ref[:], mxu_bf16).T,
                preferred_element_type=jnp.float32)
    op_dtype = jnp.bfloat16 if mxu_bf16 else x_ref.dtype
    a = jnp.maximum(h, 0.0).astype(op_dtype)
    da = jnp.dot(dy_m, _mxu(w2_ref[:], mxu_bf16),
                 preferred_element_type=jnp.float32)
    dh = jnp.where(h <= 0.0, 0.0, da).astype(op_dtype)
    # dw1 slice [bf, d] = dh^T x ; dw2 slice [d, bf] = dy^T a
    acc1_ref[:] += jnp.dot(dh.T, x_m, preferred_element_type=jnp.float32)
    acc2_ref[:] += jnp.dot(dy_m.T, a, preferred_element_type=jnp.float32)

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        dw1_ref[:] = acc1_ref[:].astype(dw1_ref.dtype)
        dw2_ref[:] = acc2_ref[:].astype(dw2_ref.dtype)


def ffn_bwd_dw_pallas(dy: jax.Array, w1: jax.Array, w2: jax.Array,
                      x: jax.Array, *, block_t: int | None = None,
                      block_f: int | None = None,
                      interpret: bool = False,
                      mxu_bf16: bool | None = None):
    """Both weight gradients, fused, reducing over token tiles:
    ``dw1 = (relu'(h) * (dy w2))^T x``, ``dw2 = dy^T relu(h)``.

    ``block_f`` defaults lower than the other kernels: this one holds TWO
    f32 accumulators plus both weight-grad output blocks in VMEM, and at
    ``block_f=512``/d=768 that footprint (with double buffering) exceeds
    the 16 MB v5e VMEM — the compiler dies at the bench shape (measured;
    256 compiles and runs)."""
    T, d = x.shape
    ffn = w1.shape[0]
    bt = _pick_block(T, block_t or _env_block("PALLAS_FFN_BT", 256),
                     _TOKEN_QUANTUM)
    bf = _pick_block(ffn, block_f or _env_block("PALLAS_FFN_DW_BF", 256),
                     _FFN_QUANTUM)
    grid = (ffn // bf, T // bt)  # token axis is the reduction
    return pl.pallas_call(
        functools.partial(_bwd_dw_kernel,
                          mxu_bf16=_resolve_mxu_bf16(mxu_bf16, interpret)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, d), lambda j, t: (t, 0)),   # x tile
            pl.BlockSpec((bt, d), lambda j, t: (t, 0)),   # dy tile
            pl.BlockSpec((bf, d), lambda j, t: (j, 0)),   # w1 slice
            pl.BlockSpec((d, bf), lambda j, t: (0, j)),   # w2 slice
        ],
        out_specs=[
            pl.BlockSpec((bf, d), lambda j, t: (j, 0)),   # dw1 slice
            pl.BlockSpec((d, bf), lambda j, t: (0, j)),   # dw2 slice
        ],
        out_shape=[jax.ShapeDtypeStruct(w1.shape, w1.dtype),
                   jax.ShapeDtypeStruct(w2.shape, w2.dtype)],
        scratch_shapes=[pltpu.VMEM((bf, d), jnp.float32),
                        pltpu.VMEM((d, bf), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, dy, w1, w2)


def ffn_bwd_pallas(dy, w1, w2, x, *, interpret: bool = False):
    """Full-block VJP from the fused kernels — same signature as
    ``ops.ffn.ffn_bwd``: returns ``(dx, (dw1, dw2))``."""
    dx = ffn_bwd_dx_pallas(dy, w1, w2, x, interpret=interpret)
    dw1, dw2 = ffn_bwd_dw_pallas(dy, w1, w2, x, interpret=interpret)
    return dx, (dw1, dw2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def pallas_ffn_block(w1, w2, x, interpret=False):
    """FFN block computed by the fused kernels, differentiated by them too."""
    return ffn_fwd_pallas(w1, w2, x, interpret=interpret)


def _block_fwd(w1, w2, x, interpret):
    return ffn_fwd_pallas(w1, w2, x, interpret=interpret), (w1, w2, x)


def _block_bwd(interpret, res, dy):
    w1, w2, x = res
    dx, (dw1, dw2) = ffn_bwd_pallas(dy, w1, w2, x, interpret=interpret)
    return dw1, dw2, dx


pallas_ffn_block.defvjp(_block_fwd, _block_bwd)
