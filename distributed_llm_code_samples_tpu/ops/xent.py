"""Softmax cross-entropy, hand-differentiated (no autograd).

The reference never computes a loss — its "gradient from the right" is a
mocked ``dloss_dx`` (``train_ffns.py:12, :30, :149-150``). The language-model
family replaces the mock with the real LM objective, and the objective gets
the same first-principles treatment as the rest of the numerical core
(``train_ffns.py:33-52``): forward written out via a stable logsumexp,
backward derived by hand (``softmax - onehot``), installed as a
``custom_vjp`` and checked against ``jax.grad`` in the tests.

Mean reduction over rows: ``loss = mean_i( lse_i - z_i[t_i] )`` where
``lse_i = logsumexp(z_i)``. The VJP is the classic
``dz_i = (softmax(z_i) - onehot(t_i)) * dy / N``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def xent_fwd(logits: jax.Array, targets: jax.Array):
    """Row-mean cross-entropy. ``logits [N, V]`` float, ``targets [N]`` int.

    Returns ``(loss, (softmax, targets))`` — the softmax is the only
    residual the manual backward needs (the logsumexp subsumes the max
    trick; no ``[N, V]`` one-hot is ever materialized).
    """
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    sumexp = jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True)
    lse = jnp.log(sumexp) + m                                  # [N, 1]
    target_z = jnp.take_along_axis(logits, targets[:, None], axis=-1)
    loss = jnp.mean(lse - target_z)
    return loss, (jnp.exp(shifted) / sumexp, targets)


def xent_bwd(dy: jax.Array, probs: jax.Array, targets: jax.Array):
    """Manual VJP: ``dlogits = dy/N * (softmax - onehot(targets))``.

    The one-hot subtraction is a scatter-add on the target column, not a
    dense ``[N, V]`` one-hot product.
    """
    n = probs.shape[0]
    dz = probs * (dy / n)
    return dz.at[jnp.arange(n), targets].add(-dy / n)


@jax.custom_vjp
def xent_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Cross-entropy whose differentiation rule is the hand-written VJP.

    ``targets`` is non-differentiable (integer class ids); its cotangent
    slot returns None.
    """
    loss, _ = xent_fwd(logits, targets)
    return loss


def _xent_bwd(res, dy):
    probs, targets = res
    return xent_bwd(dy, probs, targets), None


xent_loss.defvjp(xent_fwd, _xent_bwd)
