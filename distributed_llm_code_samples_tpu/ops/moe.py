"""MoE routing + dispatch/combine ops (single-device oracle for EP).

Built TPU-first: the router is top-k (k=1 Switch-style, k=2 GShard-style)
with a **static capacity** per expert, and dispatch/combine are dense
one-hot einsums — every shape is static, every FLOP lands on the MXU, and
there is no data-dependent control flow for XLA to choke on.

Capacity semantics (Switch/GShard): tokens overflowing an expert's
capacity are dropped from the expert computation; the *stack* passes every
token through a residual connection (``moe_stack_fwd``), so a dropped
token keeps its input activation instead of zeroing out for the rest of
the stack — the standard Switch drop behavior. ``moe_layer`` itself (the
raw layer, no residual) emits zeros for dropped tokens. With k=2, rank-0
choices of *all* tokens claim slots before any rank-1 choice (choice-major
priority), the GShard ordering.

Load balancing: ``router_aux_loss`` is the Switch auxiliary loss
``E * sum_e f_e * P_e`` (``f_e`` = fraction of tokens whose top-1 choice
is expert ``e``, ``P_e`` = mean router probability of ``e``) — minimized
at uniform routing, differentiable through ``P_e``. Trainers add
``aux_coef * d(aux)/d(params)`` to the gradients.

Differentiation follows the framework's stance (``train_ffns.py:1-3``): the
expert FFN compute runs the hand-written ``ffn_block`` VJP (vmapped over
experts); dispatch/combine are *linear* one-hot contractions whose VJPs are
exact transposes that ``jax.vjp`` composes; the router gradient flows
through the softmax gate that scales the combine (the argmax one-hot itself
is piecewise-constant — zero gradient — as in Switch).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .ffn import ffn_block


def expert_capacity(tokens: int, n_experts: int,
                    capacity_factor: float = 2.0) -> int:
    """Static per-expert slot count: ``ceil(tokens/E * factor)``."""
    return max(1, int(math.ceil(tokens / n_experts * capacity_factor)))


def route_top1(wg: jax.Array, x: jax.Array):
    """Top-1 router. ``wg [E, d]``, ``x [T, d]`` -> ``(idx [T], gate [T])``
    where ``gate`` is the chosen expert's softmax probability (the
    differentiable path to the router weights)."""
    logits = x @ wg.T                      # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits, axis=-1)      # [T]
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    return idx, gate


def route_topk(wg: jax.Array, x: jax.Array, k: int = 2,
               renormalize: bool = True):
    """Top-k router. Returns ``(idx [T, k], gates [T, k])``; with
    ``renormalize`` the k gates sum to 1 per token (the GShard top-2
    convention; k=1 + renormalize=False reduces to ``route_top1``)."""
    logits = x @ wg.T                              # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    _, idx = jax.lax.top_k(logits, k)              # [T, k], distinct experts
    gates = jnp.take_along_axis(probs, idx, axis=-1)
    if renormalize:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return idx, gates


def dispatch_tensor(idx: jax.Array, n_experts: int, capacity: int,
                    dtype=jnp.float32):
    """One-hot dispatch ``D [T, E, C]``: ``D[t, e, c] = 1`` iff token ``t``
    is the ``c``-th token routed to expert ``e`` (first-come-first-served in
    token order; overflow rows are all-zero — the token is dropped).

    Slot positions are counted in f32 regardless of ``dtype`` (a bf16
    cumsum misorders slots past 256 tokens); only the output adopts it.
    """
    onehot = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)   # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot           # [T, E]
    keep = (pos < capacity).astype(jnp.float32) * onehot
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)                      # [T, E, C]
    return (slot * keep[:, :, None]).astype(dtype)


def dispatch_tensor_topk(idx: jax.Array, n_experts: int, capacity: int,
                         dtype=jnp.float32):
    """Top-k dispatch ``D [k, T, E, C]`` with choice-major slot priority:
    every token's rank-0 choice claims its slot before any token's rank-1
    choice (GShard ordering), so under pressure second choices drop first.

    ``idx [T, k]``. Each (token, choice) pair gets at most one slot;
    summing over ``k`` gives the combined ``[T, E, C]`` dispatch (a token's
    k choices are distinct experts, so slots never collide).
    """
    t, k = idx.shape
    flat = idx.T.reshape(-1)                       # [k*T], choice-major
    disp = dispatch_tensor(flat, n_experts, capacity, dtype)  # [k*T, E, C]
    return disp.reshape(k, t, n_experts, capacity)


def _slot_positions(idx_flat: jax.Array, n_experts: int, capacity: int):
    """Per-(token, choice) slot bookkeeping without the ``[N, E, C]``
    tensor: position of each flat choice within its chosen expert
    (first-come-first-served in flat order — identical semantics to
    ``dispatch_tensor``'s cumsum) and the capacity keep-mask. O(N*E)
    elementwise work, no O(N*E*C) anything."""
    onehot = jax.nn.one_hot(idx_flat, n_experts, dtype=jnp.float32)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot - onehot,
                  axis=-1)                                     # [N]
    keep = pos < capacity
    return pos.astype(jnp.int32), keep


def route_flat(wg: jax.Array, x: jax.Array, k: int):
    """Routing in the scatter paths' flat choice-major layout:
    ``(idx_flat [k*T], gates [T, k])`` — rank-0 choices of all tokens
    precede any rank-1 choice, the GShard priority order."""
    if k == 1:
        idx, gates = route_top1(wg, x)
        return idx, gates[:, None]
    idx2, gates = route_topk(wg, x, k)
    return idx2.T.reshape(-1), gates


def scatter_dispatch(idx_flat: jax.Array, x: jax.Array, n_experts: int,
                     capacity: int):
    """Scatter tokens into the ``[E, C, d]`` expert-slot buffer:
    O(N*d) movement, dropped choices land in a dummy row that is sliced
    off. Returns ``(xe [E, C, d], dest [N], keep [N])`` — ``dest`` and
    ``keep`` feed ``scatter_combine``. Shared by the single-device and
    EP scatter paths so the slot bookkeeping cannot drift."""
    t, d = x.shape
    pos, keep = _slot_positions(idx_flat, n_experts, capacity)
    dest = jnp.where(keep, idx_flat * capacity + pos,
                     n_experts * capacity)
    tok = jnp.tile(jnp.arange(t), idx_flat.shape[0] // t)
    xe = jnp.zeros((n_experts * capacity + 1, d),
                   x.dtype).at[dest].add(x[tok])
    return xe[:-1].reshape(n_experts, capacity, d), dest, keep


def scatter_combine(ye: jax.Array, dest: jax.Array, keep: jax.Array,
                    gates: jax.Array, t: int) -> jax.Array:
    """Gather expert outputs back to their tokens and apply the gate
    scale: ``ye [E, C, d]`` -> ``[t, d]`` (dropped choices contribute
    zero via the dummy row)."""
    ec, d = ye.shape[0] * ye.shape[1], ye.shape[-1]
    padded = jnp.concatenate([ye.reshape(ec, d),
                              jnp.zeros((1, d), ye.dtype)])
    y_choice = padded[dest] * keep[:, None].astype(ye.dtype)
    return jnp.einsum("ktd,tk->td", y_choice.reshape(-1, t, d),
                      gates.astype(ye.dtype))


def gather_metadata(idx_flat: jax.Array, t: int, n_experts: int,
                    capacity: int):
    """Routing metadata for the gather dispatch: ``dest [N]`` (each flat
    choice's slot, dummy ``E*C`` when dropped), ``slot_tok [E*C]`` (the
    token filling each slot, dummy ``t`` when unclaimed), ``slot_choice
    [E*C]`` (the flat choice claiming each slot, dummy ``N``), ``keep
    [N]``. The only scatters in the whole gather path live here, and
    they move O(N) int32 elements — not O(N*d) rows."""
    n = idx_flat.shape[0]
    pos, keep = _slot_positions(idx_flat, n_experts, capacity)
    dest = jnp.where(keep, idx_flat * capacity + pos,
                     n_experts * capacity)
    tok = jnp.tile(jnp.arange(t, dtype=jnp.int32), n // t)
    slots = n_experts * capacity
    slot_tok = jnp.full((slots + 1,), t, jnp.int32).at[dest].set(tok)
    slot_choice = jnp.full((slots + 1,), n, jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32))
    return dest, slot_tok[:-1], slot_choice[:-1], keep


@jax.custom_vjp
def permute_to_slots(x: jax.Array, dest: jax.Array, slot_tok: jax.Array):
    """Dispatch as a PERMUTATION GATHER: ``xe[s] = x[slot_tok[s]]``
    (zero row for unclaimed slots). The kept (token, choice) -> slot map
    is a bijection, so the VJP is ALSO a gather — ``dx[t] = sum_k
    dxe[dest[k*T + t]]`` — instead of the scatter-add ``jax.vjp`` would
    derive from a forward scatter. On TPU gathers vectorize while
    scatter serializes; this removes every O(N*d) scatter from the
    dispatch path, both directions."""
    xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])
    return xp[slot_tok]                                   # [E*C, d]


def _pts_fwd(x, dest, slot_tok):
    return permute_to_slots(x, dest, slot_tok), (x.shape[0], dest)


def _pts_bwd(res, dxe):
    t, dest = res
    dxp = jnp.concatenate([dxe, jnp.zeros((1, dxe.shape[1]), dxe.dtype)])
    dx = jnp.sum(dxp[dest].reshape(-1, t, dxe.shape[1]), axis=0)
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # noqa: E731
    return dx, f0(dest), f0(jnp.zeros(dxe.shape[0], jnp.int32))


def _combine_gather(ye_flat, dest, keep, gates, t):
    """Shared fwd math: gather each choice's slot row, gate-scale, sum
    over choices. ``ye_flat [E*C, d]``."""
    d = ye_flat.shape[-1]
    padded = jnp.concatenate([ye_flat, jnp.zeros((1, d), ye_flat.dtype)])
    y_choice = padded[dest] * keep[:, None].astype(ye_flat.dtype)
    return jnp.einsum("ktd,tk->td", y_choice.reshape(-1, t, d),
                      gates.astype(ye_flat.dtype)), y_choice


@jax.custom_vjp
def combine_from_slots(ye: jax.Array, gates: jax.Array, dest: jax.Array,
                       slot_tok: jax.Array, slot_choice: jax.Array,
                       keep: jax.Array):
    """Combine with a gather-only VJP. Forward is ``scatter_combine``'s
    math exactly (gather slot rows by ``dest``, gate-scale, sum over
    choices); the backward uses the slot->token/choice inverse maps so
    ``dye[s] = gate[slot_choice[s]] * dy[slot_tok[s]]`` is a gather too
    — where autodiff's transpose of the forward gather would be an
    O(N*d) scatter-add."""
    ye_flat = ye.reshape(-1, ye.shape[-1])
    t = gates.shape[0]
    y, _ = _combine_gather(ye_flat, dest, keep, gates, t)
    return y


def _cfs_fwd(ye, gates, dest, slot_tok, slot_choice, keep):
    ye_flat = ye.reshape(-1, ye.shape[-1])
    t = gates.shape[0]
    y, y_choice = _combine_gather(ye_flat, dest, keep, gates, t)
    return y, (y_choice, gates, dest, slot_tok, slot_choice, keep,
               ye.shape)


def _cfs_bwd(res, dy):
    y_choice, gates, dest, slot_tok, slot_choice, keep, ye_shape = res
    t, k = gates.shape
    d = dy.shape[-1]
    # dye[s]: the gate of the choice that claimed s, times dy of the
    # token that claimed s — dummy rows of the padded operands make
    # unclaimed slots come out exactly zero
    gates_flat = (gates.T.reshape(-1)
                  * keep.astype(gates.dtype))            # [k*T] choice-major
    gates_pad = jnp.concatenate([gates_flat,
                                 jnp.zeros((1,), gates.dtype)])
    dy_pad = jnp.concatenate([dy, jnp.zeros((1, d), dy.dtype)])
    dye = (gates_pad[slot_choice][:, None].astype(dy.dtype)
           * dy_pad[slot_tok]).reshape(ye_shape)
    # dgates[t, k] = <dy[t], y_choice[k, t]> (y_choice already carries
    # the keep mask; it is the UN-gated slot row gathered in fwd)
    dgates = jnp.einsum("td,ktd->tk",
                        dy, y_choice.reshape(k, t, d)).astype(gates.dtype)
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # noqa: E731
    return (dye, dgates, f0(dest), f0(slot_tok), f0(slot_choice),
            np.zeros(keep.shape, jax.dtypes.float0))


permute_to_slots.defvjp(_pts_fwd, _pts_bwd)
combine_from_slots.defvjp(_cfs_fwd, _cfs_bwd)


def moe_layer_gather(wg: jax.Array, w1: jax.Array, w2: jax.Array,
                     x: jax.Array, capacity_factor: float = 2.0,
                     k: int = 1, capacity: int | None = None
                     ) -> jax.Array:
    """``moe_layer`` with the gather dispatch: identical routing,
    capacity drops, and GShard choice-major priority — but every
    O(T*d) data movement in BOTH directions is a gather
    (``permute_to_slots`` / ``combine_from_slots``), with only O(k*T)
    int32 scatters for the slot bookkeeping. The third dispatch
    formulation next to ``moe_layer`` (one-hot einsums, O(k*T^2*cf*d)
    MXU work) and ``moe_layer_scatter`` (scatter-add rows, serialized
    on TPU); bench_moe.py records which one the chip defends."""
    n_experts = w1.shape[0]
    t = x.shape[0]
    cap = (expert_capacity(t, n_experts, capacity_factor)
           if capacity is None else capacity)
    idx_flat, gates = route_flat(wg, x, k)
    dest, slot_tok, slot_choice, keep = gather_metadata(
        idx_flat, t, n_experts, cap)
    xe = permute_to_slots(x, dest, slot_tok).reshape(n_experts, cap, -1)
    ye = jax.vmap(ffn_block)(w1, w2, xe)
    return combine_from_slots(ye, gates, dest, slot_tok, slot_choice,
                              keep)


def moe_layer_scatter(wg: jax.Array, w1: jax.Array, w2: jax.Array,
                      x: jax.Array, capacity_factor: float = 2.0,
                      k: int = 1, capacity: int | None = None
                      ) -> jax.Array:
    """``moe_layer`` with scatter/gather dispatch — same routing, same
    capacity drops, same GShard choice-major priority, bitwise-same
    top-k/gates — but the token movement is O(T*d) scatter-add into the
    ``[E*C, d]`` expert buffer and an O(T*d) gather back, instead of the
    dense one-hot einsums' O(T*E*C*d) MXU work (``T*E*C = k*T^2 *
    capacity_factor``: QUADRATIC in tokens at fixed capacity factor,
    which at bench scale dwarfs the expert FFN compute itself).

    Every shape is static: dropped choices scatter into a dummy row
    (``E*C``) that is sliced off before the expert compute. All moves
    are linear (scatter-add / gather), so ``jax.vjp`` differentiates
    them exactly, and the router gradient still flows through the gate
    scale — the framework's linear-op stance unchanged. Differential-
    pinned leaf-for-leaf against ``moe_layer`` (tests/test_moe.py)."""
    n_experts = w1.shape[0]
    t = x.shape[0]
    cap = (expert_capacity(t, n_experts, capacity_factor)
           if capacity is None else capacity)
    idx_flat, gates = route_flat(wg, x, k)
    xe, dest, keep = scatter_dispatch(idx_flat, x, n_experts, cap)
    ye = jax.vmap(ffn_block)(w1, w2, xe)
    return scatter_combine(ye, dest, keep, gates, t)


def router_aux_loss(wg: jax.Array, x: jax.Array) -> jax.Array:
    """Switch load-balancing loss ``E * sum_e f_e * P_e`` on one layer's
    input tokens. ``f_e`` uses the (non-differentiable) top-1 assignment;
    the gradient flows through ``P_e``. Equals 1 at perfectly uniform
    routing; rises as routing collapses."""
    logits = x @ wg.T
    n_experts = wg.shape[0]
    probs = jax.nn.softmax(logits, axis=-1)                  # [T, E]
    top1 = jax.lax.stop_gradient(
        jax.nn.one_hot(jnp.argmax(logits, axis=-1), n_experts,
                       dtype=probs.dtype))
    f = jnp.mean(top1, axis=0)                               # [E]
    p = jnp.mean(probs, axis=0)                              # [E]
    return n_experts * jnp.sum(f * p)


def moe_layer(wg: jax.Array, w1: jax.Array, w2: jax.Array, x: jax.Array,
              capacity_factor: float = 2.0, k: int = 1,
              capacity: int | None = None) -> jax.Array:
    """One MoE FFN layer, dense single-device form (no residual here —
    the stack adds it).

    ``wg [E, d]``, ``w1 [E, ffn, d]``, ``w2 [E, d, ffn]``, ``x [T, d]``.
    Dispatch -> per-expert hand-VJP FFN (``ffn_block`` vmapped over the
    expert axis) -> gate-scaled combine. Dropped (token, choice) pairs
    contribute zero. ``capacity`` overrides the per-expert slot count
    (the EP-emulating dense oracle passes the grouped EP capacity, which
    ceil-rounds differently from deriving it from this ``x``'s tokens).
    """
    n_experts = w1.shape[0]
    cap = (expert_capacity(x.shape[0], n_experts, capacity_factor)
           if capacity is None else capacity)
    if k == 1:
        idx, gate = route_top1(wg, x)
        disp = dispatch_tensor(idx, n_experts, cap, x.dtype)  # [T, E, C]
        comb = disp * gate[:, None, None]
    else:
        idx, gates = route_topk(wg, x, k)
        disp_k = dispatch_tensor_topk(idx, n_experts, cap, x.dtype)
        disp = jnp.sum(disp_k, axis=0)                        # [T, E, C]
        comb = jnp.einsum("ktec,tk->tec", disp_k, gates)
    xe = jnp.einsum("tec,td->ecd", disp, x)                   # [E, C, d]
    ye = jax.vmap(ffn_block)(w1, w2, xe)                      # [E, C, d]
    return jnp.einsum("tec,ecd->td", comb, ye)


def moe_stack_fwd_aux(params, x: jax.Array, capacity_factor: float = 2.0,
                      k: int = 1, capacity: int | None = None,
                      dispatch: str = "dense"):
    """Stack of MoE layers (``MoEStackParams``) with a residual around each
    layer (Switch semantics: a capacity-dropped token passes through
    unchanged rather than zeroing for the rest of the stack). Returns
    ``(y, aux)`` where ``aux`` is the total ``router_aux_loss``, each
    layer scored on its own residual-chained input — one walk computes
    both, so trainers can take a single ``vjp`` with cotangents
    ``(dloss_dx, aux_coef)``. ``dispatch`` selects the token movement:
    ``"dense"`` one-hot einsums, ``"scatter"`` (``moe_layer_scatter`` —
    same math, O(T*d) scatter-add movement), or ``"gather"``
    (``moe_layer_gather`` — gather-only movement both directions)."""
    layers = {"dense": moe_layer, "scatter": moe_layer_scatter,
              "gather": moe_layer_gather}
    if dispatch not in layers:
        raise ValueError(f"unknown dispatch {dispatch!r}")
    layer = layers[dispatch]
    aux = jnp.asarray(0.0, jnp.float32)
    for l in range(params.w1.shape[0]):
        aux = aux + router_aux_loss(params.wg[l], x)
        x = x + layer(params.wg[l], params.w1[l], params.w2[l], x,
                      capacity_factor, k, capacity)
    return x, aux


def moe_stack_fwd(params, x: jax.Array, capacity_factor: float = 2.0,
                  k: int = 1, capacity: int | None = None,
                  dispatch: str = "dense") -> jax.Array:
    """Output half of ``moe_stack_fwd_aux``."""
    return moe_stack_fwd_aux(params, x, capacity_factor, k, capacity,
                             dispatch)[0]


def moe_stack_aux(params, x: jax.Array, capacity_factor: float = 2.0,
                  k: int = 1, capacity: int | None = None) -> jax.Array:
    """Aux half of ``moe_stack_fwd_aux``."""
    return moe_stack_fwd_aux(params, x, capacity_factor, k, capacity)[1]
