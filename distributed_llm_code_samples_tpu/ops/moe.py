"""MoE routing + dispatch/combine ops (single-device oracle for EP).

Built TPU-first: the router is top-1 (Switch-style) with a **static
capacity** per expert, and dispatch/combine are dense one-hot einsums —
every shape is static, every FLOP lands on the MXU, and there is no
data-dependent control flow for XLA to choke on. Tokens overflowing an
expert's capacity are dropped (emit zeros), the standard Switch behavior;
with the default ``capacity_factor`` sized for the test workloads nothing
drops.

Differentiation follows the framework's stance (``train_ffns.py:1-3``): the
expert FFN compute runs the hand-written ``ffn_block`` VJP (vmapped over
experts); dispatch/combine are *linear* one-hot contractions whose VJPs are
exact transposes that ``jax.vjp`` composes; the router gradient flows
through the softmax gate that scales the combine (the argmax one-hot itself
is piecewise-constant — zero gradient — as in Switch).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .ffn import ffn_block


def expert_capacity(tokens: int, n_experts: int,
                    capacity_factor: float = 2.0) -> int:
    """Static per-expert slot count: ``ceil(tokens/E * factor)``."""
    return max(1, int(math.ceil(tokens / n_experts * capacity_factor)))


def route_top1(wg: jax.Array, x: jax.Array):
    """Top-1 router. ``wg [E, d]``, ``x [T, d]`` -> ``(idx [T], gate [T])``
    where ``gate`` is the chosen expert's softmax probability (the
    differentiable path to the router weights)."""
    logits = x @ wg.T                      # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(logits, axis=-1)      # [T]
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    return idx, gate


def dispatch_tensor(idx: jax.Array, n_experts: int, capacity: int,
                    dtype=jnp.float32):
    """One-hot dispatch ``D [T, E, C]``: ``D[t, e, c] = 1`` iff token ``t``
    is the ``c``-th token routed to expert ``e`` (first-come-first-served in
    token order; overflow rows are all-zero — the token is dropped).

    Slot positions are counted in f32 regardless of ``dtype`` (a bf16
    cumsum misorders slots past 256 tokens); only the output adopts it.
    """
    onehot = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)   # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot           # [T, E]
    keep = (pos < capacity).astype(jnp.float32) * onehot
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)                      # [T, E, C]
    return (slot * keep[:, :, None]).astype(dtype)


def moe_layer(wg: jax.Array, w1: jax.Array, w2: jax.Array, x: jax.Array,
              capacity_factor: float = 2.0) -> jax.Array:
    """One MoE FFN layer, dense single-device form.

    ``wg [E, d]``, ``w1 [E, ffn, d]``, ``w2 [E, d, ffn]``, ``x [T, d]``.
    Dispatch -> per-expert hand-VJP FFN (``ffn_block`` vmapped over the
    expert axis) -> gate-scaled combine. Dropped tokens produce zeros.
    """
    n_experts = w1.shape[0]
    cap = expert_capacity(x.shape[0], n_experts, capacity_factor)
    idx, gate = route_top1(wg, x)
    disp = dispatch_tensor(idx, n_experts, cap, x.dtype)          # [T, E, C]
    xe = jnp.einsum("tec,td->ecd", disp, x)                       # [E, C, d]
    ye = jax.vmap(ffn_block)(w1, w2, xe)                          # [E, C, d]
    comb = disp * gate[:, None, None]
    return jnp.einsum("tec,ecd->td", comb, ye)


def moe_stack_fwd(params, x: jax.Array,
                  capacity_factor: float = 2.0) -> jax.Array:
    """Stack of MoE layers (``MoEStackParams``), block input chaining like
    the dense stack (``train_ffns.py:72-81``)."""
    for l in range(params.w1.shape[0]):
        x = moe_layer(params.wg[l], params.w1[l], params.w2[l], x,
                      capacity_factor)
    return x
