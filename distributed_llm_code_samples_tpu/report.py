"""`report` — fold a run's telemetry streams into one human-readable
run report.

Inputs (all optional except the metrics dir):

- the metrics JSONL a ``--metrics_dir`` run wrote
  (``runtime/telemetry.py`` schema: per-step records + recovery/chaos
  events + run meta),
- supervise's per-attempt JSONL (``runtime/failure.py``) — passed with
  ``--attempt_log`` or auto-discovered from the run's meta records,
- a profile directory (``--profile_dir``) captured with
  ``--profile_dir`` / ``jax.profiler.trace`` — folded through
  ``utils/trace_analysis`` into comm/compute overlap and per-named-scope
  region totals.

Output: step-time percentiles, throughput, MFU, HBM high-water, and ONE
merged timeline carrying training progress, faults, recovery attempts,
and post-recovery steps in wall-clock order — the "what happened to this
run" view the reference answered with scattered prints
(``train_ffns.py:378-382``).

Exit codes: 0 = report rendered (schema problems are listed, not
fatal); 2 = no usable metrics stream.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

from .runtime.telemetry import METRICS_FILENAME, read_metrics


def _fmt_bytes(n: int | None) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024:
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} PiB"


def _fmt_t(t: float, t0: float) -> str:
    return f"+{t - t0:8.2f}s"


def _load_attempt_log(path: str) -> list[dict]:
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    pass  # torn line — the stream survives a crash
    except OSError:
        return []
    return records


def _describe_step(rec: dict) -> str:
    bits = [f"step {rec['step']}"]
    if rec.get("strategy"):
        bits[0] = f"{rec['strategy']} {bits[0]}"
    if rec.get("loss") is not None:
        bits.append(f"loss {rec['loss']:.4f}")
    if rec.get("grad_norm") is not None:
        bits.append(f"|g| {rec['grad_norm']:.4f}")
    if rec.get("step_time_s") is not None:
        bits.append(f"{rec['step_time_s'] * 1e3:.1f} ms/step")
    if rec.get("tokens_per_sec") is not None:
        bits.append(f"{rec['tokens_per_sec']:.0f} tok/s")
    if rec.get("mfu") is not None:
        bits.append(f"mfu {rec['mfu']:.3f}")
    return "  ".join(bits)


def _describe_event(rec: dict) -> str:
    ev = rec.get("event", "?")
    if ev == "published":
        a, b = rec.get("steps", (None, None))
        return f"checkpoint published @ step {rec.get('step')} " \
               f"(steps {a}..{b})"
    if ev == "nonfinite_skip":
        a, b = rec.get("steps", (None, None))
        return f"NON-FINITE params after steps {a}..{b} — segment " \
               "skipped, not checkpointed"
    if ev == "anomaly" or rec.get("kind") == "anomaly":
        a, b = rec.get("steps", (None, None))
        return (f"ANOMALY: {rec.get('skipped')} step(s) skipped "
                f"in-graph in {a}..{b} (total "
                f"{rec.get('total_skipped')}, loss scale "
                f"{rec.get('loss_scale')})")
    if ev == "loss_spike":
        a, b = rec.get("steps", (None, None))
        return (f"LOSS SPIKE: update norm {rec.get('delta')} after "
                f"steps {a}..{b} vs baseline {rec.get('baseline')} "
                f"(> {rec.get('factor')}x) — segment not checkpointed")
    if ev == "rollback" or rec.get("kind") == "rollback":
        return (f"ROLLBACK #{rec.get('rollback')}: rewound to verified "
                f"step {rec.get('resume_step')} in-process — "
                f"{rec.get('error')} ({rec.get('max_rollbacks')} max)")
    if ev == "elastic_resume":
        return (f"ELASTIC RESUME @ step {rec.get('step')}: "
                f"{rec.get('saved_shards')} -> "
                f"{rec.get('current_shards')} data shard(s), "
                f"seed_accum {rec.get('seed_accum')} "
                f"({rec.get('n_devices')} device(s))")
    if ev == "attempt_failed":
        extra = " [watchdog expired]" if rec.get("watchdog_expired") else ""
        return (f"FAULT: attempt {rec.get('attempt')} failed after "
                f"{rec.get('elapsed_s')}s — {rec.get('error')}"
                f"{extra}; {rec.get('restarts_left')} restart(s) left, "
                f"backoff {rec.get('backoff_s')}s")
    if ev == "completed":
        return (f"RECOVERED: attempt {rec.get('attempt')} completed "
                f"after {rec.get('elapsed_s')}s"
                + (f" ({rec.get('rollbacks')} rollback(s))"
                   if rec.get("rollbacks") else ""))
    if ev == "chaos_corrupt_ckpt":
        return (f"CHAOS: checkpoint corruption injected at "
                f"step {rec.get('step')}")
    if ev == "hung_step":
        return (f"HUNG STEP @ engine step {rec.get('step')} — watchdog "
                f"{rec.get('watchdog_ms')}ms expired")
    if ev == "resumed":
        return (f"RESUMED from engine snapshot step {rec.get('step')} "
                f"({rec.get('live_requests')} live request(s), "
                f"{rec.get('finished')} already finished)")
    if ev == "chaos_kill":
        return f"CHAOS: SIGKILL after engine snapshot step {rec.get('step')}"
    return f"{ev}: " + ", ".join(
        f"{k}={v}" for k, v in rec.items()
        if k not in ("event", "t", "kind", "schema"))


def report_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="report",
        description="Fold a --metrics_dir run (+ supervise attempt log "
                    "+ optional profile dir) into one run report")
    p.add_argument("metrics_dir",
                   help="the run's --metrics_dir (holds metrics.jsonl)")
    p.add_argument("--attempt_log", default=None,
                   help="supervise's per-attempt JSONL (default: "
                        "discovered from the run's meta records)")
    p.add_argument("--profile_dir", default=None,
                   help="a trace directory captured with --profile_dir; "
                        "adds comm/compute overlap + per-named-scope "
                        "totals")
    p.add_argument("--json", action="store_true",
                   help="emit the folded report as one JSON object "
                        "instead of text")
    args = p.parse_args(argv)

    path = args.metrics_dir
    if os.path.isdir(path):
        path = os.path.join(path, METRICS_FILENAME)
    if not os.path.exists(path):
        print(f"report: no metrics stream at {path}", file=sys.stderr)
        return 2
    records, problems = read_metrics(path)
    if not records:
        print(f"report: {path} holds no schema-valid records "
              f"({len(problems)} problem(s))", file=sys.stderr)
        for prob in problems:
            print(f"report:   {prob}", file=sys.stderr)
        return 2

    metas = [r for r in records if r["kind"] == "meta"]
    steps = [r for r in records if r["kind"] == "step"]
    events = [r for r in records if r["kind"] == "event"]
    benches = [r for r in records if r["kind"] == "bench"]
    anomalies = [r for r in records if r["kind"] == "anomaly"]
    rollbacks = [r for r in records if r["kind"] == "rollback"]
    decodes = [r for r in records if r["kind"] == "decode"]
    # request records: drop exact replays — an in-process supervisor
    # restart resumes from a snapshot that may PREDATE records already
    # emitted, so the replayed steps re-emit identical (uid, event,
    # step) transitions (the global step is stable across restarts).
    # Legitimate repeats — a re-admission after preemption, a second
    # quarantine — land at different global steps; anonymous rejected
    # records (uid -1) are kept verbatim (distinct sheds can share a
    # step). Same stance as the attempt-log dedup below.
    requests = []
    seen_req = set()
    for r in records:
        if r["kind"] != "request":
            continue
        key = (r.get("uid"), r.get("event"), r.get("step"))
        if r.get("event") != "rejected" and key in seen_req:
            continue
        seen_req.add(key)
        requests.append(r)

    # attempt log: flag wins; else the newest meta that names one
    attempt_path = args.attempt_log
    if attempt_path is None:
        for m in reversed(metas):
            if m.get("attempt_log"):
                attempt_path = m["attempt_log"]
                break
    attempts = _load_attempt_log(attempt_path) if attempt_path else []
    if attempt_path and not attempts and not os.path.exists(attempt_path):
        problems.append(f"attempt log {attempt_path} unreadable — "
                        "recovery events missing from the timeline")

    doc: dict = {"metrics_path": path, "n_records": len(records),
                 "problems": problems}

    # ---- run header --------------------------------------------------
    header = {}
    for m in metas:  # later metas refine earlier ones
        header.update({k: v for k, v in m.items()
                       if k not in ("kind", "t", "schema")})
    doc["run"] = header

    # ---- step statistics, grouped per strategy ----------------------
    # multi-method runs (-m 0 / -m 9) interleave strategies in one
    # stream; pooled percentiles would describe no actual run
    def _stats_of(group):
        times = [s["step_time_s"] for s in group
                 if s.get("step_time_s") is not None]
        # the first logged chunk usually carries compile time; report
        # steady-state percentiles over the rest when there is a rest
        steady = times[1:] if len(times) > 1 else times
        tps = [s["tokens_per_sec"] for s in group
               if s.get("tokens_per_sec") is not None]
        mfus = [s["mfu"] for s in group if s.get("mfu") is not None]
        losses = [s["loss"] for s in group if s.get("loss") is not None]
        hbm = [max(s["hbm_high_water_bytes"].values())
               for s in group if s.get("hbm_high_water_bytes")]
        stats = {
            "logged_steps": len(group),
            "first_step": group[0]["step"],
            "last_step": group[-1]["step"],
        }
        if steady:
            q = np.percentile(np.asarray(steady, np.float64),
                              [50, 90, 99])
            stats["step_time_p50_ms"] = round(float(q[0]) * 1e3, 3)
            stats["step_time_p90_ms"] = round(float(q[1]) * 1e3, 3)
            stats["step_time_p99_ms"] = round(float(q[2]) * 1e3, 3)
        if tps:
            stats["tokens_per_sec_mean"] = round(float(np.mean(tps)), 1)
            stats["tokens_per_sec_best"] = round(float(np.max(tps)), 1)
        if mfus:
            stats["mfu_mean"] = round(float(np.mean(mfus)), 4)
            stats["mfu_best"] = round(float(np.max(mfus)), 4)
        if losses:
            stats["first_loss"] = round(losses[0], 4)
            stats["last_loss"] = round(losses[-1], 4)
        if hbm:
            stats["hbm_high_water_bytes"] = int(max(hbm))
        return stats

    if steps:
        by_strategy: dict = {}
        for s in steps:
            by_strategy.setdefault(s.get("strategy") or "run", []).append(s)
        doc["steps"] = {k: _stats_of(v) for k, v in by_strategy.items()}

    # ---- serving (decode engine) summary ----------------------------
    if decodes:
        tps = [d["tokens_per_sec"] for d in decodes
               if d.get("tokens_per_sec") is not None]
        occ = [d["batch_occupancy"] for d in decodes
               if d.get("batch_occupancy") is not None]
        util = [d["kv_pool_utilization"] for d in decodes
                if d.get("kv_pool_utilization") is not None]
        serving = {
            "records": len(decodes),
            "engine_steps": decodes[-1].get("step"),
            "tokens_generated": decodes[-1].get("tokens_generated"),
            "kv_dtype": decodes[-1].get("kv_dtype"),
            "compiled_programs": decodes[-1].get("compiled_programs"),
        }
        if tps:
            serving["tokens_per_sec_mean"] = round(float(np.mean(tps)), 1)
            serving["tokens_per_sec_best"] = round(float(np.max(tps)), 1)
        if occ:
            serving["batch_occupancy_mean"] = round(float(np.mean(occ)), 4)
        if util:
            serving["kv_pool_utilization_max"] = round(float(np.max(util)),
                                                       4)
        doc["serving"] = serving

    # ---- serving reliability (request lifecycle records) ------------
    if requests:
        by_event: dict[str, int] = {}
        for r in requests:
            by_event[r["event"]] = by_event.get(r["event"], 0) + 1
        rel = {
            "records": len(requests),
            "admitted": by_event.get("admitted", 0),
            "completed": by_event.get("completed", 0),
            "quarantined": by_event.get("quarantined", 0),
            "retried": by_event.get("retried", 0),
            "preempted": by_event.get("preempted", 0),
            # shed = load the system refused or gave up on (admission
            # rejects + deadline expiries) — the graceful-degradation
            # counter
            "shed": (by_event.get("rejected", 0)
                     + by_event.get("expired", 0)),
            "rejected": by_event.get("rejected", 0),
            "expired": by_event.get("expired", 0),
            "failed_uids": sorted({
                r["uid"] for r in requests
                if (r["event"] == "expired"
                    or (r["event"] == "quarantined"
                        and not r.get("retrying")))}),
        }
        lat = [r["latency_s"] for r in requests
               if r["event"] == "completed"
               and r.get("latency_s") is not None]
        if lat:
            q = np.percentile(np.asarray(lat, np.float64), [50, 90, 99])
            rel["latency_p50_s"] = round(float(q[0]), 4)
            rel["latency_p90_s"] = round(float(q[1]), 4)
            rel["latency_p99_s"] = round(float(q[2]), 4)
        doc["serving_reliability"] = rel

    # ---- recovery / chaos summary -----------------------------------
    fails = [a for a in attempts if a.get("event") == "attempt_failed"]
    doc["recovery"] = {
        "attempt_log": attempt_path,
        "attempts_failed": len(fails),
        "completed": any(a.get("event") == "completed" for a in attempts),
        "nonfinite_skips": sum(1 for e in events
                               if e.get("event") == "nonfinite_skip"),
        "publishes": sum(1 for e in events
                         if e.get("event") == "published"),
        # the self-healing ladder's cheap rungs (schema v2 kinds)
        "in_graph_skips": sum(int(a.get("skipped") or 0)
                              for a in anomalies),
        "rollbacks": len(rollbacks),
        "loss_spikes": sum(1 for e in events
                           if e.get("event") == "loss_spike"),
    }

    # ---- one merged timeline ----------------------------------------
    timeline = []
    for s in steps:
        timeline.append((s["t"], "step", _describe_step(s)))
    seen_events = {(e.get("t"), e.get("event")) for e in events}
    for e in events:
        timeline.append((e["t"], "event", _describe_event(e)))
    for a in anomalies:
        timeline.append((a["t"], "anomaly", _describe_event(a)))
        seen_events.add((a.get("t"), "anomaly"))
    for r in rollbacks:
        timeline.append((r["t"], "rollbck", _describe_event(r)))
        seen_events.add((r.get("t"), "rollback"))
    for d in decodes:
        bits = [f"engine step {d.get('step')}"]
        if d.get("tokens_per_sec") is not None:
            bits.append(f"{d['tokens_per_sec']:.0f} tok/s")
        if d.get("batch_occupancy") is not None:
            bits.append(f"occ {d['batch_occupancy']:.2f}")
        if d.get("kv_pool_utilization") is not None:
            bits.append(f"kv {d['kv_pool_utilization']:.2f}")
        if d.get("waiting"):
            bits.append(f"{d['waiting']} waiting")
        timeline.append((d["t"], "decode", "  ".join(bits)))
    for r in requests:
        ev = r["event"]
        bits = [f"request {r.get('uid')} {ev.upper()}"
                + (f" ({r['reason']})" if r.get("reason") else "")
                + f" @ engine step {r.get('step')}"]
        if ev == "completed":
            if r.get("latency_s") is not None:
                bits.append(f"latency {r['latency_s']:.3f}s")
            if r.get("n_new") is not None:
                bits.append(f"{r['n_new']} token(s)")
            if r.get("retries"):
                bits.append(f"{r['retries']} retry(ies)")
        elif ev == "retried":
            bits.append(f"attempt {r.get('attempt')}/"
                        f"{r.get('max_retries')}")
        elif ev == "quarantined" and not r.get("retrying"):
            bits.append("FAILED")
        timeline.append((r["t"], "request", "  ".join(bits)))
    for a in attempts:
        # supervise forwards checkpoint-layer events to its log too;
        # drop exact duplicates of what the metrics stream already has
        if (a.get("t"), a.get("event")) in seen_events:
            continue
        timeline.append((a.get("t", 0.0), "attempt", _describe_event(a)))
    timeline.sort(key=lambda x: x[0])
    doc["timeline"] = [{"t": t, "source": src, "what": what}
                       for t, src, what in timeline]

    # ---- profile folding --------------------------------------------
    if args.profile_dir:
        from .utils.trace_analysis import (load_spans, overlap_payload,
                                           scope_totals,
                                           strategy_scope_key)
        # one gunzip+parse feeds both analyses (hardware traces run to
        # hundreds of MB — never load twice)
        trace_file, spans = load_spans(args.profile_dir)
        prof = overlap_payload(spans, trace_file)
        # fold per-region totals under the RUN's strategy when the meta
        # records name one; unknown strategies fall back to the
        # prefixed-regions union (scope_totals documents why)
        scope_key = strategy_scope_key(header.get("strategy"))
        prof["scope_totals_us"] = {
            k: round(v, 1)
            for k, v in scope_totals(spans, scope_key).items() if v}
        doc["profile"] = prof

    if benches:
        doc["bench_rows"] = len(benches)

    if args.json:
        print(json.dumps(doc, indent=1))
        return 0

    # ---- render ------------------------------------------------------
    out = []
    out.append("=" * 72)
    out.append(f"RUN REPORT — {path}")
    out.append("=" * 72)
    if header:
        out.append("run config:")
        for k, v in header.items():
            out.append(f"  {k}: {v}")
    for strat, st in doc.get("steps", {}).items():
        out.append("")
        out.append(f"steps [{strat}]: {st['logged_steps']} logged "
                   f"record(s), steps {st['first_step']}.."
                   f"{st['last_step']}")
        if "step_time_p50_ms" in st:
            out.append(f"  step time   p50 {st['step_time_p50_ms']} ms  "
                       f"p90 {st['step_time_p90_ms']} ms  "
                       f"p99 {st['step_time_p99_ms']} ms "
                       "(steady-state: first logged chunk excluded)")
        if "tokens_per_sec_mean" in st:
            out.append(f"  throughput  mean {st['tokens_per_sec_mean']} "
                       f"tok/s  best {st['tokens_per_sec_best']} tok/s")
        if "mfu_mean" in st:
            out.append(f"  MFU         mean {st['mfu_mean']}  "
                       f"best {st['mfu_best']}")
        if "first_loss" in st:
            out.append(f"  loss        {st['first_loss']} -> "
                       f"{st['last_loss']}")
        if "hbm_high_water_bytes" in st:
            out.append("  HBM high-water  "
                       + _fmt_bytes(st["hbm_high_water_bytes"]))
    if "serving" in doc:
        sv = doc["serving"]
        out.append("")
        out.append(f"serving [{sv.get('kv_dtype')}]: "
                   f"{sv['records']} decode record(s), "
                   f"{sv.get('engine_steps')} engine step(s), "
                   f"{sv.get('tokens_generated')} token(s), "
                   f"{sv.get('compiled_programs')} compiled program(s)")
        if "tokens_per_sec_mean" in sv:
            out.append(f"  throughput  mean {sv['tokens_per_sec_mean']} "
                       f"tok/s  best {sv['tokens_per_sec_best']} tok/s")
        if "batch_occupancy_mean" in sv:
            out.append(f"  occupancy   mean {sv['batch_occupancy_mean']}")
        if "kv_pool_utilization_max" in sv:
            out.append("  KV pool     max utilization "
                       f"{sv['kv_pool_utilization_max']}")
    if "serving_reliability" in doc:
        rl = doc["serving_reliability"]
        out.append("")
        out.append(f"serving reliability: {rl['admitted']} admission(s), "
                   f"{rl['completed']} completed, "
                   f"{rl['quarantined']} quarantine(s), "
                   f"{rl['retried']} retry(ies), "
                   f"{rl['preempted']} preemption(s), "
                   f"{rl['shed']} shed "
                   f"({rl['rejected']} rejected / {rl['expired']} "
                   "expired)")
        if rl.get("failed_uids"):
            out.append(f"  FAILED uids: {rl['failed_uids']}")
        if "latency_p50_s" in rl:
            out.append(f"  request latency  p50 {rl['latency_p50_s']}s  "
                       f"p90 {rl['latency_p90_s']}s  "
                       f"p99 {rl['latency_p99_s']}s")
    rec = doc["recovery"]
    if (rec["attempts_failed"] or rec["nonfinite_skips"] or attempts
            or rec["in_graph_skips"] or rec["rollbacks"]):
        out.append("")
        out.append(f"recovery: {rec['in_graph_skips']} in-graph "
                   f"skip(s), {rec['rollbacks']} rollback(s), "
                   f"{rec['loss_spikes']} loss spike(s), "
                   f"{rec['attempts_failed']} failed "
                   f"attempt(s), {rec['nonfinite_skips']} non-finite "
                   f"skip(s), {rec['publishes']} checkpoint "
                   f"publish(es), run "
                   + ("COMPLETED" if rec["completed"] else
                      "did not record completion"))
    if timeline:
        t0 = timeline[0][0]
        out.append("")
        out.append("timeline:")
        for t, src, what in timeline:
            out.append(f"  {_fmt_t(t, t0)}  [{src:7s}] {what}")
    if "profile" in doc:
        pr = doc["profile"]
        out.append("")
        out.append(f"profile: {pr['trace_file']}")
        out.append(f"  {pr['comm_spans']} comm / {pr['compute_spans']} "
                   f"compute span(s), overlap {pr['overlap_us']} us")
        if pr.get("scope_totals_us"):
            out.append("  per-region span totals (us):")
            for k, v in sorted(pr["scope_totals_us"].items(),
                               key=lambda kv: -kv[1]):
                out.append(f"    {k:16s} {v}")
    if problems:
        out.append("")
        out.append(f"schema problems ({len(problems)}):")
        for prob in problems:
            out.append(f"  {prob}")
    print("\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(report_main())
