"""`report` — fold one or more runs' telemetry streams into one
human-readable run report.

Inputs (all optional except at least one metrics dir):

- one or MORE metrics dirs (``report A B ...``): each is the JSONL a
  ``--metrics_dir`` run wrote (``runtime/telemetry.py`` schema).
  Serving runs stamp an ``engine_id`` in their meta records
  (``generate --engine_id``); the multi-stream merge keys per-engine
  stats on it (falling back to the dir basename) and folds every
  stream's events onto ONE wall-clock timeline — the per-engine
  latency/shed-percentile contract the fleet-scale router (ROADMAP
  item 3) is measured against,
- supervise's per-attempt JSONL (``runtime/failure.py``) — passed with
  ``--attempt_log`` or auto-discovered from each run's meta records,
- a profile directory (``--profile_dir``) captured with
  ``jax.profiler.trace`` — folded through ``utils/trace_analysis``
  into comm/compute overlap and per-named-scope region totals,
- ``--postmortem``: render each stream's flight-recorder dump
  (``decode/engine.py`` ``flight_recorder.json`` — the bounded ring of
  per-step scheduler digests persisted on quarantine/watchdog/kill),
- ``--slo TTFT_S:ITL_S``: goodput accounting (schema v9, DESIGN.md
  section 21) — SLO attainment over completed requests with each
  violation attributed to its dominant span (queued / prefill /
  replay / decode / preempt_gap / quarantine / migration), computed
  on the MERGED streams so a migrated request's life re-assembles
  across engines; crash-resumed requests render UNRECONCILED, never
  silently as attainment. Malformed specs reject rc 2.
- ``--trace UID``: ONE request's cross-engine, cross-process causal
  waterfall (schema v12, DESIGN.md section 24) — every span, router
  move, and lifecycle event for the uid across the merged streams,
  stitched by its ``trace_id`` (minted once at admission, carried
  through migration/replay/crash-resume) instead of uid heuristics,
  rendered in causal order with per-engine attribution. Wall-clock
  gaps the spans don't cover are labeled ``migration`` only when a
  router move record explains them; an unexplained gap renders
  UNRECONCILED — dead time is never invented into a phase. A
  non-integer uid (or one no stream knows) rejects rc 2.
- ``--follow``: tail mode — poll the streams, print NEW timeline
  entries as they land, and exit rc 0 once the fleet status doc
  (``fleet_status.json``, published atomically by the router next to
  its stream) reports the fleet drained — or when ``--follow_max_s``
  elapses. Works mid-drill: records flush per line and the status doc
  only ever replaces atomically, so a SIGKILL storm can't tear what
  the tail reads.

The merged timeline is byte-deterministic: entries sort by
``(t, stream index, per-stream record order)``, so repeated merges of
the same dirs render identical output even under equal timestamps.

Output: step-time percentiles, throughput, MFU, HBM high-water, the
serving summary + reliability block per engine, a per-request
**waterfall** (schema-v5 ``span`` records: queued / prefill / replay /
decode / quarantine / preempt_gap, whose summed durations RECONCILE
with each completed request's recorded ``latency_s``), and ONE merged
timeline carrying every stream's progress, faults, and recoveries in
wall-clock order.

Exit codes: 0 = report rendered (schema problems are listed, not
fatal; a record-free stream renders an explicit "no records" summary);
2 = no metrics stream exists at any given path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

from .runtime.telemetry import (FLIGHT_FILENAME, METRICS_FILENAME,
                                RECORD_KINDS,
                                ROUTER_POSTMORTEM_PREFIX,
                                STATUS_FILENAME, read_metrics)

# a completed request's span durations telescope to its latency by
# construction (runtime/tracing.py); the tolerance only absorbs the
# per-record rounding (latency 4 decimals, durations 6)
RECONCILE_TOL_S = 0.01

# slack when splitting a request's spans at its first-token instant
# (t_first is reconstructed from two 4-decimal-rounded record fields,
# so a boundary span's end can sit ~1e-4 off the reconstruction)
_FIRST_TOKEN_EPS_S = 5e-3

# the SLO attribution vocabulary (DESIGN.md section 21): the span
# categories a violation can be attributed to. "migration" is not a
# span kind — it is the unaccounted wall-clock gap of a uid the router
# moved (plus the re-admission churn that follows a kill-migration),
# reconstructed from the merged streams; a gap WITHOUT a router
# migration record stays "unreconciled" (a crash, not a measured
# phase) and is never counted as attainment
SLO_SPAN_CATEGORIES = ("queued", "prefill", "replay", "decode",
                       "preempt_gap", "quarantine", "migration")


def _pct3(vals, ndigits=4):
    """(p50, p90, p99) of a non-empty value list, rounded."""
    q = np.percentile(np.asarray(vals, np.float64), [50, 90, 99])
    return tuple(round(float(x), ndigits) for x in q)


def _fmt_bytes(n: int | None) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024:
            return f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} PiB"


def _fmt_t(t: float, t0: float) -> str:
    return f"+{t - t0:8.2f}s"


def _load_attempt_log(path: str) -> list[dict]:
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    pass  # torn line — the stream survives a crash
    except OSError:
        return []
    return records


def _describe_step(rec: dict) -> str:
    bits = [f"step {rec['step']}"]
    if rec.get("strategy"):
        bits[0] = f"{rec['strategy']} {bits[0]}"
    if rec.get("loss") is not None:
        bits.append(f"loss {rec['loss']:.4f}")
    if rec.get("grad_norm") is not None:
        bits.append(f"|g| {rec['grad_norm']:.4f}")
    if rec.get("step_time_s") is not None:
        bits.append(f"{rec['step_time_s'] * 1e3:.1f} ms/step")
    if rec.get("tokens_per_sec") is not None:
        bits.append(f"{rec['tokens_per_sec']:.0f} tok/s")
    if rec.get("mfu") is not None:
        bits.append(f"mfu {rec['mfu']:.3f}")
    return "  ".join(bits)


def _describe_event(rec: dict) -> str:
    ev = rec.get("event", "?")
    if ev == "published":
        a, b = rec.get("steps", (None, None))
        return f"checkpoint published @ step {rec.get('step')} " \
               f"(steps {a}..{b})"
    if ev == "nonfinite_skip":
        a, b = rec.get("steps", (None, None))
        return f"NON-FINITE params after steps {a}..{b} — segment " \
               "skipped, not checkpointed"
    if ev == "anomaly" or rec.get("kind") == "anomaly":
        a, b = rec.get("steps", (None, None))
        return (f"ANOMALY: {rec.get('skipped')} step(s) skipped "
                f"in-graph in {a}..{b} (total "
                f"{rec.get('total_skipped')}, loss scale "
                f"{rec.get('loss_scale')})")
    if ev == "loss_spike":
        a, b = rec.get("steps", (None, None))
        return (f"LOSS SPIKE: update norm {rec.get('delta')} after "
                f"steps {a}..{b} vs baseline {rec.get('baseline')} "
                f"(> {rec.get('factor')}x) — segment not checkpointed")
    if ev == "rollback" or rec.get("kind") == "rollback":
        return (f"ROLLBACK #{rec.get('rollback')}: rewound to verified "
                f"step {rec.get('resume_step')} in-process — "
                f"{rec.get('error')} ({rec.get('max_rollbacks')} max)")
    if ev == "elastic_resume":
        return (f"ELASTIC RESUME @ step {rec.get('step')}: "
                f"{rec.get('saved_shards')} -> "
                f"{rec.get('current_shards')} data shard(s), "
                f"seed_accum {rec.get('seed_accum')} "
                f"({rec.get('n_devices')} device(s))")
    if ev == "attempt_failed":
        extra = " [watchdog expired]" if rec.get("watchdog_expired") else ""
        return (f"FAULT: attempt {rec.get('attempt')} failed after "
                f"{rec.get('elapsed_s')}s — {rec.get('error')}"
                f"{extra}; {rec.get('restarts_left')} restart(s) left, "
                f"backoff {rec.get('backoff_s')}s")
    if ev == "completed":
        return (f"RECOVERED: attempt {rec.get('attempt')} completed "
                f"after {rec.get('elapsed_s')}s"
                + (f" ({rec.get('rollbacks')} rollback(s))"
                   if rec.get("rollbacks") else ""))
    if ev == "chaos_corrupt_ckpt":
        return (f"CHAOS: checkpoint corruption injected at "
                f"step {rec.get('step')}")
    if ev == "hung_step":
        return (f"HUNG STEP @ engine step {rec.get('step')} — watchdog "
                f"{rec.get('watchdog_ms')}ms expired")
    if ev == "resumed":
        return (f"RESUMED from engine snapshot step {rec.get('step')} "
                f"({rec.get('live_requests')} live request(s), "
                f"{rec.get('finished')} already finished)")
    if ev == "chaos_kill":
        return f"CHAOS: SIGKILL after engine snapshot step {rec.get('step')}"
    return f"{ev}: " + ", ".join(
        f"{k}={v}" for k, v in rec.items()
        if k not in ("event", "t", "kind", "schema"))


def _stats_of(group):
    """Per-strategy step statistics (multi-method runs interleave
    strategies in one stream; pooled percentiles would describe no
    actual run)."""
    times = [s["step_time_s"] for s in group
             if s.get("step_time_s") is not None]
    # the first logged chunk usually carries compile time; report
    # steady-state percentiles over the rest when there is a rest
    steady = times[1:] if len(times) > 1 else times
    tps = [s["tokens_per_sec"] for s in group
           if s.get("tokens_per_sec") is not None]
    mfus = [s["mfu"] for s in group if s.get("mfu") is not None]
    losses = [s["loss"] for s in group if s.get("loss") is not None]
    hbm = [max(s["hbm_high_water_bytes"].values())
           for s in group if s.get("hbm_high_water_bytes")]
    stats = {
        "logged_steps": len(group),
        "first_step": group[0]["step"],
        "last_step": group[-1]["step"],
    }
    if steady:
        q = np.percentile(np.asarray(steady, np.float64), [50, 90, 99])
        stats["step_time_p50_ms"] = round(float(q[0]) * 1e3, 3)
        stats["step_time_p90_ms"] = round(float(q[1]) * 1e3, 3)
        stats["step_time_p99_ms"] = round(float(q[2]) * 1e3, 3)
    if tps:
        stats["tokens_per_sec_mean"] = round(float(np.mean(tps)), 1)
        stats["tokens_per_sec_best"] = round(float(np.max(tps)), 1)
    if mfus:
        stats["mfu_mean"] = round(float(np.mean(mfus)), 4)
        stats["mfu_best"] = round(float(np.max(mfus)), 4)
    if losses:
        stats["first_loss"] = round(losses[0], 4)
        stats["last_loss"] = round(losses[-1], 4)
    if hbm:
        stats["hbm_high_water_bytes"] = int(max(hbm))
    return stats


class _Stream:
    """One metrics dir's parsed state + its folded report sections."""

    def __init__(self, metrics_dir: str, attempt_log: str | None):
        self.dir = metrics_dir
        path = metrics_dir
        if os.path.isdir(path):
            path = os.path.join(path, METRICS_FILENAME)
        self.path = path
        self.exists = os.path.exists(path)
        # an EXISTING dir with no metrics.jsonl is a run that wrote
        # nothing — a record-free answer (rc 0), not a bad path (rc 2)
        self.dir_exists = self.exists or os.path.isdir(metrics_dir)
        self.records: list[dict] = []
        self.problems: list[str] = []
        if self.exists:
            self.records, self.problems = read_metrics(path)
        elif self.dir_exists:
            self.problems.append(f"no {METRICS_FILENAME} in "
                                 f"{metrics_dir} (empty metrics dir)")
        by = {}
        for r in self.records:
            by.setdefault(r["kind"], []).append(r)
        self.metas = by.get("meta", [])
        self.steps = by.get("step", [])
        self.events = by.get("event", [])
        self.benches = by.get("bench", [])
        self.anomalies = by.get("anomaly", [])
        self.rollbacks = by.get("rollback", [])
        self.decodes = by.get("decode", [])
        # fleet-router decision records (decode/fleet.py); the router
        # process never resumes, so no replay dedup applies
        self.routers = by.get("router", [])
        # schema-v9 per-round fleet health records (decode/fleet.py)
        self.fleets = by.get("fleet", [])
        # schema-v11 rolling-deploy lifecycle records (decode/fleet.py)
        self.deploys = by.get("deploy", [])
        # schema-v13 trace-replay interval records (the workload
        # driver, decode/workload_driver.py)
        self.workloads = by.get("workload", [])
        # schema-v15 watchtower alert records (runtime/watch.py):
        # fired/resolved detector transitions on the fleet round clock
        self.alerts = by.get("alert", [])
        # request records: drop exact replays — an in-process
        # supervisor restart resumes from a snapshot that may PREDATE
        # records already emitted, so the replayed steps re-emit
        # identical (uid, event, step) transitions (the global step is
        # stable across restarts). Legitimate repeats — a re-admission
        # after preemption, a second quarantine — land at different
        # global steps; anonymous rejected records (uid -1) are kept
        # verbatim (distinct sheds can share a step). Same stance as
        # the attempt-log dedup below.
        self.requests = []
        seen_req = set()
        for r in by.get("request", []):
            key = (r.get("uid"), r.get("event"), r.get("step"))
            if r.get("event") != "rejected" and key in seen_req:
                continue
            seen_req.add(key)
            self.requests.append(r)
        # span records: the same replay-dedup, keyed on the span's full
        # step window (two prefill-chunk spans can share a start_step —
        # admission and the first chunk land in one engine step)
        self.spans = []
        seen_span = set()
        for s in by.get("span", []):
            key = (s.get("uid"), s.get("span"), s.get("start_step"),
                   s.get("step"))
            if key in seen_span:
                continue
            seen_span.add(key)
            self.spans.append(s)

        # run header: later metas refine earlier ones
        self.header = {}
        for m in self.metas:
            self.header.update({k: v for k, v in m.items()
                                if k not in ("kind", "t", "schema")})
        self.label = self.header.get("engine_id") or os.path.basename(
            os.path.normpath(metrics_dir))

        # attempt log: flag wins; else the newest meta that names one
        self.attempt_path = attempt_log
        if self.attempt_path is None:
            for m in reversed(self.metas):
                if m.get("attempt_log"):
                    self.attempt_path = m["attempt_log"]
                    break
        self.attempts = (_load_attempt_log(self.attempt_path)
                         if self.attempt_path else [])
        if self.attempt_path and not self.attempts \
                and not os.path.exists(self.attempt_path):
            self.problems.append(
                f"attempt log {self.attempt_path} unreadable — "
                "recovery events missing from the timeline")

    # ---- folded sections -------------------------------------------

    def step_stats(self) -> dict:
        by_strategy: dict = {}
        for s in self.steps:
            by_strategy.setdefault(s.get("strategy") or "run",
                                   []).append(s)
        return {k: _stats_of(v) for k, v in by_strategy.items()}

    def serving(self) -> dict | None:
        decodes = self.decodes
        if not decodes:
            return None
        tps = [d["tokens_per_sec"] for d in decodes
               if d.get("tokens_per_sec") is not None]
        occ = [d["batch_occupancy"] for d in decodes
               if d.get("batch_occupancy") is not None]
        util = [d["kv_pool_utilization"] for d in decodes
                if d.get("kv_pool_utilization") is not None]
        last = decodes[-1]
        serving = {
            "records": len(decodes),
            "engine_steps": last.get("step"),
            "tokens_generated": last.get("tokens_generated"),
            "kv_dtype": last.get("kv_dtype"),
            "compiled_programs": last.get("compiled_programs"),
        }
        if tps:
            serving["tokens_per_sec_mean"] = round(float(np.mean(tps)), 1)
            serving["tokens_per_sec_best"] = round(float(np.max(tps)), 1)
        if occ:
            serving["batch_occupancy_mean"] = round(float(np.mean(occ)), 4)
        if util:
            serving["kv_pool_utilization_max"] = round(
                float(np.max(util)), 4)
        # schema-v5 KV-pool internals (older v4-era streams fail schema
        # validation wholesale, so presence here is all-or-nothing)
        lows = [d["free_blocks_low_water"] for d in decodes
                if d.get("free_blocks_low_water") is not None]
        frags = [d["kv_fragmentation"] for d in decodes
                 if d.get("kv_fragmentation") is not None]
        stored = [d["kv_bytes_stored"] for d in decodes
                  if d.get("kv_bytes_stored") is not None]
        if lows:
            serving["free_blocks_low_water"] = int(min(lows))
        if frags:
            serving["kv_fragmentation_max"] = round(float(np.max(frags)),
                                                    4)
        if stored:
            serving["kv_bytes_stored_max"] = int(max(stored))
        for key in ("block_allocs", "block_frees", "block_scrubs"):
            if last.get(key) is not None:
                serving[key] = last[key]
        # schema-v6 speculation keys: acceptance rate + measured
        # tokens-per-step (generated tokens over engine steps — > 1
        # exactly when verify dispatches emitted multi-token steps)
        if last.get("drafted_tokens"):
            serving["drafted_tokens"] = last["drafted_tokens"]
            serving["accepted_tokens"] = last.get("accepted_tokens")
            serving["accept_rate"] = last.get("accept_rate")
            if last.get("step") and last.get("tokens_generated") \
                    is not None:
                serving["tokens_per_step"] = round(
                    last["tokens_generated"] / last["step"], 3)
        # schema-v7 shared-prefix keys: cumulative admission hits and
        # the prompt tokens they skipped (the prefill the pool never
        # paid), plus the CoW trigger count (0 = the write-barrier
        # invariant held) and the peak instantaneous sharing
        if last.get("prefix_hit_blocks"):
            serving["prefix_hit_blocks"] = last["prefix_hit_blocks"]
            serving["prefill_tokens_saved"] = last.get(
                "prefill_tokens_saved")
            if last.get("prefix_hit_rate") is not None:
                serving["prefix_hit_rate"] = last["prefix_hit_rate"]
            shared = [d["shared_blocks"] for d in decodes
                      if d.get("shared_blocks") is not None]
            if shared:
                serving["shared_blocks_max"] = int(max(shared))
        if last.get("cow_copies") is not None:
            serving["cow_copies"] = last["cow_copies"]
        # schema-v17 KV spill keys: cumulative demotions/promotions
        # through the host-RAM tier, the prefill tokens restores
        # skipped, the wall clock the donated implant path cost, and
        # the peak host-tier occupancy — only when the tier ever held
        # a block (a tier-less run's summary stays pre-v17)
        if last.get("spilled_blocks"):
            serving["spilled_blocks"] = last["spilled_blocks"]
            serving["spill_bytes"] = last.get("spill_bytes")
            serving["restores"] = last.get("restores")
            serving["restore_tokens_saved"] = last.get(
                "restore_tokens_saved")
            serving["restore_stall_s"] = last.get("restore_stall_s")
            util = [d["host_tier_utilization"] for d in decodes
                    if d.get("host_tier_utilization") is not None]
            if util:
                serving["host_tier_utilization_max"] = max(util)
        if last.get("partial_hits"):
            serving["partial_hits"] = last["partial_hits"]
        return serving

    def reliability(self) -> dict | None:
        requests = self.requests
        if not requests:
            return None
        by_event: dict[str, int] = {}
        for r in requests:
            by_event[r["event"]] = by_event.get(r["event"], 0) + 1
        rel = {
            "records": len(requests),
            "admitted": by_event.get("admitted", 0),
            "completed": by_event.get("completed", 0),
            "quarantined": by_event.get("quarantined", 0),
            "retried": by_event.get("retried", 0),
            "preempted": by_event.get("preempted", 0),
            # shed = load the system refused or gave up on (admission
            # rejects + deadline expiries) — the graceful-degradation
            # counter
            "shed": (by_event.get("rejected", 0)
                     + by_event.get("expired", 0)),
            "rejected": by_event.get("rejected", 0),
            "expired": by_event.get("expired", 0),
            "failed_uids": sorted({
                r["uid"] for r in requests
                if (r["event"] == "expired"
                    or (r["event"] == "quarantined"
                        and not r.get("retrying")))}),
        }
        lat = [r["latency_s"] for r in requests
               if r["event"] == "completed"
               and r.get("latency_s") is not None]
        if lat:
            q = np.percentile(np.asarray(lat, np.float64), [50, 90, 99])
            rel["latency_p50_s"] = round(float(q[0]), 4)
            rel["latency_p90_s"] = round(float(q[1]), 4)
            rel["latency_p99_s"] = round(float(q[2]), 4)
        # schema-v9 latency decomposition: TTFT straight off the
        # completed records, ITL from the per-decode-segment spans
        # (duration/tokens — the segment's mean inter-token gap; the
        # segment's first token lands at its open instant)
        ttfts = [r["ttft_s"] for r in requests
                 if r["event"] == "completed"
                 and r.get("ttft_s") is not None]
        if ttfts:
            (rel["ttft_p50_s"], rel["ttft_p90_s"],
             rel["ttft_p99_s"]) = _pct3(ttfts)
        gaps = [s["duration_s"] / s["tokens"] for s in self.spans
                if s["span"] == "decode" and s.get("tokens")
                and s.get("duration_s") is not None]
        if gaps:
            (rel["itl_p50_s"], rel["itl_p90_s"],
             rel["itl_p99_s"]) = _pct3(gaps, 6)
        # v11 per-version completions: each uid completed exactly once
        # per stream (the replay dedup above), counted under its
        # weights-version pin — a mid-deploy stream shows both
        vers: dict[str, int] = {}
        for r in requests:
            if r["event"] == "completed" \
                    and r.get("weights_version") is not None:
                key = f"v{r['weights_version']}"
                vers[key] = vers.get(key, 0) + 1
        if vers:
            rel["completed_by_version"] = vers
        return rel

    def recovery(self) -> dict:
        fails = [a for a in self.attempts
                 if a.get("event") == "attempt_failed"]
        return {
            "attempt_log": self.attempt_path,
            "attempts_failed": len(fails),
            "completed": any(a.get("event") == "completed"
                             for a in self.attempts),
            "nonfinite_skips": sum(1 for e in self.events
                                   if e.get("event") == "nonfinite_skip"),
            "publishes": sum(1 for e in self.events
                             if e.get("event") == "published"),
            # the self-healing ladder's cheap rungs (schema v2 kinds)
            "in_graph_skips": sum(int(a.get("skipped") or 0)
                                  for a in self.anomalies),
            "rollbacks": len(self.rollbacks),
            "loss_spikes": sum(1 for e in self.events
                               if e.get("event") == "loss_spike"),
        }

    def waterfalls(self) -> dict:
        """Per-uid span waterfall: phase breakdown + the span-sum vs
        latency reconciliation (runtime/tracing.py's telescoping
        contract — a completed request whose spans DON'T sum to its
        latency had unaccounted wall time, e.g. a crash gap)."""
        if not self.spans:
            return {}
        comp = {r["uid"]: r for r in self.requests
                if r["event"] == "completed"}
        by_uid: dict = {}
        for s in self.spans:
            by_uid.setdefault(s["uid"], []).append(s)
        out = {}
        for uid in sorted(by_uid):
            ss = sorted(by_uid[uid],
                        key=lambda s: (s.get("start_t") or 0.0,
                                       s.get("t") or 0.0))
            total = round(sum(s.get("duration_s") or 0.0 for s in ss), 4)
            rec = comp.get(uid)
            latency = rec.get("latency_s") if rec else None
            ttft = rec.get("ttft_s") if rec else None
            entry = {
                "spans": [{
                    "span": s["span"],
                    "duration_s": s.get("duration_s"),
                    "start_step": s.get("start_step"),
                    "end_step": s.get("step"),
                } for s in ss],
                "span_sum_s": total,
                "latency_s": latency,
                "ttft_s": ttft,
                "reconciled": (latency is not None
                               and abs(total - latency)
                               <= RECONCILE_TOL_S),
            }
            if latency is not None and ttft is not None and rec:
                # the v9 decomposition reconciliation: the first-token
                # mark sits exactly on a span boundary, so ttft + the
                # post-first-token span sum telescopes to the latency
                t_first = rec.get("t", 0.0) - latency + ttft
                post = sum(s.get("duration_s") or 0.0 for s in ss
                           if (s.get("t") or 0.0)
                           > t_first + _FIRST_TOKEN_EPS_S)
                entry["ttft_plus_post_s"] = round(ttft + post, 4)
                entry["ttft_reconciled"] = (
                    abs(ttft + post - latency) <= RECONCILE_TOL_S)
            out[str(uid)] = entry
        return out

    def router_postmortems(self) -> list[dict]:
        """Router-side dead-host evidence dumps published next to this
        stream (``decode/fleet.py`` publishes one per declared-dead
        engine: last digests, pending call ids, op/backoff/ping
        history, declaration reason — the half of the post-mortem the
        SIGKILLed worker's own flight recorder cannot hold)."""
        out = []
        base = os.path.dirname(self.path)
        try:
            names = sorted(os.listdir(base))
        except OSError:
            return out
        for name in names:
            if not (name.startswith(ROUTER_POSTMORTEM_PREFIX)
                    and name.endswith(".json")):
                continue
            path = os.path.join(base, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except ValueError:
                doc = {"error": f"unparseable router postmortem at "
                                f"{path}"}
            doc["path"] = path
            out.append(doc)
        return out

    def flight_recorder(self) -> dict | None:
        """The stream's flight-recorder dump, if one was persisted
        (decode/engine.py dumps on quarantine; the supervisor on
        watchdog latch and chaos kill)."""
        path = os.path.join(os.path.dirname(self.path), FLIGHT_FILENAME)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError:
            return {"error": f"unparseable flight recorder at {path}"}
        doc["path"] = path
        return doc

    def timeline_entries(self) -> list[tuple[float, str, str]]:
        timeline = []
        for s in self.steps:
            timeline.append((s["t"], "step", _describe_step(s)))
        seen_events = {(e.get("t"), e.get("event")) for e in self.events}
        for e in self.events:
            timeline.append((e["t"], "event", _describe_event(e)))
        for a in self.anomalies:
            timeline.append((a["t"], "anomaly", _describe_event(a)))
            seen_events.add((a.get("t"), "anomaly"))
        for r in self.rollbacks:
            timeline.append((r["t"], "rollbck", _describe_event(r)))
            seen_events.add((r.get("t"), "rollback"))
        for d in self.decodes:
            bits = [f"engine step {d.get('step')}"]
            if d.get("tokens_per_sec") is not None:
                bits.append(f"{d['tokens_per_sec']:.0f} tok/s")
            if d.get("batch_occupancy") is not None:
                bits.append(f"occ {d['batch_occupancy']:.2f}")
            if d.get("kv_pool_utilization") is not None:
                bits.append(f"kv {d['kv_pool_utilization']:.2f}")
            if d.get("kv_fragmentation"):
                bits.append(f"frag {d['kv_fragmentation']:.2f}")
            if d.get("waiting"):
                bits.append(f"{d['waiting']} waiting")
            timeline.append((d["t"], "decode", "  ".join(bits)))
        for r in self.routers:
            ev = r["event"]
            arrow = ""
            if r.get("source") is not None and r.get("target") is not None:
                arrow = f" {r['source']} -> {r['target']}"
            elif r.get("target") is not None:
                arrow = f" -> {r['target']}"
            elif r.get("source") is not None:
                arrow = f" from {r['source']}"
            bits = [f"request {r.get('uid')} {ev.upper()}{arrow}"
                    + (f" ({r['reason']})" if r.get("reason") else "")
                    + f" @ fleet round {r.get('step')}"]
            if r.get("replay"):
                bits.append(f"replay {r['replay']} token(s)")
            if r.get("prefix_hit_blocks"):
                bits.append(f"{r['prefix_hit_blocks']} warm block(s)")
            timeline.append((r["t"], "router", "  ".join(bits)))
        for d in self.deploys:
            ev = d["event"]
            pair = (f"v{d.get('from_version')} -> "
                    f"v{d.get('to_version')}")
            if ev == "started":
                what = f"DEPLOY STARTED {pair}"
            elif ev == "engine_swapped":
                what = (f"DEPLOY {pair}: engine {d.get('engine')} "
                        "drained + swapped")
            elif ev == "completed":
                what = (f"DEPLOY COMPLETED {pair} across "
                        f"{d.get('engines')} engine(s) in "
                        f"{d.get('duration_s')}s "
                        f"({d.get('drained')} request(s) migrated, "
                        "zero shed)")
            elif ev == "rolled_back":
                what = f"DEPLOY ROLLED BACK — {d.get('reason')}"
            else:
                what = f"DEPLOY {ev} {pair}"
            timeline.append((d["t"], "deploy",
                             what + f" @ fleet round {d.get('step')}"))
        for wrec in self.workloads:
            tb = ", ".join(
                f"{t}:{c.get('completed')}/{c.get('offered')}"
                for t, c in sorted((wrec.get("tenants") or {}).items()))
            timeline.append((
                wrec["t"], "workld",
                f"interval offered {wrec.get('offered')} admitted "
                f"{wrec.get('admitted')} @ round {wrec.get('step')}"
                + (f"  [{tb}]" if tb else "")))
        for a in self.alerts:
            ev = a["event"]
            bits = [f"ALERT {a.get('detector')} {ev.upper()} "
                    f"[{a.get('severity')}] @ fleet round "
                    f"{a.get('step')}"]
            if ev == "resolved" and a.get("fired_step") is not None:
                bits.append(f"fired @ {a['fired_step']}")
            if a.get("burn_fast") is not None:
                bits.append(f"burn fast {a['burn_fast']} / slow "
                            f"{a['burn_slow']}")
            for k in ("waiting", "imbalance", "stalled_rounds",
                      "incidents", "p95_s"):
                if a.get(k) is not None:
                    bits.append(f"{k} {a[k]}")
            timeline.append((a["t"], "alert", "  ".join(bits)))
        for r in self.requests:
            ev = r["event"]
            bits = [f"request {r.get('uid')} {ev.upper()}"
                    + (f" ({r['reason']})" if r.get("reason") else "")
                    + f" @ engine step {r.get('step')}"]
            if ev == "completed":
                if r.get("latency_s") is not None:
                    bits.append(f"latency {r['latency_s']:.3f}s")
                if r.get("n_new") is not None:
                    bits.append(f"{r['n_new']} token(s)")
                if r.get("retries"):
                    bits.append(f"{r['retries']} retry(ies)")
            elif ev == "retried":
                bits.append(f"attempt {r.get('attempt')}/"
                            f"{r.get('max_retries')}")
            elif ev == "quarantined" and not r.get("retrying"):
                bits.append("FAILED")
            timeline.append((r["t"], "request", "  ".join(bits)))
        for a in self.attempts:
            # supervise forwards checkpoint-layer events to its log
            # too; drop exact duplicates of what the metrics stream
            # already has
            if (a.get("t"), a.get("event")) in seen_events:
                continue
            timeline.append((a.get("t", 0.0), "attempt",
                             _describe_event(a)))
        return timeline


def _merged_completions(streams) -> dict:
    """uid -> its FIRST completion record across every stream (a
    request completed on an engine after its last snapshot re-completes
    on a survivor when that engine dies — same tokens, two records;
    the caller saw the first one)."""
    comp: dict = {}
    for r in sorted((r for s in streams for r in s.requests
                     if r["event"] == "completed"),
                    key=lambda r: r.get("t", 0.0)):
        comp.setdefault(r["uid"], r)
    return comp


def _merged_spans(streams) -> dict:
    """uid -> its deduped spans pooled across every stream (the
    per-stream replay dedup applied once more across streams — a
    migrated request's life is split over several engines' files)."""
    by_uid: dict = {}
    seen = set()
    for s in streams:
        for sp in s.spans:
            key = (sp.get("uid"), sp.get("span"), sp.get("start_step"),
                   sp.get("step"))
            if key in seen:
                continue
            seen.add(key)
            by_uid.setdefault(sp["uid"], []).append(sp)
    for ss in by_uid.values():
        ss.sort(key=lambda s: (s.get("start_t") or 0.0,
                               s.get("t") or 0.0))
    return by_uid


def _merged_decode_gaps(streams) -> list:
    """Per-decode-segment mean inter-token gaps (duration/tokens)
    pooled across streams — the fleet-wide ITL sample set."""
    return [s.get("duration_s") / s["tokens"]
            for ss in _merged_spans(streams).values() for s in ss
            if s["span"] == "decode" and s.get("tokens")
            and s.get("duration_s") is not None]


def _slo_accounting(streams, slo_ttft: float, slo_itl: float) -> dict:
    """Goodput accounting over the merged streams (DESIGN.md §21).

    A completed request ATTAINS the SLO when its decomposition
    reconciles AND ``ttft_s <= slo_ttft`` AND its observed inter-token
    latency ``(latency_s - ttft_s) / (n_new - 1)`` — stalls included,
    what the caller actually experienced — is ``<= slo_itl``. Each
    violation is attributed to its dominant span category:

    - post-first-token spans fold by kind (decode / preempt_gap /
      quarantine), with the re-admission churn after a stall (queued /
      prefill / replay spans) charged to the stall's CAUSE — a
      kill-migration's replay is migration cost, not an innocent
      "replay" line item;
    - a wall-clock gap the spans don't cover is ``migration`` when the
      router has a handoff/migrated record for the uid (the span
      clock deliberately restarts on the target engine — the gap IS
      the migration stall). A gap with NO migration record is a crash:
      the request is UNRECONCILED and never counted as attainment.
    """
    comp = _merged_completions(streams)
    spans_by_uid = _merged_spans(streams)
    # per-policy attribution (v14): one run serves one policy, so each
    # completion inherits the ``--policy`` label of the stream (run)
    # that emitted it — merged with the same first-completion-wins
    # ordering as ``_merged_completions`` so the label matches the
    # record the numbers came from
    policy_of: dict = {}
    for r, label in sorted(((r, s.header.get("policy"))
                            for s in streams for r in s.requests
                            if r["event"] == "completed"),
                           key=lambda rl: rl[0].get("t", 0.0)):
        policy_of.setdefault(r["uid"], label)
    moved_t: dict = {}
    for s in streams:
        for r in s.routers:
            if r["event"] in ("handoff", "migrated"):
                t = r.get("t", 0.0)
                moved_t[r["uid"]] = min(moved_t.get(r["uid"], t), t)
    per_uid = []
    counts = {"attained": 0, "violated": 0, "unreconciled": 0}
    by_span: dict = {}
    for uid in sorted(comp):
        rec = comp[uid]
        latency = rec.get("latency_s")
        ttft = rec.get("ttft_s")
        n_new = rec.get("n_new")
        entry = {"uid": uid, "latency_s": latency, "ttft_s": ttft,
                 "n_new": n_new, "migrated": uid in moved_t,
                 "tenant": _tenant_of(rec)}
        if policy_of.get(uid) is not None:
            entry["policy"] = policy_of[uid]
        spans = spans_by_uid.get(uid, [])
        if latency is None or ttft is None:
            entry["status"] = "unreconciled"
            entry["why"] = ("no TTFT decomposition (first token "
                            "predates a crash-resume)")
            counts["unreconciled"] += 1
            per_uid.append(entry)
            continue
        t_first = rec.get("t", 0.0) - latency + ttft
        pre = [s for s in spans
               if (s.get("t") or 0.0) <= t_first + _FIRST_TOKEN_EPS_S]
        post = [s for s in spans
                if (s.get("t") or 0.0) > t_first + _FIRST_TOKEN_EPS_S]
        mig_t = moved_t.get(uid)

        def fold(side_spans: list) -> dict:
            """Category totals with the cause-tracking rules (the same
            walk on both sides of the first token — a kill BEFORE the
            first token stalls the TTFT side, DESIGN.md §21)."""
            cats: dict = {}
            cause = None
            for s in side_spans:
                name = s["span"]
                if name == "decode":
                    cat, cause = "decode", None
                elif name == "preempt_gap":
                    cat = cause = "preempt_gap"
                elif name == "quarantine":
                    cat = cause = "quarantine"
                elif (cause is None and mig_t is not None
                      and (s.get("start_t") or 0.0)
                      >= mig_t - _FIRST_TOKEN_EPS_S):
                    # queued/prefill/replay after the migration with no
                    # closer stall cause: the kill-migration's catch-up
                    cat = cause = "migration"
                elif cause is not None:
                    cat = cause      # re-admission churn -> its cause
                else:
                    cat = name
                cats[cat] = cats.get(cat, 0.0) + (s.get("duration_s")
                                                  or 0.0)
            return cats

        cats = fold(post)
        pre_cats = fold(pre)
        post_sum = sum(s.get("duration_s") or 0.0 for s in post)
        pre_sum = sum(s.get("duration_s") or 0.0 for s in pre)
        # gaps the spans don't cover, on EACH side of the first token:
        # ttft == pre-span sum by construction, so a pre-side gap is a
        # stall whose spans died with an engine (a kill before the
        # first token), exactly like the post-side gap of a mid-decode
        # kill — migration when the router recorded the move, a crash
        # (UNRECONCILED) otherwise
        post_gap = latency - ttft - post_sum
        pre_gap = ttft - pre_sum
        entry["post_span_sum_s"] = round(post_sum, 4)
        entry["gap_s"] = round(post_gap, 4)
        if abs(pre_gap) > RECONCILE_TOL_S:
            entry["pre_gap_s"] = round(pre_gap, 4)
        unaccounted = None
        for side_cats, gap in ((cats, post_gap), (pre_cats, pre_gap)):
            if gap > RECONCILE_TOL_S and uid in moved_t:
                side_cats["migration"] = (
                    side_cats.get("migration", 0.0) + gap)
            elif abs(gap) > RECONCILE_TOL_S:
                unaccounted = gap
        if unaccounted is not None:
            entry["status"] = "unreconciled"
            entry["why"] = (f"{round(unaccounted, 4)}s unaccounted "
                            "and no router migration record — a crash "
                            "gap, not a measured phase")
            counts["unreconciled"] += 1
            per_uid.append(entry)
            continue
        mig_total = (cats.get("migration", 0.0)
                     + pre_cats.get("migration", 0.0))
        if mig_total:
            entry["migration_s"] = round(mig_total, 4)
        itl = ((latency - ttft) / (n_new - 1)
               if n_new and n_new > 1 else None)
        entry["itl_s"] = None if itl is None else round(itl, 6)
        entry["breakdown"] = {k: round(v, 4) for k, v in
                              sorted(cats.items(),
                                     key=lambda kv: -kv[1])}
        entry["ttft_breakdown"] = {k: round(v, 4) for k, v in
                                   sorted(pre_cats.items(),
                                          key=lambda kv: -kv[1])}
        ttft_viol = ttft > slo_ttft + 1e-9
        itl_viol = itl is not None and itl > slo_itl + 1e-9
        if not (ttft_viol or itl_viol):
            entry["status"] = "attained"
            counts["attained"] += 1
        else:
            entry["status"] = "violated"
            entry["violates"] = [d for d, v in (("ttft", ttft_viol),
                                                ("itl", itl_viol)) if v]
            pool: dict = {}
            if itl_viol:
                pool.update(cats)
            if ttft_viol:
                for k, v in pre_cats.items():
                    pool[k] = pool.get(k, 0.0) + v
            attributed = (max(pool.items(), key=lambda kv: kv[1])[0]
                          if pool else "decode")
            entry["attributed"] = attributed
            by_span[attributed] = by_span.get(attributed, 0) + 1
            counts["violated"] += 1
        per_uid.append(entry)
    total = len(per_uid)
    # the per-tenant goodput slice (v13): the same fold, grouped by
    # the completed record's tenant — the noisy-tenant drill's numbers
    by_tenant: dict = {}
    for e in per_uid:
        b = by_tenant.setdefault(e["tenant"], {
            "completed": 0, "attained": 0, "violated": 0,
            "unreconciled": 0})
        b["completed"] += 1
        b[e["status"]] += 1
    for b in by_tenant.values():
        b["attainment"] = (round(b["attained"] / b["completed"], 4)
                           if b["completed"] else None)
    # the per-policy goodput slice (v14): the offline policy search's
    # comparison surface — group by the run's ``--policy`` label (a
    # report over two labelled runs of the same trace prints both
    # policies' attainment side by side); unlabelled runs fold nowhere
    by_policy: dict = {}
    for e in per_uid:
        label = e.get("policy")
        if label is None:
            continue
        b = by_policy.setdefault(label, {
            "completed": 0, "attained": 0, "violated": 0,
            "unreconciled": 0})
        b["completed"] += 1
        b[e["status"]] += 1
    for b in by_policy.values():
        b["attainment"] = (round(b["attained"] / b["completed"], 4)
                           if b["completed"] else None)
    return {
        "slo_ttft_s": slo_ttft, "slo_itl_s": slo_itl,
        "completed": total, **counts,
        "attainment": (round(counts["attained"] / total, 4)
                       if total else None),
        "violations_by_span": by_span,
        "by_tenant": by_tenant,
        "by_policy": by_policy,
        "requests": per_uid,
    }


def _trace_doc(streams, uid: int) -> dict | None:
    """ONE request's cross-engine causal waterfall (schema v12,
    DESIGN.md section 24): every span, router move, and lifecycle
    event for ``uid`` across the merged streams, stitched by the
    request's ``trace_id`` (records carrying a DIFFERENT trace id are
    another life of a reused uid and are excluded — the stitch key is
    the id, not the uid). Wall-clock gaps the spans don't cover are
    classified ``migration`` only when a router move record explains
    them; an unexplained gap renders UNRECONCILED and the whole
    request is flagged — dead time is never invented into a phase."""
    reqs, spans, moves = [], [], []
    for s in streams:
        for r in s.requests:
            if r.get("uid") == uid:
                reqs.append((s.label, r))
        for sp in s.spans:
            if sp.get("uid") == uid:
                spans.append((s.label, sp))
        for r in s.routers:
            if r.get("uid") == uid:
                moves.append((s.label, r))
    if not (reqs or spans or moves):
        return None
    problems = []
    traces = {r.get("trace_id") for _, r in reqs + spans + moves
              if r.get("trace_id")}
    trace_id = None
    if traces:
        # the NEWEST life by record timestamp — the nonce prefix is
        # random and carries no temporal order, so a lexicographic
        # pick could stitch an old life of a reused uid
        trace_id = max(
            (r for _, r in reqs + spans + moves if r.get("trace_id")),
            key=lambda r: r.get("t", 0.0)).get("trace_id")
    if len(traces) > 1:
        problems.append(
            f"uid {uid} appears under {len(traces)} trace ids "
            f"{sorted(traces)} — stitching the newest-by-timestamp "
            f"({trace_id}); an older id is a different request's "
            "life behind a reused uid")
    if trace_id is not None:
        keep = (trace_id, None)
        reqs = [(l, r) for l, r in reqs if r.get("trace_id") in keep]
        spans = [(l, r) for l, r in spans if r.get("trace_id") in keep]
        moves = [(l, r) for l, r in moves if r.get("trace_id") in keep]
    # spans were already replay-deduped PER STREAM (_Stream); across
    # streams every span is genuine — two engines can emit spans with
    # coincident (span, step) windows (fleet rounds keep global steps
    # comparable), so the dedup key must include the engine or a real
    # span gets dropped and renders a false UNRECONCILED gap
    spans_d, seen = [], set()
    for label, sp in sorted(spans,
                            key=lambda x: (x[1].get("start_t") or 0.0,
                                           x[1].get("t") or 0.0)):
        key = (label, sp.get("span"), sp.get("start_step"),
               sp.get("step"))
        if key in seen:
            continue
        seen.add(key)
        spans_d.append((label, sp))
    moves_sorted = sorted(moves, key=lambda x: x[1].get("t", 0.0))
    comp = None
    for _label, r in sorted(reqs, key=lambda x: x[1].get("t", 0.0)):
        if r["event"] == "completed":
            comp = r
            break

    def move_row(label, mr):
        row = {"type": "move", "event": mr["event"], "t": mr.get("t"),
               "source": mr.get("source"), "target": mr.get("target"),
               "reason": mr.get("reason"), "round": mr.get("step")}
        for k in ("blocks", "bytes", "duration_s", "replay",
                  "transport", "policy"):
            if mr.get(k) is not None:
                row[k] = mr[k]
        return row

    chain = []
    span_sum = mig_gap = unrec_gap = 0.0
    prev_end = None
    mi = 0
    eps = _FIRST_TOKEN_EPS_S
    for label, sp in spans_d:
        st = sp.get("start_t") or 0.0
        while (mi < len(moves_sorted)
               and moves_sorted[mi][1].get("t", 0.0) <= st + eps):
            chain.append(move_row(*moves_sorted[mi]))
            mi += 1
        if prev_end is not None and st - prev_end > RECONCILE_TOL_S:
            gap = st - prev_end
            explained = any(
                mr["event"] in ("handoff", "migrated", "wire_rejected")
                and prev_end - eps <= mr.get("t", 0.0) <= st + eps
                for _l, mr in moves)
            cause = "migration" if explained else "UNRECONCILED"
            if explained:
                mig_gap += gap
            else:
                unrec_gap += gap
            chain.append({"type": "gap", "cause": cause,
                          "duration_s": round(gap, 4)})
        row = {"type": "span", "engine": label, "span": sp["span"],
               "duration_s": sp.get("duration_s"),
               "start_step": sp.get("start_step"),
               "end_step": sp.get("step")}
        if sp.get("tokens") is not None:
            row["tokens"] = sp["tokens"]
        chain.append(row)
        span_sum += sp.get("duration_s") or 0.0
        end = sp.get("t") or st
        prev_end = end if prev_end is None else max(prev_end, end)
    while mi < len(moves_sorted):
        chain.append(move_row(*moves_sorted[mi]))
        mi += 1
    latency = comp.get("latency_s") if comp else None
    # the acceptance identity: covered span time + router-explained
    # migration gaps telescope to the recorded latency (the first
    # span opens at t_submit, the last closes on the completion
    # timestamp); any residual is unaccounted crash time
    reconciled = (latency is not None
                  and unrec_gap <= RECONCILE_TOL_S
                  and abs(span_sum + mig_gap + unrec_gap - latency)
                  <= RECONCILE_TOL_S)
    events = [{"engine": label, "event": r["event"],
               "step": r.get("step"), "t": r.get("t"),
               "reason": r.get("reason"),
               "weights_version": r.get("weights_version")}
              for label, r in sorted(reqs,
                                     key=lambda x: x[1].get("t", 0.0))]
    return {
        "uid": uid,
        "trace_id": trace_id,
        "engines": sorted({l for l, _ in spans_d}
                          | {e["engine"] for e in events}),
        "chain": chain,
        "events": events,
        "span_sum_s": round(span_sum, 4),
        "migration_gap_s": round(mig_gap, 4),
        "unreconciled_gap_s": round(unrec_gap, 4),
        "latency_s": latency,
        "ttft_s": comp.get("ttft_s") if comp else None,
        "weights_version": (comp or {}).get("weights_version"),
        "completed": comp is not None,
        "reconciled": reconciled,
        "problems": problems,
    }


def _render_trace(out: list, tr: dict) -> None:
    out.append("")
    out.append(f"trace {tr['trace_id']} — uid {tr['uid']} across "
               + (", ".join(tr["engines"]) or "(no engine)"))
    for row in tr["chain"]:
        if row["type"] == "span":
            toks = (f"  {row['tokens']} token(s)"
                    if row.get("tokens") else "")
            dur = row.get("duration_s")
            out.append(f"  [{row['engine']}] {row['span']:12s} "
                       f"{dur if dur is not None else '?':>9}s  steps "
                       f"{row.get('start_step')}.."
                       f"{row.get('end_step')}{toks}")
        elif row["type"] == "move":
            arrow = ""
            if row.get("source") or row.get("target"):
                arrow = (f" {row.get('source') or '?'} -> "
                         f"{row.get('target') or '?'}")
            bits = [f"  >> {row['event'].upper()}{arrow}"
                    + (f" ({row['reason']})" if row.get("reason")
                       else "")
                    + f" @ fleet round {row.get('round')}"]
            if row.get("blocks") is not None:
                bits.append(f"{row['blocks']} block(s) / "
                            + _fmt_bytes(row.get("bytes")))
            tp = row.get("transport") or {}
            if tp.get("crc_verify_s") is not None:
                bits.append(f"crc_verify "
                            f"{tp['crc_verify_s'] * 1e3:.2f} ms")
            if row.get("replay"):
                bits.append(f"replay {row['replay']} token(s)")
            out.append("  ".join(bits))
        else:   # gap
            tag = ("migration stall (router move explains it)"
                   if row["cause"] == "migration" else
                   "UNRECONCILED — no router record explains this "
                   "dead time (a crash gap, never invented into a "
                   "phase)")
            out.append(f"  ~~ gap {row['duration_s']:>9}s  {tag}")
    if tr["completed"]:
        verdict = ("reconciled" if tr["reconciled"] else
                   "NOT RECONCILED")
        out.append(f"  span sum {tr['span_sum_s']}s + migration gaps "
                   f"{tr['migration_gap_s']}s vs latency "
                   f"{tr['latency_s']}s ({verdict}"
                   + (f"; {tr['unreconciled_gap_s']}s unaccounted)"
                      if tr["unreconciled_gap_s"] > 0 else ")"))
        if tr.get("ttft_s") is not None:
            out.append(f"  ttft {tr['ttft_s']}s  weights version "
                       f"v{tr.get('weights_version')}")
    else:
        out.append("  (no completion record — the request did not "
                   "finish in these streams)")
    for prob in tr["problems"]:
        out.append(f"  note: {prob}")


def _transport_fold(streams) -> dict | None:
    """The latest ``transport_stats`` event across the streams
    (decode/fleet.py emits one at drain end): per-worker per-op RPC
    call/overhead percentiles + the overhead share of round wall."""
    recs = [e for s in streams for e in s.events
            if e.get("event") == "transport_stats"]
    if not recs:
        return None
    rec = max(recs, key=lambda r: r.get("t", 0.0))
    engines = {k: v for k, v in (rec.get("engines") or {}).items() if v}
    if not engines:
        return None
    wall = rec.get("round_wall_s") or 0.0
    overhead = sum(v.get("overhead_total_s") or 0.0
                   for v in engines.values())
    return {
        "rounds": rec.get("rounds"),
        "round_wall_s": wall,
        "rpc_overhead_total_s": round(overhead, 6),
        "rpc_overhead_share_of_round_wall": (
            round(overhead / wall, 4) if wall else None),
        "engines": engines,
    }


def _render_transport(out: list, tr: dict) -> None:
    out.append("")
    share = tr.get("rpc_overhead_share_of_round_wall")
    out.append(f"transport: RPC overhead "
               f"{tr['rpc_overhead_total_s']}s over "
               f"{tr['round_wall_s']}s of round wall"
               + (f" ({share * 100:.1f}%)" if share is not None
                  else ""))
    for eid, st in sorted(tr["engines"].items()):
        hb = ""
        if st.get("heartbeat_rtt_p50_ms") is not None:
            hb = (f"  heartbeat RTT p50 {st['heartbeat_rtt_p50_ms']} "
                  f"ms / p99 {st['heartbeat_rtt_p99_ms']} ms "
                  f"({st.get('heartbeats')} ping(s))")
        out.append(f"  {eid}:{hb}")
        for op, o in (st.get("ops") or {}).items():
            line = (f"    {op:12s} x{o['n']:<5d} call p50 "
                    f"{o['call_p50_ms']} ms  p99 {o['call_p99_ms']} ms")
            if "overhead_p50_ms" in o:
                line += (f"  overhead p50 {o['overhead_p50_ms']} ms  "
                         f"p99 {o['overhead_p99_ms']} ms")
            out.append(line)


def _render_router_postmortem(out: list, label: str | None,
                              docs: list) -> None:
    tag = f" [{label}]" if label else ""
    for doc in docs:
        out.append("")
        if doc.get("error"):
            out.append(f"router postmortem{tag}: {doc['error']}")
            continue
        out.append(f"router postmortem{tag}: engine "
                   f"{doc.get('engine')} declared dead @ round "
                   f"{doc.get('round')} — {doc.get('reason')} "
                   f"({doc.get('path')})")
        al = (doc.get("alerts") or {}).get("active") or []
        if al:
            out.append("  active alert(s) at declaration: " + ", ".join(
                f"{a['detector']} [{a['severity']}] since round "
                f"{a['since_round']}" for a in al))
        ev = doc.get("evidence") or {}
        d = ev.get("last_digest")
        if d:
            out.append(f"  last digest (call id "
                       f"{ev.get('last_digest_call_id')}): waiting "
                       f"{d.get('waiting')}, active {d.get('active')},"
                       f" free blocks {d.get('free_blocks')}, serving "
                       f"v{d.get('serving_version')}")
        if ev.get("pending_call_ids"):
            out.append(f"  pending call id(s): "
                       f"{ev['pending_call_ids']}")
        if ev.get("ping_rtt_ms"):
            out.append(f"  heartbeat RTTs (ms): {ev['ping_rtt_ms']}")
        if ev.get("backoff_log"):
            out.append(f"  backoff retries before the verdict: "
                       f"{len(ev['backoff_log'])}")
        for op in (ev.get("op_log") or [])[-8:]:
            out.append(f"    op {op.get('op'):12s} id {op.get('id')}"
                       f"  {op.get('call_ms')} ms  "
                       f"{'ok' if op.get('ok') else 'ERROR'}")
        if ev.get("last_snapshot_step") is not None:
            out.append(f"  last router-held snapshot: step "
                       f"{ev['last_snapshot_step']} with "
                       f"{ev.get('last_snapshot_requests')} live "
                       "request(s) (migration source)")


def _follow(metrics_dirs: list, interval: float, max_s: float) -> int:
    """Tail mode: poll the streams, print NEW timeline entries as they
    land (keyed by content — the streams are append-only JSONL), exit
    rc 0 once a discovered fleet status doc reports the fleet drained
    with nothing new to print, or after ``max_s``. Reads are
    crash-safe mid-drill: records flush per line (a torn tail is
    skipped by read_metrics) and the status doc only ever replaces
    atomically."""
    import time as _time
    printed: set = set()
    t_start = _time.monotonic()
    t0_ref = None
    sizes: dict = {}
    cache: dict = {}
    last_alerts: str | None = None
    while True:
        new = []
        for d in metrics_dirs:
            # re-parse a stream only when its JSONL actually grew —
            # idle ticks must not re-validate the whole history just
            # to find nothing (streams are append-only)
            path = d
            if os.path.isdir(path):
                path = os.path.join(path, METRICS_FILENAME)
            try:
                size = os.path.getsize(path)
            except OSError:
                size = -1
            if sizes.get(d) != size:
                sizes[d] = size
                s = _Stream(d, None)
                cache[d] = ([(t, s.label, src, what)
                             for t, src, what in s.timeline_entries()]
                            if s.dir_exists else [])
            for key in cache.get(d, ()):
                if key in printed:
                    continue
                printed.add(key)
                new.append(key)
        new.sort(key=lambda x: (x[0], x[1]))
        for t, lab, src, what in new:
            if t0_ref is None:
                t0_ref = t
            print(f"  {_fmt_t(t, t0_ref)}  [{src:7s}] [{lab}] {what}",
                  flush=True)
        status = None
        for d in metrics_dirs:
            p = os.path.join(d, STATUS_FILENAME)
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        status = json.load(f)
                except ValueError:
                    pass    # racing the atomic replace; next tick
        # live watchtower surface (v15): render the status doc's
        # active-alert block whenever it CHANGES — the tail shows
        # what is firing right now, not just the fired/resolved
        # timeline entries as they land
        if status is not None:
            active = (status.get("alerts") or {}).get("active") or []
            fp = json.dumps(active, sort_keys=True)
            if fp != last_alerts and (active or last_alerts
                                      is not None):
                if active:
                    print("  ACTIVE ALERTS: " + ", ".join(
                        f"{a.get('detector')} [{a.get('severity')}] "
                        f"since round {a.get('since_round')}"
                        for a in active), flush=True)
                elif last_alerts is not None:
                    print("  active alerts: none (all resolved)",
                          flush=True)
                last_alerts = fp
        if status is not None and status.get("drained") and not new:
            print(f"report: fleet drained @ round "
                  f"{status.get('round')} — follow complete")
            return 0
        if _time.monotonic() - t_start > max_s:
            print("report: --follow_max_s elapsed without a drained "
                  "status doc — stopping the tail")
            return 0
        _time.sleep(interval)


def _fleet_health(streams) -> dict | None:
    """Fold the per-round ``fleet`` records (decode/fleet.py) into a
    balance summary + a sampled utilization timeline."""
    recs = sorted((r for s in streams for r in s.fleets),
                  key=lambda r: r.get("step", 0))
    if not recs:
        return None
    imbs = [r.get("load_imbalance") or 0.0 for r in recs]
    agg: dict = {}
    for r in recs:
        for eid, st in (r.get("engines") or {}).items():
            a = agg.setdefault(eid, {"alive_rounds": 0,
                                     "dead_rounds": 0, "util": [],
                                     "active": [], "waiting": []})
            if not st.get("alive"):
                a["dead_rounds"] += 1
                continue
            a["alive_rounds"] += 1
            a["role"] = st.get("role")
            a["util"].append(st.get("utilization") or 0.0)
            a["active"].append(st.get("active") or 0)
            a["waiting"].append(st.get("waiting") or 0)
    n = len(recs)
    idx = (range(n) if n <= 16 else
           sorted({round(i * (n - 1) / 15) for i in range(16)}))
    timeline = [{
        "round": recs[i].get("step"),
        "load_imbalance": recs[i].get("load_imbalance"),
        "utilization": {
            eid: (st.get("utilization") if st.get("alive") else None)
            for eid, st in (recs[i].get("engines") or {}).items()},
    } for i in idx]
    return {
        "records": n,
        "rounds": recs[-1].get("step"),
        "load_imbalance_mean": round(float(np.mean(imbs)), 4),
        "load_imbalance_max": round(float(np.max(imbs)), 4),
        "engines": {eid: {
            "role": a.get("role"),
            "alive_rounds": a["alive_rounds"],
            "dead_rounds": a["dead_rounds"],
            "utilization_mean": (round(float(np.mean(a["util"])), 4)
                                 if a["util"] else None),
            "utilization_max": (round(float(np.max(a["util"])), 4)
                                if a["util"] else None),
            "active_mean": (round(float(np.mean(a["active"])), 2)
                            if a["active"] else None),
            "waiting_max": (int(max(a["waiting"]))
                            if a["waiting"] else None),
        } for eid, a in sorted(agg.items())},
        "timeline": timeline,
    }


def _render_fleet_health(out: list, fh: dict) -> None:
    out.append("")
    out.append(f"fleet health: {fh['records']} round record(s) "
               f"(through round {fh['rounds']}), load imbalance "
               f"mean {fh['load_imbalance_mean']} / "
               f"max {fh['load_imbalance_max']}")
    for eid, a in fh["engines"].items():
        if a["alive_rounds"] == 0:
            out.append(f"  {eid:8s} dead for all "
                       f"{a['dead_rounds']} recorded round(s)")
            continue
        dead = (f", dead {a['dead_rounds']} round(s)"
                if a["dead_rounds"] else "")
        out.append(f"  {eid:8s} [{a.get('role')}]  util mean "
                   f"{a['utilization_mean']} max {a['utilization_max']}"
                   f"  active mean {a['active_mean']}  waiting max "
                   f"{a['waiting_max']}{dead}")
    out.append("  utilization timeline (sampled):")
    for row in fh["timeline"]:
        cells = "  ".join(
            f"{eid} {'dead' if u is None else format(u, '.2f')}"
            for eid, u in sorted(row["utilization"].items()))
        out.append(f"    round {row['round']:>4}  "
                   f"imb {row['load_imbalance']:.2f}  {cells}")


def _tenant_of(rec) -> str:
    """The per-tenant bucket key (schema v13): null tenants fold under
    the driver's single-tenant bucket — ONE definition
    (runtime/workload.py ``tenant_key``), so record-side and
    driver-side counts reconcile key for key by construction."""
    from .runtime.workload import tenant_key
    return tenant_key(rec.get("tenant"))


def _workload_fold(streams) -> dict | None:
    """Fold the schema-v13 workload plane: trace identity + the
    offered-vs-served interval curve from the driver's ``workload``
    records, and per-tenant latency/TTFT/ITL percentiles +
    shed/quarantine counts from the per-request records — with the
    cross-check that the driver's cumulative per-tenant counts
    RECONCILE with the request records (sum of per-tenant completions
    == fleet-wide completions; a mismatch renders, never hides)."""
    wl_recs = sorted((r for s in streams for r in s.workloads),
                     key=lambda r: (r.get("t", 0.0), r.get("step", 0)))
    comp = _merged_completions(streams)
    has_tenants = any(r.get("tenant") is not None
                      for s in streams for r in s.requests)
    if not wl_recs and not has_tenants:
        return None
    out: dict = {}
    if wl_recs:
        out["trace"] = wl_recs[0].get("trace")
        n = len(wl_recs)
        idx = (range(n) if n <= 16 else
               sorted({round(i * (n - 1) / 15) for i in range(16)}))
        out["intervals"] = [{
            "step": wl_recs[i].get("step"),
            "offered": wl_recs[i].get("offered"),
            "admitted": wl_recs[i].get("admitted"),
        } for i in idx]
        out["offered_total"] = sum(int(r.get("offered") or 0)
                                   for r in wl_recs)
        out["admitted_total"] = sum(int(r.get("admitted") or 0)
                                    for r in wl_recs)
        # the driver's cumulative per-tenant book: the LAST record is
        # the totals (monotonic by contract)
        out["driver_tenants"] = wl_recs[-1].get("tenants") or {}
    # per-tenant slices off the per-request records (merged + deduped
    # like every fleet-level read)
    tenants: dict = {}

    def bucket(t):
        return tenants.setdefault(t, {
            "completed": 0, "quarantined": 0, "shed": 0,
            "latencies": [], "ttfts": []})

    for r in comp.values():
        b = bucket(_tenant_of(r))
        b["completed"] += 1
        if r.get("latency_s") is not None:
            b["latencies"].append(r["latency_s"])
        if r.get("ttft_s") is not None:
            b["ttfts"].append(r["ttft_s"])
    seen_q = set()
    seen_exp = set()
    for s in streams:
        for r in s.requests:
            key = (r.get("uid"), r.get("event"), r.get("step"))
            if r["event"] == "quarantined":
                if key in seen_q:
                    continue
                seen_q.add(key)
                bucket(_tenant_of(r))["quarantined"] += 1
            elif r["event"] == "expired":
                # by UID, not (uid, step): a request that expired on a
                # dead engine after its last snapshot re-expires on the
                # survivor it was replayed to — two records, ONE
                # caller-visible loss (the fleet summary's
                # expired_uids stance)
                if r.get("uid") in seen_exp:
                    continue
                seen_exp.add(r.get("uid"))
                bucket(_tenant_of(r))["shed"] += 1
    # driver-counted admission sheds (the request records never saw a
    # shed request's tenant — the anonymous uid -1)
    for t, c in (out.get("driver_tenants") or {}).items():
        if c.get("shed"):
            bucket(t)["shed"] += int(c["shed"])
    # per-tenant ITL off the decode-segment spans (spans pin tenant)
    itl: dict = {}
    for ss in _merged_spans(streams).values():
        for sp in ss:
            if sp["span"] == "decode" and sp.get("tokens") \
                    and sp.get("duration_s") is not None:
                itl.setdefault(_tenant_of(sp), []).append(
                    sp["duration_s"] / sp["tokens"])
    folded = {}
    for t in sorted(tenants):
        b = tenants[t]
        e = {"completed": b["completed"],
             "quarantined": b["quarantined"], "shed": b["shed"]}
        if b["latencies"]:
            (e["latency_p50_s"], e["latency_p90_s"],
             e["latency_p99_s"]) = _pct3(b["latencies"])
        if b["ttfts"]:
            (e["ttft_p50_s"], e["ttft_p90_s"],
             e["ttft_p99_s"]) = _pct3(b["ttfts"])
        if itl.get(t):
            (e["itl_p50_s"], e["itl_p90_s"],
             e["itl_p99_s"]) = _pct3(itl[t], 6)
        folded[t] = e
    out["tenants"] = folded
    # the reconciliation: per-tenant sums vs fleet totals, and the
    # driver's book vs the records' — numbers that disagree are a
    # measurement bug, so the report SAYS so instead of averaging it
    total_completed = sum(e["completed"] for e in folded.values())
    out["completed_total"] = len(comp)
    out["reconciled"] = total_completed == len(comp)
    if wl_recs:
        drv = out["driver_tenants"]
        rec_ok = all(
            folded.get(t, {}).get("completed") == c.get("completed")
            for t, c in drv.items())
        out["reconciled"] = out["reconciled"] and rec_ok
    return out


def _render_workload(out: list, wl: dict) -> None:
    out.append("")
    tr = wl.get("trace") or {}
    head = "workload"
    if tr:
        head += (f" [trace {tr.get('id')} v{tr.get('version')}]")
    offered = wl.get("offered_total")
    if offered is not None:
        head += (f": {offered} offered, {wl.get('admitted_total')} "
                 f"admitted, {wl.get('completed_total')} completed")
    out.append(head + ("" if wl["reconciled"] else
                       "  [NOT RECONCILED — per-tenant sums disagree "
                       "with fleet totals]"))
    for t, e in wl["tenants"].items():
        line = (f"  tenant {t:10s} {e['completed']} completed, "
                f"{e['shed']} shed, {e['quarantined']} quarantined")
        if "latency_p50_s" in e:
            line += (f"  latency p50 {e['latency_p50_s']}s "
                     f"p99 {e['latency_p99_s']}s")
        if "ttft_p50_s" in e:
            line += (f"  TTFT p50 {e['ttft_p50_s']}s "
                     f"p99 {e['ttft_p99_s']}s")
        if "itl_p50_s" in e:
            line += (f"  ITL p50 {e['itl_p50_s']}s "
                     f"p99 {e['itl_p99_s']}s")
        out.append(line)
    if wl.get("intervals"):
        out.append("  offered vs admitted per interval (sampled):")
        for row in wl["intervals"]:
            out.append(f"    round {row['step']:>4}  offered "
                       f"{row['offered']:>3}  admitted "
                       f"{row['admitted']:>3}")


def _render_slo(out: list, slo: dict) -> None:
    out.append("")
    pct = ("n/a" if slo["attainment"] is None
           else f"{slo['attainment'] * 100:.1f}%")
    out.append(f"SLO attainment (TTFT <= {slo['slo_ttft_s']}s, "
               f"ITL <= {slo['slo_itl_s']}s): {pct} — "
               f"{slo['attained']}/{slo['completed']} attained, "
               f"{slo['violated']} violated, "
               f"{slo['unreconciled']} unreconciled")
    if slo["violations_by_span"]:
        out.append("  violations by attributed span: " + ", ".join(
            f"{k} {v}" for k, v in sorted(
                slo["violations_by_span"].items(),
                key=lambda kv: -kv[1])))
    bt = slo.get("by_tenant") or {}
    if bt and set(bt) != {"default"}:
        # the per-tenant goodput slice (v13): print only on a real
        # multi-tenant run — a single-tenant report already said it
        for t, b in sorted(bt.items()):
            pct = ("n/a" if b["attainment"] is None
                   else f"{b['attainment'] * 100:.1f}%")
            out.append(f"  tenant {t:10s} goodput {pct} — "
                       f"{b['attained']}/{b['completed']} attained, "
                       f"{b['violated']} violated, "
                       f"{b['unreconciled']} unreconciled")
    bp = slo.get("by_policy") or {}
    if bp:
        # the per-policy goodput slice (v14): only labelled runs
        # (``generate --policy``) land here — the policy-search readout
        for p, b in sorted(bp.items()):
            pct = ("n/a" if b["attainment"] is None
                   else f"{b['attainment'] * 100:.1f}%")
            out.append(f"  policy {p:10s} goodput {pct} — "
                       f"{b['attained']}/{b['completed']} attained, "
                       f"{b['violated']} violated, "
                       f"{b['unreconciled']} unreconciled")
    for e in slo["requests"]:
        if e["status"] == "attained":
            continue
        if e["status"] == "unreconciled":
            out.append(f"  uid {e['uid']} UNRECONCILED — {e.get('why')}")
            continue
        viol = "+".join(e.get("violates", []))
        bd = ", ".join(f"{k} {v}s" for k, v in
                       list(e.get("breakdown", {}).items())[:4])
        out.append(f"  uid {e['uid']} VIOLATED ({viol}: ttft "
                   f"{e['ttft_s']}s, itl {e['itl_s']}s) -> attributed "
                   f"{e.get('attributed')}"
                   + (" [migrated]" if e["migrated"] else "")
                   + (f"  ({bd})" if bd else ""))


def _render_engine_sections(out: list, doc: dict) -> None:
    """Text render of one stream's folded sections (appended to
    ``out``) — shared between the single- and multi-stream layouts."""
    if doc.get("run"):
        out.append("run config:")
        for k, v in doc["run"].items():
            out.append(f"  {k}: {v}")
    for strat, st in doc.get("steps", {}).items():
        out.append("")
        out.append(f"steps [{strat}]: {st['logged_steps']} logged "
                   f"record(s), steps {st['first_step']}.."
                   f"{st['last_step']}")
        if "step_time_p50_ms" in st:
            out.append(f"  step time   p50 {st['step_time_p50_ms']} ms  "
                       f"p90 {st['step_time_p90_ms']} ms  "
                       f"p99 {st['step_time_p99_ms']} ms "
                       "(steady-state: first logged chunk excluded)")
        if "tokens_per_sec_mean" in st:
            out.append(f"  throughput  mean {st['tokens_per_sec_mean']} "
                       f"tok/s  best {st['tokens_per_sec_best']} tok/s")
        if "mfu_mean" in st:
            out.append(f"  MFU         mean {st['mfu_mean']}  "
                       f"best {st['mfu_best']}")
        if "first_loss" in st:
            out.append(f"  loss        {st['first_loss']} -> "
                       f"{st['last_loss']}")
        if "hbm_high_water_bytes" in st:
            out.append("  HBM high-water  "
                       + _fmt_bytes(st["hbm_high_water_bytes"]))
    if doc.get("serving"):
        sv = doc["serving"]
        out.append("")
        out.append(f"serving [{sv.get('kv_dtype')}]: "
                   f"{sv['records']} decode record(s), "
                   f"{sv.get('engine_steps')} engine step(s), "
                   f"{sv.get('tokens_generated')} token(s), "
                   f"{sv.get('compiled_programs')} compiled program(s)")
        if "tokens_per_sec_mean" in sv:
            out.append(f"  throughput  mean {sv['tokens_per_sec_mean']} "
                       f"tok/s  best {sv['tokens_per_sec_best']} tok/s")
        if "batch_occupancy_mean" in sv:
            out.append(f"  occupancy   mean {sv['batch_occupancy_mean']}")
        if "accept_rate" in sv:
            out.append(f"  speculation accept rate {sv['accept_rate']}  "
                       f"({sv.get('accepted_tokens')}/"
                       f"{sv.get('drafted_tokens')} drafted; "
                       f"{sv.get('tokens_per_step')} tokens/step)")
        if "prefix_hit_blocks" in sv:
            rate = sv.get("prefix_hit_rate")
            out.append(f"  prefix cache hit {sv['prefix_hit_blocks']} "
                       f"block(s)"
                       + (f" (rate {rate})" if rate is not None else "")
                       + f", saved {sv.get('prefill_tokens_saved')} "
                       f"prefill token(s), peak "
                       f"{sv.get('shared_blocks_max')} shared block(s), "
                       f"{sv.get('cow_copies')} CoW cop(ies)")
        if "spilled_blocks" in sv:
            out.append(f"  KV spill    {sv['spilled_blocks']} "
                       f"demotion(s) ({_fmt_bytes(sv.get('spill_bytes'))}"
                       f"), {sv.get('restores')} restore(s) saving "
                       f"{sv.get('restore_tokens_saved')} prefill "
                       f"token(s) in {sv.get('restore_stall_s')}s, "
                       f"peak host tier "
                       f"{sv.get('host_tier_utilization_max')}")
        if "partial_hits" in sv:
            out.append(f"  KV spill    {sv['partial_hits']} sub-block "
                       "partial hit(s)")
        if "kv_pool_utilization_max" in sv:
            out.append("  KV pool     max utilization "
                       f"{sv['kv_pool_utilization_max']}")
        if "free_blocks_low_water" in sv:
            out.append(f"  KV pool     free-block low water "
                       f"{sv['free_blocks_low_water']}, churn "
                       f"{sv.get('block_allocs')} alloc(s) / "
                       f"{sv.get('block_frees')} free(s) / "
                       f"{sv.get('block_scrubs')} scrub(s)")
        if "kv_fragmentation_max" in sv:
            out.append(f"  KV pool     max fragmentation "
                       f"{sv['kv_fragmentation_max']}  stored "
                       + _fmt_bytes(sv.get("kv_bytes_stored_max")))
    if doc.get("serving_reliability"):
        rl = doc["serving_reliability"]
        out.append("")
        out.append(f"serving reliability: {rl['admitted']} admission(s), "
                   f"{rl['completed']} completed, "
                   f"{rl['quarantined']} quarantine(s), "
                   f"{rl['retried']} retry(ies), "
                   f"{rl['preempted']} preemption(s), "
                   f"{rl['shed']} shed "
                   f"({rl['rejected']} rejected / {rl['expired']} "
                   "expired)")
        if rl.get("failed_uids"):
            out.append(f"  FAILED uids: {rl['failed_uids']}")
        if "latency_p50_s" in rl:
            out.append(f"  request latency  p50 {rl['latency_p50_s']}s  "
                       f"p90 {rl['latency_p90_s']}s  "
                       f"p99 {rl['latency_p99_s']}s")
        if "ttft_p50_s" in rl:
            out.append(f"  TTFT             p50 {rl['ttft_p50_s']}s  "
                       f"p90 {rl['ttft_p90_s']}s  "
                       f"p99 {rl['ttft_p99_s']}s")
        if "itl_p50_s" in rl:
            out.append(f"  ITL (per decode segment)  "
                       f"p50 {rl['itl_p50_s']}s  "
                       f"p90 {rl['itl_p90_s']}s  "
                       f"p99 {rl['itl_p99_s']}s")
        if len(rl.get("completed_by_version") or {}) > 1:
            out.append("  completions by weights version: " + ", ".join(
                f"{k} x{v}" for k, v in sorted(
                    rl["completed_by_version"].items())))
    rec = doc.get("recovery", {})
    if (rec.get("attempts_failed") or rec.get("nonfinite_skips")
            or rec.get("attempt_log")
            or rec.get("in_graph_skips") or rec.get("rollbacks")):
        out.append("")
        out.append(f"recovery: {rec['in_graph_skips']} in-graph "
                   f"skip(s), {rec['rollbacks']} rollback(s), "
                   f"{rec['loss_spikes']} loss spike(s), "
                   f"{rec['attempts_failed']} failed "
                   f"attempt(s), {rec['nonfinite_skips']} non-finite "
                   f"skip(s), {rec['publishes']} checkpoint "
                   f"publish(es), run "
                   + ("COMPLETED" if rec["completed"] else
                      "did not record completion"))


def _render_waterfalls(out: list, label: str | None, wf: dict) -> None:
    if not wf:
        return
    out.append("")
    tag = f" [{label}]" if label else ""
    out.append(f"per-request waterfalls{tag}:")
    shown = 0
    for uid, w in wf.items():
        if shown >= 16:
            out.append(f"  ... {len(wf) - shown} more request(s) "
                       "(see --json for all)")
            break
        shown += 1
        verdict = ("reconciled" if w["reconciled"] else
                   ("no completion record" if w["latency_s"] is None
                    else "NOT RECONCILED — unaccounted wall time"))
        lat = ("" if w["latency_s"] is None
               else f", latency {w['latency_s']}s")
        ttft = ("" if w.get("ttft_s") is None
                else f", ttft {w['ttft_s']}s")
        out.append(f"  uid {uid} — {len(w['spans'])} span(s), "
                   f"span sum {w['span_sum_s']}s{lat}{ttft} "
                   f"({verdict})")
        for s in w["spans"]:
            dur = s.get("duration_s")
            out.append(f"    {s['span']:12s} "
                       f"{dur if dur is not None else '?':>9}s  "
                       f"steps {s.get('start_step')}.."
                       f"{s.get('end_step')}")


def _alerts_active_at(alerts: list, t: float) -> list:
    """The watchtower alerts active (fired, unresolved) at wall time
    ``t`` — ``alerts`` pre-sorted by envelope time. Drift alerts key
    per metric (one detector name, two lifecycles)."""
    active: dict = {}
    for a in alerts:
        if a.get("t", 0.0) > t:
            break
        key = (a.get("detector"), a.get("metric"))
        if a.get("event") == "fired":
            active[key] = a
        else:
            active.pop(key, None)
    return [{"detector": a.get("detector"),
             "severity": a.get("severity"),
             "since_round": a.get("step")}
            for _, a in sorted(active.items(),
                               key=lambda kv: str(kv[0]))]


def _render_postmortem(out: list, label: str | None,
                       fr: dict | None) -> None:
    tag = f" [{label}]" if label else ""
    out.append("")
    if fr is None:
        out.append(f"postmortem{tag}: no flight-recorder dump (the "
                   "engine dumps on quarantine / watchdog / kill only)")
        return
    if fr.get("error"):
        out.append(f"postmortem{tag}: {fr['error']}")
        return
    out.append(f"postmortem{tag}: {fr.get('reason')!r} @ engine step "
               f"{fr.get('step')} — {len(fr.get('digests', []))} "
               f"step digest(s) ({fr.get('path')})")
    if fr.get("alerts_at_dump"):
        out.append("  active alert(s) at declaration: " + ", ".join(
            f"{a['detector']} [{a['severity']}] since round "
            f"{a['since_round']}" for a in fr["alerts_at_dump"]))
    for d in fr.get("digests", []):
        bits = [f"step {d.get('step'):>4}",
                f"occ {d.get('occupancy'):.2f}",
                f"free {d.get('free_blocks')}",
                f"waiting {d.get('waiting')}"]
        if d.get("prefill_uid") is not None:
            bits.append(f"prefill uid {d['prefill_uid']}")
        if d.get("decode_uids"):
            bits.append(f"decode uids {d['decode_uids']}")
        if d.get("finite") is not None and not all(d["finite"]):
            bits.append(f"FINITE {d['finite']}")
        line = "  " + "  ".join(bits)
        if d.get("events"):
            line += "  | " + "; ".join(d["events"])
        out.append(line)


# ---- golden-stream diffing (v15, DESIGN.md section 27) --------------
# Two replays of one committed trace must agree on every pinned value;
# where they legitimately differ is WALL TIME — the unpinned envelope
# plus any measured duration/throughput. The differ strips the
# envelope, localizes the first divergent record, and classifies what
# kind of drift it is so "the replays differ" is never the end of the
# diagnosis. scripts/stream_diff.py is the standalone CLI over the
# same functions.

# a differing key is TIMING (not a determinism break) when it measures
# wall-clock — matched by suffix so new measured fields inherit the
# classification without a registry edit
_TIMING_SUFFIXES = ("_s", "_ms", "_us", "_per_sec")
_TIMING_KEYS = {"t", "t_start", "t_end", "dt", "tokens_per_sec"}


def _is_timing_key(key: str) -> bool:
    return key in _TIMING_KEYS or key.endswith(_TIMING_SUFFIXES)


# inside a record's nested ``transport`` attribution these keys name
# HOW the bytes moved, not WHAT moved — two honest replays of one run
# under different transports (inproc vs process vs tcp) legitimately
# disagree on them while every pinned value (tokens, bytes, blocks,
# positions) must still match
_TRANSPORT_EQUIV_KEYS = {"mode"}


def _transport_equiv(va, vb) -> bool:
    """True when two ``transport`` values differ only by carrier: the
    meta record's transport label (a string), or a migration record's
    attribution dict differing only in ``mode`` and wall-clock
    measurements (``crc_verify_s`` — the in-process mode honestly
    reports None where a wire mode reports a verify wall). Any pinned
    content key (``bytes``, ``retries``) must agree."""
    if isinstance(va, str) and isinstance(vb, str):
        return True
    if not (isinstance(va, dict) and isinstance(vb, dict)):
        return False
    if va.keys() != vb.keys():
        return False
    return all(va[k] == vb[k] or k in _TRANSPORT_EQUIV_KEYS
               or _is_timing_key(k) for k in va)


def _is_benign_diff(key: str, ra: dict, rb: dict) -> bool:
    """A differing key that does NOT break determinism: a wall-clock
    measurement, or a transport attribution differing only by
    carrier (the transport-mode-only class — two transports replaying
    one trace token-identically)."""
    if _is_timing_key(key):
        return True
    return key == "transport" and _transport_equiv(ra.get(key),
                                                   rb.get(key))


def load_diff_stream(metrics_dir: str,
                     kinds: tuple | None = None) -> list[dict]:
    """One side of a golden-stream diff: the dir's ``metrics.jsonl``
    in append order, schema-valid records only, the unpinned wall
    envelope (``t``) stripped. ``kinds`` filters to those record
    kinds (e.g. ``("alert",)`` for the replay-identity check)."""
    path = metrics_dir
    if os.path.isdir(path):
        path = os.path.join(path, METRICS_FILENAME)
    records, _problems = read_metrics(path)
    out = []
    for r in records:
        if kinds is not None and r.get("kind") not in kinds:
            continue
        r = dict(r)
        r.pop("t", None)
        out.append(r)
    return out


def diff_streams(a: list[dict], b: list[dict]) -> dict:
    """Localize + classify the first divergence between two record
    streams (each from ``load_diff_stream``). Returns a dict with
    ``verdict`` one of:

    - ``identical`` — byte-equivalent after envelope stripping;
    - ``timing-only`` — records align and every differing key is a
      wall-clock measurement or a transport-mode-only attribution
      (two honest replays of one run — possibly on two transports);
    - ``token-divergence`` — a pinned content key differs, or one
      stream holds records the other lacks (THE determinism break);
    - ``schema-drift`` — aligned records disagree on kind/key-set/
      schema version (different writers, not different runs).

    Verdict severity is schema-drift > token-divergence > timing-only;
    ``index``/``a``/``b``/``keys`` localize the first record of the
    verdict's class."""
    first: dict[str, tuple] = {}
    for i in range(min(len(a), len(b))):
        ra, rb = a[i], b[i]
        if ra == rb:
            continue
        if (ra.get("kind") != rb.get("kind")
                or ra.get("schema") != rb.get("schema")
                or ra.keys() != rb.keys()):
            first.setdefault("schema-drift", (i, ra, rb, sorted(
                ra.keys() ^ rb.keys())))
            continue
        keys = sorted(k for k in ra if ra[k] != rb[k])
        if all(_is_benign_diff(k, ra, rb) for k in keys):
            first.setdefault("timing-only", (i, ra, rb, keys))
        else:
            first.setdefault("token-divergence",
                             (i, ra, rb,
                              [k for k in keys
                               if not _is_benign_diff(k, ra, rb)]))
    if len(a) != len(b):
        i = min(len(a), len(b))
        first.setdefault("token-divergence",
                         (i, a[i] if i < len(a) else None,
                          b[i] if i < len(b) else None, ["<length>"]))
    for verdict in ("schema-drift", "token-divergence", "timing-only"):
        if verdict in first:
            i, ra, rb, keys = first[verdict]
            return {"verdict": verdict, "index": i, "keys": keys,
                    "a": ra, "b": rb,
                    "n_a": len(a), "n_b": len(b)}
    return {"verdict": "identical", "n_a": len(a), "n_b": len(b)}


# ---- telemetry invariant audit (v15, DESIGN.md section 27) ----------
# The one-shot auditor behind `report --audit`: every invariant the
# writers are SUPPOSED to hold, checked over a finished run's metrics
# dirs. The catalog is ordered — rc 2 names the FIRST violated
# invariant and the record that broke it, so a red audit is a
# diagnosis, not a boolean.

def _audit_violation(inv: str, stream, what: str) -> str:
    return (f"audit: VIOLATION [{inv}] in {stream.path}: {what}")


def _audit_schema(streams) -> str | None:
    for s in streams:
        if s.problems:
            return _audit_violation("schema", s, s.problems[0])
    return None


def _audit_span_reconciliation(streams) -> str | None:
    """Span telescoping + request latency arithmetic: every span ends
    at-or-after it starts (both clocks), and a completed request's
    TTFT never exceeds its latency (``ttft_s + post-first-token time
    == latency_s`` is the waterfall fold's reconciliation; the hard
    invariant auditable per record is the ordering)."""
    for s in streams:
        for sp in s.spans:
            if (sp.get("start_step") is not None
                    and sp["start_step"] > sp["step"]):
                return _audit_violation(
                    "span_reconciliation", s,
                    f"span {sp.get('span')!r} uid {sp.get('uid')} "
                    f"starts at step {sp['start_step']} AFTER its end "
                    f"step {sp['step']}")
            if (sp.get("t_start") is not None
                    and sp["t_start"] > sp["t"] + 1e-9):
                return _audit_violation(
                    "span_reconciliation", s,
                    f"span {sp.get('span')!r} uid {sp.get('uid')} "
                    f"t_start {sp['t_start']} after its end t "
                    f"{sp['t']}")
        for r in s.requests:
            if r.get("event") != "completed":
                continue
            ttft, lat = r.get("ttft_s"), r.get("latency_s")
            if (ttft is not None and lat is not None
                    and ttft > lat + RECONCILE_TOL_S):
                return _audit_violation(
                    "span_reconciliation", s,
                    f"completed uid {r.get('uid')} has ttft_s {ttft} "
                    f"> latency_s {lat}")
            if r.get("n_new") is not None and r["n_new"] < 1:
                return _audit_violation(
                    "span_reconciliation", s,
                    f"completed uid {r.get('uid')} claims n_new "
                    f"{r['n_new']} (< 1 token)")
    return None


def _audit_counter_monotonicity(streams) -> str | None:
    """Per-stream clocks and cumulative books never run backwards —
    across resume too (replayed records re-emit at their original,
    stable steps)."""
    for s in streams:
        last_fleet = None
        for f in s.fleets:
            if last_fleet is not None and f["step"] <= last_fleet:
                return _audit_violation(
                    "counter_monotonicity", s,
                    f"fleet round {f['step']} after round "
                    f"{last_fleet} (round clock ran backwards)")
            last_fleet = f["step"]
        last = None
        for d in s.decodes:
            if last is not None and d["step"] < last:
                return _audit_violation(
                    "counter_monotonicity", s,
                    f"decode record at step {d['step']} after step "
                    f"{last}")
            last = d["step"]
        # the workload driver's cumulative per-tenant book
        prev: dict = {}
        for w in s.workloads:
            for tn, c in (w.get("tenants") or {}).items():
                for key in ("offered", "completed", "shed"):
                    cur = int(c.get(key) or 0)
                    if cur < prev.get((tn, key), 0):
                        return _audit_violation(
                            "counter_monotonicity", s,
                            f"workload record @ round {w['step']}: "
                            f"tenant {tn} cumulative {key} fell "
                            f"{prev[(tn, key)]} -> {cur}")
                    prev[(tn, key)] = cur
    return None


def _audit_tenant_reconciliation(streams) -> str | None:
    """The final workload record's per-tenant book must balance:
    completed + shed never exceeds offered, and the interval counters
    sum to no more than the cumulative offered."""
    for s in streams:
        if not s.workloads:
            continue
        final = s.workloads[-1]
        for tn, c in (final.get("tenants") or {}).items():
            off = int(c.get("offered") or 0)
            done = int(c.get("completed") or 0)
            shed = int(c.get("shed") or 0)
            if done + shed > off:
                return _audit_violation(
                    "tenant_reconciliation", s,
                    f"tenant {tn}: completed {done} + shed {shed} > "
                    f"offered {off} in the final workload record")
        total_off = sum(int(w.get("offered") or 0)
                        for w in s.workloads)
        cum_off = sum(int(c.get("offered") or 0)
                      for c in (final.get("tenants") or {}).values())
        if total_off != cum_off:
            return _audit_violation(
                "tenant_reconciliation", s,
                f"interval offered counts sum to {total_off} but the "
                f"final cumulative book holds {cum_off}")
    return None


def _audit_trace_consistency(streams) -> str | None:
    """One uid, one trace_id — across every stream in the set (the
    spine of cross-process stitching; a uid with two trace ids can't
    be traced)."""
    seen: dict = {}
    for s in streams:
        for r in (*s.requests, *s.spans, *s.routers):
            uid, tid = r.get("uid"), r.get("trace_id")
            if uid is None or uid == -1 or tid is None:
                continue
            if uid in seen and seen[uid][0] != tid:
                return _audit_violation(
                    "trace_consistency", s,
                    f"uid {uid} carries trace_id {tid!r} but "
                    f"{seen[uid][1]} recorded {seen[uid][0]!r}")
            seen.setdefault(uid, (tid, s.path))
    return None


def _audit_router_xref(streams) -> str | None:
    """Router decisions cross-reference request outcomes: a uid the
    router shed never completes, and a uid the router moved
    (handoff/migration) was routed first."""
    shed, routed, moved = set(), set(), {}
    for s in streams:
        for r in s.routers:
            uid = r.get("uid")
            if uid is None or uid == -1:
                continue
            if r["event"] == "shed":
                shed.add(uid)
            elif r["event"] == "routed":
                routed.add(uid)
            elif r["event"] in ("handoff", "migrated"):
                moved.setdefault(uid, r)
    if not (shed or routed or moved):
        return None     # no router stream in the set — nothing to xref
    for s in streams:
        for r in s.requests:
            if r.get("event") == "completed" and r.get("uid") in shed:
                return _audit_violation(
                    "router_xref", s,
                    f"uid {r['uid']} completed but the router shed it")
    for uid, r in sorted(moved.items()):
        if uid not in routed:
            for s in streams:
                if r in s.routers:
                    return _audit_violation(
                        "router_xref", s,
                        f"uid {uid} was {r['event']} @ round "
                        f"{r.get('step')} without a routed record")
    return None


def _audit_dedup(streams) -> str | None:
    """Replayed records must be REPLAYS: duplicate (uid, event, step)
    request records within one stream agree on their deterministic
    payload (token count), or a resume double-counted work."""
    for s in streams:
        by: dict = {}
        for r in s.records:
            if r["kind"] == "request":
                by.setdefault((r.get("uid"), r.get("event"),
                               r.get("step")), []).append(r)
        for (uid, ev, step), recs in by.items():
            if len(recs) < 2 or ev == "rejected":
                continue
            n_new = {r.get("n_new") for r in recs}
            if len(n_new) > 1:
                return _audit_violation(
                    "dedup", s,
                    f"uid {uid} {ev} @ step {step} recorded "
                    f"{len(recs)}x with differing n_new "
                    f"{sorted(n_new, key=str)}")
    return None


# ordered: rc 2 names the FIRST violated invariant in THIS order
_AUDIT_CATALOG = (
    ("schema", _audit_schema),
    ("span_reconciliation", _audit_span_reconciliation),
    ("counter_monotonicity", _audit_counter_monotonicity),
    ("tenant_reconciliation", _audit_tenant_reconciliation),
    ("trace_consistency", _audit_trace_consistency),
    ("router_xref", _audit_router_xref),
    ("dedup", _audit_dedup),
)


def audit_streams(streams) -> str | None:
    """Run the ordered invariant catalog over the stream set; None
    when every invariant holds, else the first violation line."""
    for _name, check in _AUDIT_CATALOG:
        msg = check(streams)
        if msg is not None:
            return msg
    return None


def report_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="report",
        description="Fold one or more --metrics_dir runs (+ supervise "
                    "attempt logs + optional profile dir) into one run "
                    "report; multiple dirs merge onto one timeline "
                    "with per-engine stats")
    p.add_argument("metrics_dirs", nargs="+",
                   help="the run's --metrics_dir (holds metrics.jsonl); "
                        "pass several to merge engines onto one "
                        "timeline")
    p.add_argument("--attempt_log", default=None,
                   help="supervise's per-attempt JSONL (default: "
                        "discovered from each run's meta records)")
    p.add_argument("--profile_dir", default=None,
                   help="a trace directory captured with --profile_dir; "
                        "adds comm/compute overlap + per-named-scope "
                        "totals")
    p.add_argument("--postmortem", action="store_true",
                   help="render each stream's flight-recorder dump "
                        "(per-step scheduler digests persisted on "
                        "quarantine / watchdog / kill)")
    p.add_argument("--slo", default=None, metavar="TTFT_S:ITL_S",
                   help="serving-SLO goodput accounting over the "
                        "merged streams: attainment of TTFT <= TTFT_S "
                        "and observed inter-token latency <= ITL_S "
                        "over completed requests, each violation "
                        "attributed to its dominant span (queued / "
                        "prefill / replay / decode / preempt_gap / "
                        "quarantine / migration); e.g. --slo 0.5:0.05")
    p.add_argument("--trace", default=None, metavar="UID",
                   help="render ONE request's cross-engine causal "
                        "waterfall, stitched by its trace_id (schema "
                        "v12): spans, router moves, and lifecycle "
                        "events across every given stream in causal "
                        "order, with unexplained wall-clock gaps "
                        "flagged UNRECONCILED; rc 2 on a non-integer "
                        "or unknown uid")
    p.add_argument("--follow", action="store_true",
                   help="tail mode: poll the streams, print NEW "
                        "timeline entries as they land, exit rc 0 "
                        "when the router's fleet status doc reports "
                        "the fleet drained (or after --follow_max_s)")
    p.add_argument("--follow_interval", type=float, default=0.5,
                   help="poll cadence of --follow in seconds")
    p.add_argument("--follow_max_s", type=float, default=60.0,
                   help="--follow gives up (rc 0, with a note) after "
                        "this many seconds without a drained status")
    p.add_argument("--audit", action="store_true",
                   help="one-shot telemetry invariant audit over the "
                        "given metrics dir(s): schema validity, span "
                        "telescoping + latency arithmetic, counter "
                        "monotonicity across resume, per-tenant "
                        "reconciliation, trace_id consistency, "
                        "router/request cross-references, replay "
                        "dedup; rc 0 clean, rc 2 naming the FIRST "
                        "violated invariant and the record")
    p.add_argument("--diff", action="store_true",
                   help="golden-stream diff of EXACTLY TWO metrics "
                        "dirs: strips the wall envelope, localizes "
                        "the first divergent record, classifies it "
                        "timing-only / token-divergence / "
                        "schema-drift; rc 0 when identical or "
                        "timing-only, rc 2 otherwise")
    p.add_argument("--kinds", default=None, metavar="K1,K2",
                   help="--diff filter: compare only these record "
                        "kinds (e.g. --kinds alert for the alert-"
                        "history replay-identity check)")
    p.add_argument("--json", action="store_true",
                   help="emit the folded report as one JSON object "
                        "instead of text")
    args = p.parse_args(argv)

    # the train-CLI parse discipline: a malformed --trace uid rejects
    # rc 2 BEFORE any stream is read
    trace_uid = None
    if args.trace is not None:
        try:
            trace_uid = int(args.trace)
        except ValueError:
            print(f"report: unparseable --trace {args.trace!r} (want "
                  "a request uid, e.g. --trace 2)", file=sys.stderr)
            return 2
    if args.follow and args.json:
        print("report: --follow is a live text tail; drop --json",
              file=sys.stderr)
        return 2
    if args.audit and args.diff:
        print("report: --audit checks one run's invariants, --diff "
              "compares two runs — pick one", file=sys.stderr)
        return 2
    if args.diff and len(args.metrics_dirs) != 2:
        print(f"report: --diff compares exactly TWO metrics dirs, got "
              f"{len(args.metrics_dirs)}", file=sys.stderr)
        return 2
    if args.kinds is not None and not args.diff:
        print("report: --kinds filters a --diff; pass --diff A B",
              file=sys.stderr)
        return 2
    diff_kinds = None
    if args.kinds is not None:
        diff_kinds = tuple(k.strip() for k in args.kinds.split(",")
                           if k.strip())
        bad = [k for k in diff_kinds if k not in RECORD_KINDS]
        if not diff_kinds or bad:
            print(f"report: unparseable --kinds {args.kinds!r} (want "
                  f"a comma list of record kinds from "
                  f"{'/'.join(RECORD_KINDS)})", file=sys.stderr)
            return 2
    if args.follow_interval <= 0 or args.follow_max_s <= 0:
        print("report: --follow_interval/--follow_max_s must be > 0",
              file=sys.stderr)
        return 2

    # the train-CLI parse discipline: a malformed spec rejects rc 2
    # BEFORE any stream is read
    slo = None
    if args.slo is not None:
        parts = args.slo.split(":")
        try:
            if len(parts) != 2:
                raise ValueError
            slo = (float(parts[0]), float(parts[1]))
            if slo[0] < 0 or slo[1] < 0:
                raise ValueError
        except ValueError:
            print(f"report: unparseable --slo {args.slo!r} (want "
                  "TTFT_S:ITL_S with both >= 0, e.g. 0.5:0.05)",
                  file=sys.stderr)
            return 2

    # an explicit --attempt_log names ONE supervisor log: attach it to
    # the first stream only — giving it to every stream would replay
    # the same recovery events once per engine on the merged timeline
    # (the other streams still auto-discover their own from meta)
    streams = [_Stream(d, args.attempt_log if i == 0 else None)
               for i, d in enumerate(args.metrics_dirs)]
    # engine labels key the merge: disambiguate collisions (two dirs
    # both named "metrics" with no engine_id stamped) instead of
    # silently overwriting one stream's entire report
    seen_labels: dict = {}
    for s in streams:
        n = seen_labels.get(s.label, 0)
        seen_labels[s.label] = n + 1
        if n:
            s.label = f"{s.label}#{n + 1}"
    missing = [s for s in streams if not s.dir_exists]
    if missing:
        for s in missing:
            print(f"report: no metrics stream at {s.path}",
                  file=sys.stderr)
        return 2
    if args.diff:
        res = diff_streams(
            load_diff_stream(args.metrics_dirs[0], diff_kinds),
            load_diff_stream(args.metrics_dirs[1], diff_kinds))
        if args.json:
            print(json.dumps(res, indent=1))
        else:
            what = (f" over kinds {','.join(diff_kinds)}"
                    if diff_kinds else "")
            if res["verdict"] == "identical":
                print(f"diff: identical{what} — {res['n_a']} "
                      "record(s) each, byte-equivalent after "
                      "envelope stripping")
            else:
                print(f"diff: {res['verdict']}{what} @ record "
                      f"{res['index']} (streams hold {res['n_a']} / "
                      f"{res['n_b']} record(s))")
                print(f"  differing key(s): {res['keys']}")
                print(f"  a: {json.dumps(res['a'], sort_keys=True)}")
                print(f"  b: {json.dumps(res['b'], sort_keys=True)}")
        return 0 if res["verdict"] in ("identical",
                                       "timing-only") else 2
    if args.audit:
        msg = audit_streams(streams)
        if msg is not None:
            print(msg, file=sys.stderr)
            return 2
        n = sum(len(s.records) for s in streams)
        print(f"audit: clean — {len(_AUDIT_CATALOG)} invariant(s) "
              f"hold over {n} record(s) across {len(streams)} "
              "stream(s)")
        return 0
    if args.follow:
        # the live tail replaces the one-shot fold (a run may still be
        # record-free while its engines boot — the tail waits for it)
        return _follow(args.metrics_dirs, args.follow_interval,
                       args.follow_max_s)
    multi = len(streams) > 1

    if not any(s.records for s in streams):
        if trace_uid is not None:
            # asking to trace a uid through streams that hold nothing
            # is an unknown-uid error, not a record-free answer
            print(f"report: no record for uid {trace_uid} — the given "
                  "stream(s) hold no schema-valid records",
                  file=sys.stderr)
            return 2
        # a record-free stream is an ANSWER (the run emitted nothing),
        # not a tooling failure: rc 0 with an explicit summary naming
        # whatever failed to validate
        out = []
        for s in streams:
            out.append(f"report: no records — {s.path} holds no "
                       f"schema-valid records "
                       f"({len(s.problems)} problem(s))")
            for prob in s.problems:
                out.append(f"  {prob}")
        if args.json:
            print(json.dumps({
                "no_records": True,
                "streams": [{"metrics_path": s.path,
                             "problems": s.problems}
                            for s in streams]}, indent=1))
        else:
            print("\n".join(out))
        return 0

    # ---- fold every stream ------------------------------------------
    doc: dict = {}
    per_engine: dict = {}
    timeline = []
    waterfalls: dict = {}
    for si, s in enumerate(streams):
        sub = {"metrics_path": s.path, "n_records": len(s.records),
               "problems": s.problems, "run": s.header,
               "steps": s.step_stats(), "recovery": s.recovery()}
        serving = s.serving()
        if serving:
            sub["serving"] = serving
        rel = s.reliability()
        if rel:
            sub["serving_reliability"] = rel
        per_engine[s.label] = sub
        wf = s.waterfalls()
        if wf:
            waterfalls[s.label] = wf
        for order, (t, src, what) in enumerate(s.timeline_entries()):
            timeline.append((t, si, order, src, what, s.label))
    # deterministic merge: equal timestamps break ties by (stream,
    # per-stream entry order), so repeated merges of the same dirs
    # render byte-identical timelines (pinned by test)
    timeline.sort(key=lambda x: (x[0], x[1], x[2]))
    timeline = [(t, src, what, lab)
                for t, _si, _order, src, what, lab in timeline]

    # ---- fleet summary (schema-v8 router records, decode/fleet.py) --
    # the fleet-LEVEL read of the merged streams: routing decisions
    # from any router stream + request outcomes from EVERY stream, so
    # the latency percentiles describe what a caller of the fleet saw,
    # not any one engine
    router_recs = [r for s in streams for r in s.routers]
    if router_recs:
        by_ev: dict[str, int] = {}
        for r in router_recs:
            by_ev[r["event"]] = by_ev.get(r["event"], 0) + 1
        mig_reasons: dict[str, int] = {}
        for r in router_recs:
            if r["event"] == "migrated":
                key = r.get("reason") or "?"
                mig_reasons[key] = mig_reasons.get(key, 0) + 1
        # completions dedupe by uid across streams (a request completed
        # on an engine after its last snapshot re-completes on a
        # survivor when that engine dies — same tokens, two records;
        # the caller saw the FIRST one), and the headline shed counts
        # only CALLER-visible losses: the router's fleet-wide "shed"
        # records plus deadline expiries — never per-engine "rejected"
        # events, which a spillover leaves behind even when the request
        # lands (and completes) on the next engine
        completed = list(_merged_completions(streams).values())
        expired_uids = {r["uid"] for s in streams for r in s.requests
                        if r["event"] == "expired"}
        # routed-policy attribution (v9) + live-move stall stats
        policies: dict[str, int] = {}
        for r in router_recs:
            if r["event"] == "routed" and r.get("policy"):
                policies[r["policy"]] = policies.get(r["policy"], 0) + 1
        moves = [r for r in router_recs
                 if r["event"] in ("handoff", "migrated")
                 and r.get("duration_s") is not None]
        fleet = {
            "engines": len([s for s in streams if s.decodes]),
            "routed": by_ev.get("routed", 0),
            "routed_by_policy": policies,
            "handoffs": by_ev.get("handoff", 0),
            "migrations": by_ev.get("migrated", 0),
            "migrated_by_reason": mig_reasons,
            "shed": by_ev.get("shed", 0) + len(expired_uids),
            "shed_at_router": by_ev.get("shed", 0),
            # v10: CRC/torn/version-rejected wire handoffs (each was
            # replay-rerouted; the records carry the one-line reason)
            "wire_rejected": by_ev.get("wire_rejected", 0),
            "completed": len(completed),
        }
        # v11 live-deploy surface: per-version completion counts dedup
        # BY UID across streams first (a migrated-then-completed
        # request may appear in two engines' files — one uid, one
        # version, one count) and the deploy lifecycle tallies
        vers: dict[str, int] = {}
        for r in completed:
            if r.get("weights_version") is not None:
                key = f"v{r['weights_version']}"
                vers[key] = vers.get(key, 0) + 1
        if vers:
            fleet["completed_by_version"] = vers
        deploy_recs = [d for s in streams for d in s.deploys]
        if deploy_recs:
            fleet["deploys"] = sum(1 for d in deploy_recs
                                   if d["event"] == "completed")
            fleet["deploy_rollbacks"] = sum(1 for d in deploy_recs
                                            if d["event"]
                                            == "rolled_back")
        if moves:
            fleet["handoff_blocks"] = sum(int(r.get("blocks") or 0)
                                          for r in moves)
            fleet["handoff_bytes"] = sum(int(r.get("bytes") or 0)
                                         for r in moves)
            fleet["handoff_stall_p90_ms"] = round(float(np.percentile(
                np.asarray([r["duration_s"] for r in moves],
                           np.float64), 90)) * 1e3, 3)
            # v10 transport attribution: how each move actually
            # crossed (inproc doc / wire file / replay re-queue)
            modes: dict[str, int] = {}
            for r in moves:
                mode = (r.get("transport") or {}).get("mode") or "?"
                modes[mode] = modes.get(mode, 0) + 1
            fleet["moves_by_transport"] = modes
        lat = [r["latency_s"] for r in completed
               if r.get("latency_s") is not None]
        if lat:
            q = np.percentile(np.asarray(lat, np.float64), [50, 90, 99])
            fleet["latency_p50_s"] = round(float(q[0]), 4)
            fleet["latency_p90_s"] = round(float(q[1]), 4)
            fleet["latency_p99_s"] = round(float(q[2]), 4)
        # fleet-wide TTFT/ITL (v9): completions deduped by uid, decode
        # segments pooled across every stream
        ttfts = [r["ttft_s"] for r in completed
                 if r.get("ttft_s") is not None]
        if ttfts:
            (fleet["ttft_p50_s"], fleet["ttft_p90_s"],
             fleet["ttft_p99_s"]) = _pct3(ttfts)
        gaps = _merged_decode_gaps(streams)
        if gaps:
            (fleet["itl_p50_s"], fleet["itl_p90_s"],
             fleet["itl_p99_s"]) = _pct3(gaps, 6)
        doc["fleet"] = fleet

    fh = _fleet_health(streams)
    if fh:
        doc["fleet_health"] = fh
    wl = _workload_fold(streams)
    if wl:
        doc["workload"] = wl
    tp = _transport_fold(streams)
    if tp:
        doc["transport"] = tp
    if slo is not None:
        doc["slo"] = _slo_accounting(streams, *slo)
    if trace_uid is not None:
        tr = _trace_doc(streams, trace_uid)
        if tr is None:
            print(f"report: no record for uid {trace_uid} in the "
                  "given stream(s) — nothing to trace (pass every "
                  "engine's metrics dir plus the router's)",
                  file=sys.stderr)
            return 2
        doc["trace"] = tr

    if multi:
        doc["engines"] = per_engine
        doc["problems"] = [f"[{s.label}] {p}" for s in streams
                           for p in s.problems]
    else:
        doc.update(per_engine[streams[0].label])
    doc["timeline"] = [{"t": t, "source": src, "what": what,
                        **({"engine": lab} if multi else {})}
                       for t, src, what, lab in timeline]
    if waterfalls:
        doc["waterfalls"] = (waterfalls if multi
                             else waterfalls[streams[0].label])

    flights = {}
    rposts: dict = {}
    if args.postmortem:
        flights = {s.label: s.flight_recorder() for s in streams}
        # active-alerts-at-declaration (v15): the worker's flight
        # recorder can't see the router's alert plane, so the merge
        # folds it here — every alert fired but not yet resolved at
        # the dump's wall time was ACTIVE while the engine died
        all_alerts = sorted((a for s in streams for a in s.alerts),
                            key=lambda a: (a.get("t", 0.0),
                                           a.get("step", 0)))
        for fr in flights.values():
            if fr and not fr.get("error") and fr.get("t") is not None:
                fr["alerts_at_dump"] = _alerts_active_at(
                    all_alerts, fr["t"])
        doc["postmortem"] = (flights if multi
                             else flights[streams[0].label])
        rposts = {s.label: v for s in streams
                  if (v := s.router_postmortems())}
        if rposts:
            doc["router_postmortem"] = rposts

    # ---- profile folding (first stream's strategy names the scopes) --
    if args.profile_dir:
        from .utils.trace_analysis import (load_spans, overlap_payload,
                                           scope_totals,
                                           strategy_scope_key)
        # one gunzip+parse feeds both analyses (hardware traces run to
        # hundreds of MB — never load twice)
        trace_file, spans = load_spans(args.profile_dir)
        prof = overlap_payload(spans, trace_file)
        # fold per-region totals under the RUN's strategy when the meta
        # records name one; unknown strategies fall back to the
        # prefixed-regions union (scope_totals documents why)
        scope_key = strategy_scope_key(
            streams[0].header.get("strategy"))
        prof["scope_totals_us"] = {
            k: round(v, 1)
            for k, v in scope_totals(spans, scope_key).items() if v}
        doc["profile"] = prof

    if not multi and streams[0].benches:
        doc["bench_rows"] = len(streams[0].benches)

    if args.json:
        print(json.dumps(doc, indent=1))
        return 0

    # ---- render ------------------------------------------------------
    out = []
    out.append("=" * 72)
    if multi:
        out.append(f"RUN REPORT — {len(streams)} merged stream(s): "
                   + ", ".join(s.label for s in streams))
    else:
        out.append(f"RUN REPORT — {streams[0].path}")
    out.append("=" * 72)
    if doc.get("fleet"):
        # ABOVE the per-engine blocks: the caller-facing fleet view
        fl = doc["fleet"]
        out.append("")
        out.append(f"fleet: {fl['routed']} routed, "
                   f"{fl['handoffs']} prefill handoff(s), "
                   f"{fl['migrations']} migration(s)"
                   + (f" {fl['migrated_by_reason']}"
                      if fl["migrated_by_reason"] else "")
                   + f", {fl['shed']} shed, "
                   f"{fl['completed']} completed")
        if "latency_p50_s" in fl:
            out.append(f"  fleet latency  p50 {fl['latency_p50_s']}s  "
                       f"p90 {fl['latency_p90_s']}s  "
                       f"p99 {fl['latency_p99_s']}s")
        if fl.get("routed_by_policy"):
            out.append("  routed by policy: " + ", ".join(
                f"{k} {v}" for k, v in sorted(
                    fl["routed_by_policy"].items(),
                    key=lambda kv: -kv[1])))
        if "ttft_p50_s" in fl:
            out.append(f"  fleet TTFT     p50 {fl['ttft_p50_s']}s  "
                       f"p90 {fl['ttft_p90_s']}s  "
                       f"p99 {fl['ttft_p99_s']}s")
        if "itl_p50_s" in fl:
            out.append(f"  fleet ITL      p50 {fl['itl_p50_s']}s  "
                       f"p90 {fl['itl_p90_s']}s  "
                       f"p99 {fl['itl_p99_s']}s  (per decode segment)")
        if "handoff_stall_p90_ms" in fl:
            via = ""
            if fl.get("moves_by_transport"):
                via = " via " + ", ".join(
                    f"{k} x{v}" for k, v in sorted(
                        fl["moves_by_transport"].items()))
            out.append(f"  KV moves       {fl['handoff_blocks']} "
                       f"block(s) / {_fmt_bytes(fl['handoff_bytes'])} "
                       f"shipped, stall p90 "
                       f"{fl['handoff_stall_p90_ms']} ms{via}")
        if fl.get("wire_rejected"):
            out.append(f"  wire integrity {fl['wire_rejected']} "
                       "handoff doc(s) REJECTED (CRC/torn/version — "
                       "replay-rerouted; reasons on the timeline)")
        if "deploys" in fl or "deploy_rollbacks" in fl:
            out.append(f"  deploys        {fl.get('deploys', 0)} "
                       f"completed, {fl.get('deploy_rollbacks', 0)} "
                       "rolled back (events on the timeline)")
        if fl.get("completed_by_version"):
            out.append("  completions by weights version: " + ", ".join(
                f"{k} x{v}" for k, v in sorted(
                    fl["completed_by_version"].items())))
    if doc.get("fleet_health"):
        _render_fleet_health(out, doc["fleet_health"])
    if doc.get("workload"):
        _render_workload(out, doc["workload"])
    if doc.get("transport"):
        _render_transport(out, doc["transport"])
    if doc.get("slo"):
        _render_slo(out, doc["slo"])
    if doc.get("trace"):
        _render_trace(out, doc["trace"])
    if multi:
        for s in streams:
            sub = per_engine[s.label]
            out.append("")
            out.append(f"--- engine [{s.label}] — {s.path} ---")
            _render_engine_sections(out, sub)
    else:
        _render_engine_sections(out, doc)
    for lab, wf in waterfalls.items():
        _render_waterfalls(out, lab if multi else None, wf)
    if timeline:
        t0 = timeline[0][0]
        out.append("")
        out.append("timeline:")
        for t, src, what, lab in timeline:
            tag = f"[{lab}] " if multi else ""
            out.append(f"  {_fmt_t(t, t0)}  [{src:7s}] {tag}{what}")
    if args.postmortem:
        for s in streams:
            _render_postmortem(out, s.label if multi else None,
                               flights.get(s.label))
        for s in streams:
            if rposts.get(s.label):
                _render_router_postmortem(out,
                                          s.label if multi else None,
                                          rposts[s.label])
    if "profile" in doc:
        pr = doc["profile"]
        out.append("")
        out.append(f"profile: {pr['trace_file']}")
        out.append(f"  {pr['comm_spans']} comm / {pr['compute_spans']} "
                   f"compute span(s), overlap {pr['overlap_us']} us")
        if pr.get("scope_totals_us"):
            out.append("  per-region span totals (us):")
            for k, v in sorted(pr["scope_totals_us"].items(),
                               key=lambda kv: -kv[1]):
                out.append(f"    {k:16s} {v}")
    problems = (doc.get("problems") if multi
                else streams[0].problems) or []
    if problems:
        out.append("")
        out.append(f"schema problems ({len(problems)}):")
        for prob in problems:
            out.append(f"  {prob}")
    print("\n".join(out))
    return 0


if __name__ == "__main__":
    sys.exit(report_main())
