"""Hand-written optimizers — from inline SGD up to Adam, all functional.

The reference's entire optimizer surface is inline SGD,
``param = param - LR * grad`` with unscaled summed gradients
(``train_ffns.py:29, :114, :171-172, :258-259, :311-312``). No optimizer
state, no classes. Gradients across data-parallel ranks are reduced with
**SUM, not mean** (``train_ffns.py:165``) and the LR is left unscaled — so
multi-rank results intentionally differ from the single-device run; only
strategy-vs-strategy equivalence is asserted, mirroring the reference's
verification design (``train_ffns.py:386-391``).

Beyond the reference, this module adds *stateful* optimizers in the same
first-principles style: an ``Optimizer`` is a ``(init, update)`` pair of
pure functions over arbitrary param pytrees, with the update math written
out by hand (verified against the optax implementations in
``tests/test_optim.py`` — optax is the test oracle, never the training
path). Stateful optimizers are what make ZeRO-1 meaningful: the state is
the thing worth sharding (``parallel/zero1.py``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from . import LR


def sgd(params, grads, lr: float = LR):
    """Functional SGD over an arbitrary param pytree."""
    return jax.tree_util.tree_map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)


class Optimizer(NamedTuple):
    """A functional optimizer: ``init(params) -> state`` and
    ``update(grads, state, params, lr) -> (new_params, new_state)``.
    ``stateless=True`` marks an empty-state rule (plain SGD): the
    checkpoint layer uses it to decide whether a resume without saved
    state would change the math."""
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any, float], tuple]
    name: str = "optimizer"
    stateless: bool = False


def check_state_args(optimizer, opt_state, return_state) -> None:
    """The stateful-trainer surface contract, shared by every launcher
    that threads optimizer state: state in/out requires an optimizer."""
    if optimizer is None and (return_state or opt_state is not None):
        raise ValueError("opt_state/return_state need an optimizer")


def sgd_optimizer() -> Optimizer:
    """The reference's stateless SGD as an ``Optimizer`` (empty state), so
    every strategy that takes an optimizer degrades to exact reference
    semantics."""
    def update(grads, state, params, lr):
        return sgd(params, grads, lr), state
    return Optimizer(init=lambda params: (), update=update, name="sgd",
                     stateless=True)


def momentum(beta: float = 0.9) -> Optimizer:
    """Heavy-ball momentum: ``v = beta*v + g``, ``p = p - lr*v`` (the
    classic accumulator form, optax's default convention)."""
    def init(params):
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def update(grads, vel, params, lr):
        vel = jax.tree_util.tree_map(
            lambda v, g: beta * v + g.astype(v.dtype), vel, grads)
        params = jax.tree_util.tree_map(lambda p, v: p - lr * v,
                                        params, vel)
        return params, vel

    return Optimizer(init=init, update=update, name=f"momentum({beta})")


class AdamState(NamedTuple):
    mu: Any          # first-moment pytree, like params
    nu: Any          # second-moment pytree, like params
    count: jax.Array  # step counter for bias correction


def adam(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8) -> Optimizer:
    """Adam (Kingma & Ba) with bias correction, written out by hand:
    ``mu = b1*mu + (1-b1)*g``; ``nu = b2*nu + (1-b2)*g^2``;
    ``p -= lr * (mu/(1-b1^t)) / (sqrt(nu/(1-b2^t)) + eps)``."""
    def init(params):
        zeros = lambda p: jnp.zeros_like(p)  # noqa: E731
        return AdamState(mu=jax.tree_util.tree_map(zeros, params),
                         nu=jax.tree_util.tree_map(zeros, params),
                         count=jnp.zeros((), jnp.int32))

    def update(grads, state, params, lr):
        count = state.count + 1
        t = count.astype(jnp.float32)
        c1 = 1.0 - b1 ** t
        c2 = 1.0 - b2 ** t
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1.0 - b1) * g.astype(m.dtype),
            state.mu, grads)
        nu = jax.tree_util.tree_map(
            lambda n, g: b2 * n + (1.0 - b2) * jnp.square(g.astype(n.dtype)),
            state.nu, grads)
        params = jax.tree_util.tree_map(
            lambda p, m, n: p - lr * (m / c1) / (jnp.sqrt(n / c2) + eps),
            params, mu, nu)
        return params, AdamState(mu=mu, nu=nu, count=count)

    return Optimizer(init=init, update=update,
                     name=f"adam({b1},{b2},{eps})")


def adamw(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          weight_decay: float = 1e-2, decay_mask=None) -> Optimizer:
    """AdamW (Loshchilov & Hutter): Adam with *decoupled* weight decay —
    the decay applies directly to the params (``p -= lr * wd * p``),
    never entering the moment estimates (the difference from L2-in-loss
    that makes it "decoupled").

    ``decay_mask`` selects which leaves decay (``leaf -> bool``). The
    default is the standard LLM recipe: matmul weights and embedding
    tables decay; LayerNorm gains (initialized at 1) and biases do not —
    decaying norm gains toward 0 degrades training at scale. Because this
    framework stacks per-layer leaves with a leading layer dim (a block's
    ``ln1`` gain is ``[L, d]``, 2-D), a pure ndim test can't see gains:
    the default mask is *path-aware* — a leaf decays iff ``ndim >= 2``
    AND its field name doesn't mark it as a norm gain or bias
    (``ln*``/``bias``/``gain``/``scale``). Pass
    ``decay_mask=lambda p: True`` for uniform decay (optax's unmasked
    ``adamw``), or any custom per-leaf predicate."""
    base = adam(b1, b2, eps)

    def _default_decays(path, p) -> bool:
        entry = path[-1] if path else None
        name = str(getattr(entry, "name", getattr(entry, "key", "")))
        return (p.ndim >= 2 and not name.startswith("ln")
                and name not in ("bias", "gain", "scale"))

    def update(grads, state, params, lr):
        factor = 1.0 - lr * weight_decay
        if decay_mask is None:
            params = jax.tree_util.tree_map_with_path(
                lambda path, p: p * factor if _default_decays(path, p)
                else p, params)
        else:
            params = jax.tree_util.tree_map(
                lambda p: p * factor if decay_mask(p) else p, params)
        return base.update(grads, state, params, lr)

    return Optimizer(init=base.init, update=update,
                     name=f"adamw({b1},{b2},{eps},{weight_decay})")


def _sum_squares(grads) -> jax.Array:
    return sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
               for g in jax.tree_util.tree_leaves(grads))


def global_norm(grads) -> jax.Array:
    """L2 norm over every leaf of a gradient pytree, written out."""
    return jnp.sqrt(_sum_squares(grads))


def clipped(opt: Optimizer, max_norm: float,
            axis: str | tuple | None = None) -> Optimizer:
    """Wrap an optimizer with global-norm gradient clipping: grads are
    scaled by ``min(1, max_norm / ||g||)`` before the inner update — the
    standard LLM-training stabilizer, stateless, composing with any
    strategy that threads optimizer state.

    ``axis``: when the *update itself* runs on a gradient shard (FSDP's
    param shards, ZeRO-1's layer shards), the local leaf norm is not the
    global norm — pass the mesh axis the grads are sharded over and the
    squared norm is ``psum``-med across it before the scale is computed,
    so every shard clips by the same, true global norm. Leave ``None``
    when the update sees full gradients (single device, DDP post-psum).
    """
    if max_norm <= 0:
        raise ValueError(f"max_norm must be > 0, got {max_norm}")

    def update(grads, state, params, lr):
        sq = _sum_squares(grads)
        if axis is not None:
            sq = jax.lax.psum(sq, axis)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-16))
        grads = jax.tree_util.tree_map(
            lambda g: g * scale.astype(g.dtype), grads)
        return opt.update(grads, state, params, lr)

    return Optimizer(init=opt.init, update=update,
                     name=f"clipped({opt.name},{max_norm},{axis})",
                     stateless=opt.stateless)


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  min_lr: float = 0.0):
    """The standard LLM-training schedule, written out: linear warmup from
    0 to ``peak_lr`` over ``warmup_steps``, then cosine decay to
    ``min_lr`` at ``total_steps``. Returns ``step -> lr`` on a traced
    int step."""
    def schedule(step):
        t = step.astype(jnp.float32)
        warm = peak_lr * (t + 1.0) / max(warmup_steps, 1)
        frac = jnp.clip((t - warmup_steps) /
                        max(total_steps - warmup_steps, 1), 0.0, 1.0)
        cos = min_lr + 0.5 * (peak_lr - min_lr) * (1.0 +
                                                   jnp.cos(jnp.pi * frac))
        return jnp.where(t < warmup_steps, warm, cos)

    return schedule


def constant_with_warmup(peak_lr: float, warmup_steps: int):
    """Linear warmup to ``peak_lr``, constant after."""
    def schedule(step):
        t = step.astype(jnp.float32)
        return jnp.minimum(peak_lr, peak_lr * (t + 1.0) /
                           max(warmup_steps, 1))

    return schedule


def scheduled(opt: Optimizer, schedule) -> Optimizer:
    """Wrap an optimizer with a per-step LR schedule. The wrapper keeps
    its own step counter in the state, so it composes with any strategy
    that threads optimizer state (DDP, ZeRO-1) — the trainer's static
    ``lr`` argument is superseded by ``schedule(step)``."""
    def init(params):
        return (opt.init(params), jnp.zeros((), jnp.int32))

    def update(grads, state, params, lr):
        inner, count = state
        params, inner = opt.update(grads, inner, params, schedule(count))
        return params, (inner, count + 1)

    return Optimizer(init=init, update=update,
                     name=f"scheduled({opt.name})")


OPTIMIZERS = {
    "sgd": sgd_optimizer,
    "momentum": momentum,
    "adam": adam,
    "adamw": adamw,
}
