"""Inline SGD — the reference's entire optimizer surface.

``param = param - LR * grad`` with unscaled summed gradients
(``train_ffns.py:29, :114, :171-172, :258-259, :311-312``). No optimizer
state, no classes. Gradients across data-parallel ranks are reduced with
**SUM, not mean** (``train_ffns.py:165``) and the LR is left unscaled — so
multi-rank results intentionally differ from the single-device run; only
strategy-vs-strategy equivalence is asserted, mirroring the reference's
verification design (``train_ffns.py:386-391``).
"""

from __future__ import annotations

import jax

from . import LR


def sgd(params, grads, lr: float = LR):
    """Functional SGD over an arbitrary param pytree."""
    return jax.tree_util.tree_map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
