// Native XLA custom calls via the XLA FFI — C++ kernels that run INSIDE
// jitted XLA programs.
//
// The reference reached native compute through torch's prebuilt CUDA
// kernels; here the native path is first-party: kernels registered with
// the XLA runtime through the stable FFI ABI (headers shipped with jaxlib,
// see jax.ffi.include_dir()). Registered on the CPU platform (TPU custom
// calls are not user-extensible; on TPU the equivalent role is played by
// Pallas kernels in ops/pallas_ffn.py).
//
// Kernels:
//   dlcs_fused_sgd  — out = p - lr * g, one pass (the reference's inline
//                     SGD, train_ffns.py:171-172, as a fused native op)
//   dlcs_relu_bwd   — out = where(x <= 0, 0, dy) (train_ffns.py:50-52)

#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error FusedSgdImpl(ffi::Buffer<ffi::F32> p,
                               ffi::Buffer<ffi::F32> g,
                               ffi::Buffer<ffi::F32> lr,
                               ffi::ResultBuffer<ffi::F32> out) {
  const float* pp = p.typed_data();
  const float* gg = g.typed_data();
  const float lrv = lr.typed_data()[0];
  float* oo = out->typed_data();
  const int64_t n = static_cast<int64_t>(p.element_count());
  for (int64_t i = 0; i < n; ++i) oo[i] = pp[i] - lrv * gg[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(DlcsFusedSgd, FusedSgdImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()   // p
                                  .Arg<ffi::Buffer<ffi::F32>>()   // g
                                  .Arg<ffi::Buffer<ffi::F32>>()   // lr (scalar)
                                  .Ret<ffi::Buffer<ffi::F32>>()); // out

static ffi::Error ReluBwdImpl(ffi::Buffer<ffi::F32> dy,
                              ffi::Buffer<ffi::F32> x,
                              ffi::ResultBuffer<ffi::F32> out) {
  const float* d = dy.typed_data();
  const float* xx = x.typed_data();
  float* oo = out->typed_data();
  const int64_t n = static_cast<int64_t>(dy.element_count());
  for (int64_t i = 0; i < n; ++i) oo[i] = xx[i] <= 0.0f ? 0.0f : d[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(DlcsReluBwd, ReluBwdImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()   // dy
                                  .Arg<ffi::Buffer<ffi::F32>>()   // x
                                  .Ret<ffi::Buffer<ffi::F32>>()); // out
