// Native watchdog — training-loop hang detection.
//
// The reference has no failure detection at all: no try/except around
// workers, no timeout on join (train_ffns.py:190-191, SURVEY.md section 5).
// This component supplies the missing piece for the TPU runtime: a monitor
// thread armed with a deadline that the training loop must "kick" every
// step. If the deadline lapses (a wedged collective, a hung device, a
// deadlocked host thread), the watchdog latches `expired` — the Python
// supervisor (runtime/failure.py) polls it and triggers checkpoint-based
// recovery. Latching (rather than aborting the process) keeps policy in
// Python; the native layer only does the timing, immune to a GIL held by
// the hung code.
//
// Implementation note: raw pthreads + CLOCK_MONOTONIC rather than
// std::thread / std::condition_variable — this library is dlopen'd into
// processes that also load jaxlib's wheels (which bundle their own C++
// runtime), and the pthread surface lives in libc with a stable ABI, so
// there is no C++-runtime coupling to worry about.
//
// C ABI only; bound via ctypes (runtime/native.py).

#include <pthread.h>
#include <time.h>

#include <cstdint>

namespace {

int64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

struct Watchdog {
  int64_t timeout_ms = 0;
  int64_t deadline_ms = 0;
  int expired = 0;  // guarded by mu; latched until the next kick
  int stop = 0;
  pthread_mutex_t mu;
  pthread_cond_t cv;  // initialized with a CLOCK_MONOTONIC condattr
  pthread_t th;
};

void* monitor(void* arg) {
  auto* W = static_cast<Watchdog*>(arg);
  pthread_mutex_lock(&W->mu);
  while (!W->stop) {
    if (now_ms() >= W->deadline_ms) {
      W->expired = 1;
      pthread_cond_wait(&W->cv, &W->mu);  // sleep until kick or destroy
    } else {
      timespec ts;
      ts.tv_sec = W->deadline_ms / 1000;
      ts.tv_nsec = (W->deadline_ms % 1000) * 1000000;
      pthread_cond_timedwait(&W->cv, &W->mu, &ts);
    }
  }
  pthread_mutex_unlock(&W->mu);
  return nullptr;
}

}  // namespace

extern "C" {

void* dlcs_watchdog_create(int timeout_ms) {
  auto* W = new Watchdog;
  W->timeout_ms = timeout_ms;
  W->deadline_ms = now_ms() + timeout_ms;
  pthread_mutex_init(&W->mu, nullptr);
  pthread_condattr_t attr;
  pthread_condattr_init(&attr);
  pthread_condattr_setclock(&attr, CLOCK_MONOTONIC);
  pthread_cond_init(&W->cv, &attr);
  pthread_condattr_destroy(&attr);
  if (pthread_create(&W->th, nullptr, monitor, W) != 0) {
    pthread_cond_destroy(&W->cv);
    pthread_mutex_destroy(&W->mu);
    delete W;
    return nullptr;
  }
  return W;
}

// Reset the deadline (call once per training step / heartbeat interval).
// Also clears a latched expiry so the watchdog can re-arm after recovery.
void dlcs_watchdog_kick(void* h) {
  auto* W = static_cast<Watchdog*>(h);
  pthread_mutex_lock(&W->mu);
  W->deadline_ms = now_ms() + W->timeout_ms;
  W->expired = 0;
  pthread_cond_signal(&W->cv);
  pthread_mutex_unlock(&W->mu);
}

// 1 if the deadline lapsed without a kick since arming.
int dlcs_watchdog_expired(void* h) {
  auto* W = static_cast<Watchdog*>(h);
  pthread_mutex_lock(&W->mu);
  int e = W->expired;
  pthread_mutex_unlock(&W->mu);
  return e;
}

void dlcs_watchdog_destroy(void* h) {
  auto* W = static_cast<Watchdog*>(h);
  pthread_mutex_lock(&W->mu);
  W->stop = 1;
  pthread_cond_signal(&W->cv);
  pthread_mutex_unlock(&W->mu);
  pthread_join(W->th, nullptr);
  pthread_cond_destroy(&W->cv);
  pthread_mutex_destroy(&W->mu);
  delete W;
}

}  // extern "C"
