// Host ring-collective engine — the framework's native communication core.
//
// The reference consumed its collectives from NCCL through torch.distributed
// (train_ffns.py:20,125; test_nccl.py:2). On TPU the device-side collectives
// are XLA HLOs over ICI (parallel/collectives.py); THIS engine is the
// native host-side counterpart: real ring algorithms (reduce-scatter +
// all-gather phases, N ranks as threads over shared memory) used as
//   (a) an independent native oracle for the XLA collectives in tests —
//       the CPU-oracle pattern of test_nccl.py with the oracle itself
//       implemented from first principles, and
//   (b) the host-side reduction fallback for runtime components that
//       operate outside any XLA program (e.g. cross-process data-layer
//       reductions).
//
// C ABI only; bound from Python via ctypes (runtime/native.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// Reusable N-thread barrier (generation-counted).
class Barrier {
 public:
  explicit Barrier(int n) : n_(n), waiting_(0), generation_(0) {}
  void wait() {
    std::unique_lock<std::mutex> lk(mu_);
    int gen = generation_;
    if (++waiting_ == n_) {
      waiting_ = 0;
      ++generation_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return gen != generation_; });
    }
  }

 private:
  int n_;
  int waiting_;
  int generation_;
  std::mutex mu_;
  std::condition_variable cv_;
};

inline int64_t chunk_begin(int64_t count, int n, int c) {
  int64_t base = count / n, rem = count % n;
  return c * base + (c < rem ? c : rem);
}
inline int64_t chunk_end(int64_t count, int n, int c) {
  return chunk_begin(count, n, c + 1);
}
inline int mod(int a, int n) { return ((a % n) + n) % n; }

// Ring all-reduce over shared memory: the classic two phases.
// Phase 1 (reduce-scatter): n-1 steps; at step s, rank r accumulates its
// predecessor's chunk mod(r-1-s, n) into its own copy. Afterwards rank r
// holds the fully-reduced chunk mod(r+1, n).
// Phase 2 (all-gather): n-1 steps; at step s, rank r copies chunk
// mod(r-s, n) from its predecessor. Barriers order the steps; reads and
// writes of a step touch disjoint chunks.
void ring_all_reduce(float** bufs, int n, int64_t count) {
  if (n == 1) return;
  Barrier bar(n);
  std::vector<std::thread> ts;
  ts.reserve(n);
  for (int r = 0; r < n; ++r) {
    ts.emplace_back([&, r] {
      int pred = mod(r - 1, n);
      for (int s = 0; s < n - 1; ++s) {  // reduce-scatter phase
        int c = mod(r - 1 - s, n);
        int64_t b = chunk_begin(count, n, c), e = chunk_end(count, n, c);
        for (int64_t i = b; i < e; ++i) bufs[r][i] += bufs[pred][i];
        bar.wait();
      }
      for (int s = 0; s < n - 1; ++s) {  // all-gather phase
        int c = mod(r - s, n);
        int64_t b = chunk_begin(count, n, c), e = chunk_end(count, n, c);
        std::memcpy(bufs[r] + b, bufs[pred] + b, (e - b) * sizeof(float));
        bar.wait();
      }
    });
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// In-place SUM all-reduce across n_ranks buffers of `count` floats.
void dlcs_all_reduce_sum_f32(float** bufs, int n_ranks, int64_t count) {
  ring_all_reduce(bufs, n_ranks, count);
}

// Each rank contributes `shard_count` floats; every output buffer receives
// the rank-order concatenation (n_ranks * shard_count floats).
void dlcs_all_gather_f32(const float** shards, float** outs, int n_ranks,
                         int64_t shard_count) {
  Barrier bar(n_ranks);
  std::vector<std::thread> ts;
  ts.reserve(n_ranks);
  for (int r = 0; r < n_ranks; ++r) {
    ts.emplace_back([&, r] {
      // seed own shard at its slot, then ring-forward predecessor slots
      std::memcpy(outs[r] + r * shard_count, shards[r],
                  shard_count * sizeof(float));
      bar.wait();
      int pred = mod(r - 1, n_ranks);
      for (int s = 0; s < n_ranks - 1; ++s) {
        int c = mod(r - 1 - s, n_ranks);
        std::memcpy(outs[r] + c * shard_count, outs[pred] + c * shard_count,
                    shard_count * sizeof(float));
        bar.wait();
      }
    });
  }
  for (auto& t : ts) t.join();
}

// Each rank contributes n_ranks*shard_count floats; rank r's output gets
// the SUM over ranks of shard r. Implemented as a reduce-scatter ring over
// an internal scratch copy (inputs are not modified).
void dlcs_reduce_scatter_sum_f32(const float** ins, float** outs, int n_ranks,
                                 int64_t shard_count) {
  int n = n_ranks;
  int64_t count = static_cast<int64_t>(n) * shard_count;
  std::vector<std::vector<float>> scratch(n);
  std::vector<float*> bufs(n);
  for (int r = 0; r < n; ++r) {
    scratch[r].assign(ins[r], ins[r] + count);
    bufs[r] = scratch[r].data();
  }
  if (n > 1) {
    Barrier bar(n);
    std::vector<std::thread> ts;
    ts.reserve(n);
    for (int r = 0; r < n; ++r) {
      ts.emplace_back([&, r] {
        int pred = mod(r - 1, n);
        for (int s = 0; s < n - 1; ++s) {
          int c = mod(r - 1 - s, n);
          for (int64_t i = c * shard_count; i < (c + 1) * shard_count; ++i)
            bufs[r][i] += bufs[pred][i];
          bar.wait();
        }
      });
    }
    for (auto& t : ts) t.join();
  }
  // after the ring, chunk c is fully reduced on rank mod(c+1... owner of
  // chunk c is the rank r with mod(r+1, n) == c, i.e. r = mod(c-1, n)
  for (int c = 0; c < n; ++c) {
    int owner = mod(c - 1, n);
    std::memcpy(outs[c], bufs[owner] + c * shard_count,
                shard_count * sizeof(float));
  }
}

// ppermute on a ring: out[mod(r+shift, n)] = ins[r].
void dlcs_ring_permute_f32(const float** ins, float** outs, int n_ranks,
                           int64_t count, int shift) {
  for (int r = 0; r < n_ranks; ++r) {
    int dst = mod(r + shift, n_ranks);
    std::memcpy(outs[dst], ins[r], count * sizeof(float));
  }
}

}  // extern "C"
