// Native seeded data loader with background prefetch.
//
// The reference's data layer is a host-side Python generator re-seeding a
// torch.Generator per step (train_ffns.py:144-151). This is its native
// counterpart: a C++ thread pool that materializes (x, dloss_dx) batches
// from integer seeds ahead of consumption, so host data production overlaps
// device compute — the role CUDA streams played for the reference's
// host->device copies. Determinism contract matches the reference's
// seeds-as-dataset design: a batch is a pure function of (seed, index),
// via splitmix64 counters + Box-Muller normals.
//
// C ABI only; bound via ctypes (runtime/native.py). Numbers intentionally
// differ from jax.random (different PRNG); tests pin determinism, moments,
// and cross-thread reproducibility rather than bit-equality with JAX.

#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// uniform in (0,1]: avoid 0 for the log in Box-Muller
inline double u01(uint64_t bits) {
  return (static_cast<double>(bits >> 11) + 1.0) * (1.0 / 9007199254740993.0);
}

// normal(0,1) as a pure function of (seed, stream, i)
inline float counter_normal(uint64_t seed, uint64_t stream, uint64_t i) {
  uint64_t base = splitmix64(seed * 0x100000001b3ULL + stream);
  uint64_t a = splitmix64(base + 2 * i);
  uint64_t b = splitmix64(base + 2 * i + 1);
  double u1 = u01(a), u2 = u01(b);
  return static_cast<float>(std::sqrt(-2.0 * std::log(u1)) *
                            std::cos(2.0 * M_PI * u2));
}

struct Batch {
  int64_t seed;
  std::vector<float> x;
  std::vector<float> dloss_dx;
};

struct Loader {
  int64_t batch, d;
  float dloss_coef;
  std::vector<std::thread> workers;
  std::deque<int64_t> pending;               // seeds to produce
  std::map<int64_t, Batch> ready;            // produced, keyed by order id
  std::deque<int64_t> order;                 // consumption order (order ids)
  std::map<int64_t, int64_t> order_of_seed;  // order id -> seed
  int64_t next_submit = 0, next_pop = 0;
  bool shutdown = false;
  std::mutex mu;
  std::condition_variable cv_work, cv_ready;

  void fill(Batch& out, int64_t seed) const {
    int64_t n = batch * d;
    out.seed = seed;
    out.x.resize(n);
    out.dloss_dx.resize(n);
    for (int64_t i = 0; i < n; ++i)
      out.x[i] = counter_normal(static_cast<uint64_t>(seed), 1, i);
    for (int64_t i = 0; i < n; ++i)
      out.dloss_dx[i] =
          dloss_coef * counter_normal(static_cast<uint64_t>(seed), 2, i);
  }

  void worker() {
    for (;;) {
      int64_t order_id, seed;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return shutdown || !pending.empty(); });
        if (shutdown && pending.empty()) return;
        order_id = pending.front();
        pending.pop_front();
        seed = order_of_seed[order_id];
      }
      Batch b;
      fill(b, seed);
      {
        std::unique_lock<std::mutex> lk(mu);
        ready.emplace(order_id, std::move(b));
        cv_ready.notify_all();
      }
    }
  }
};

}  // namespace

extern "C" {

void* dlcs_loader_create(int64_t batch, int64_t d, int n_threads,
                         float dloss_coef) {
  auto* L = new Loader;
  L->batch = batch;
  L->d = d;
  L->dloss_coef = dloss_coef;
  if (n_threads < 1) n_threads = 1;
  for (int i = 0; i < n_threads; ++i)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

void dlcs_loader_submit(void* h, int64_t seed) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  int64_t id = L->next_submit++;
  L->order_of_seed[id] = seed;
  L->pending.push_back(id);
  L->cv_work.notify_one();
}

// Blocking pop in submission order; fills caller buffers of size batch*d.
// Returns the seed of the batch produced, or -1 if called more times than
// batches were submitted (fail-fast instead of blocking forever).
int64_t dlcs_loader_next(void* h, float* x_out, float* dl_out) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  if (L->next_pop >= L->next_submit) return -1;
  int64_t id = L->next_pop++;
  L->cv_ready.wait(lk, [&] { return L->ready.count(id) > 0; });
  Batch b = std::move(L->ready[id]);
  L->ready.erase(id);
  L->order_of_seed.erase(id);
  lk.unlock();
  std::memcpy(x_out, b.x.data(), b.x.size() * sizeof(float));
  std::memcpy(dl_out, b.dloss_dx.data(), b.dloss_dx.size() * sizeof(float));
  return b.seed;
}

void dlcs_loader_destroy(void* h) {
  auto* L = static_cast<Loader*>(h);
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->shutdown = true;
    L->cv_work.notify_all();
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
