// Native async checkpoint writer — file I/O off the training critical path.
//
// The reference has no serialization at all (SURVEY.md section 5); the
// framework's checkpoint subsystem (checkpoint.py) is synchronous Python
// I/O. For large models the write stalls training for the full
// params-to-disk time. This component moves the write to a native worker
// pool: `submit` memcpy's the leaf buffers (so the caller may donate or
// mutate its arrays immediately) and returns; a worker thread writes each
// leaf to `<tmp_dir>/<name>.raw` and atomically `rename`s the staged
// directory to `final_dir` — the same publish protocol as the Python
// backends, so `latest_step` never observes a torn checkpoint. Training
// on segment N+1 overlaps the disk write of segment N.
//
// Same ABI stance as the rest of the native runtime (see watchdog.cpp):
// raw pthreads + POSIX I/O, C ABI only, no C++ runtime coupling beyond
// operator new; bound via ctypes (runtime/native.py).

#include <fcntl.h>
#include <pthread.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Job {
  std::string tmp_dir;
  std::string final_dir;
  std::vector<std::string> names;
  std::vector<std::vector<char>> bufs;
  Job* next = nullptr;
};

struct Writer {
  pthread_mutex_t mu;
  pthread_cond_t cv_submit;  // signals workers: job available / stopping
  pthread_cond_t cv_done;    // signals waiters: pending count dropped
  Job* head = nullptr;       // FIFO queue
  Job* tail = nullptr;
  int pending = 0;  // queued + in-flight jobs
  int errors = 0;   // failed jobs (tmp dir left behind for debugging)
  int stop = 0;
  std::vector<pthread_t> threads;
};

// write the whole buffer + fsync, retrying short writes; 0 on success
int write_file(const std::string& path, const char* data, size_t size) {
  int fd = open(path.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  size_t off = 0;
  while (off < size) {
    ssize_t w = write(fd, data + off, size - off);
    if (w < 0) {
      if (errno == EINTR) continue;
      close(fd);
      return -1;
    }
    off += static_cast<size_t>(w);
  }
  // data must be on disk BEFORE the publish rename: a journaled rename
  // with unflushed pages would survive a crash as a published-but-torn
  // step — exactly what the protocol exists to rule out
  if (fsync(fd) != 0) {
    close(fd);
    return -1;
  }
  return close(fd);
}

int fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (fd < 0) return -1;
  int rc = fsync(fd);
  close(fd);
  return rc;
}

int run_job(Job* j) {
  // the checkpoint layer may pre-create the tmp dir (it stages meta.json
  // there before submitting the arrays) — EEXIST is expected
  if (mkdir(j->tmp_dir.c_str(), 0755) != 0 && errno != EEXIST) return -1;
  for (size_t i = 0; i < j->names.size(); ++i) {
    std::string path = j->tmp_dir + "/" + j->names[i] + ".raw";
    if (write_file(path, j->bufs[i].data(), j->bufs[i].size()) != 0)
      return -1;
  }
  if (fsync_dir(j->tmp_dir) != 0) return -1;  // dir entries durable
  // atomic publish — after this, latest_step sees the complete step
  if (rename(j->tmp_dir.c_str(), j->final_dir.c_str()) != 0) return -1;
  // make the rename itself durable in the parent directory
  size_t slash = j->final_dir.find_last_of('/');
  std::string parent = slash == std::string::npos
                           ? std::string(".")
                           : j->final_dir.substr(0, slash);
  return fsync_dir(parent);
}

void* worker(void* arg) {
  auto* W = static_cast<Writer*>(arg);
  pthread_mutex_lock(&W->mu);
  for (;;) {
    while (W->head == nullptr && !W->stop)
      pthread_cond_wait(&W->cv_submit, &W->mu);
    if (W->head == nullptr && W->stop) break;
    Job* j = W->head;
    W->head = j->next;
    if (W->head == nullptr) W->tail = nullptr;
    pthread_mutex_unlock(&W->mu);

    int rc = run_job(j);

    pthread_mutex_lock(&W->mu);
    if (rc != 0) W->errors++;
    W->pending--;
    pthread_cond_broadcast(&W->cv_done);
    delete j;
  }
  pthread_mutex_unlock(&W->mu);
  return nullptr;
}

}  // namespace

extern "C" {

void* dlcs_ckpt_writer_create(int n_threads) {
  auto* W = new Writer;
  pthread_mutex_init(&W->mu, nullptr);
  pthread_cond_init(&W->cv_submit, nullptr);
  pthread_cond_init(&W->cv_done, nullptr);
  if (n_threads < 1) n_threads = 1;
  W->threads.resize(n_threads);
  for (int i = 0; i < n_threads; ++i)
    pthread_create(&W->threads[i], nullptr, worker, W);
  return W;
}

// Copies every buffer before returning: the caller's arrays are free the
// moment this returns (donation-safe).
void dlcs_ckpt_writer_submit(void* w, const char* tmp_dir,
                             const char* final_dir, const char** names,
                             const void** ptrs, const int64_t* sizes,
                             int n) {
  auto* W = static_cast<Writer*>(w);
  auto* j = new Job;
  j->tmp_dir = tmp_dir;
  j->final_dir = final_dir;
  j->names.reserve(n);
  j->bufs.reserve(n);
  for (int i = 0; i < n; ++i) {
    j->names.emplace_back(names[i]);
    j->bufs.emplace_back(static_cast<const char*>(ptrs[i]),
                         static_cast<const char*>(ptrs[i]) + sizes[i]);
  }
  pthread_mutex_lock(&W->mu);
  if (W->tail) W->tail->next = j; else W->head = j;
  W->tail = j;
  W->pending++;
  pthread_cond_signal(&W->cv_submit);
  pthread_mutex_unlock(&W->mu);
}

int dlcs_ckpt_writer_pending(void* w) {
  auto* W = static_cast<Writer*>(w);
  pthread_mutex_lock(&W->mu);
  int p = W->pending;
  pthread_mutex_unlock(&W->mu);
  return p;
}

// Block until every submitted job has been published (or failed).
void dlcs_ckpt_writer_wait(void* w) {
  auto* W = static_cast<Writer*>(w);
  pthread_mutex_lock(&W->mu);
  while (W->pending > 0) pthread_cond_wait(&W->cv_done, &W->mu);
  pthread_mutex_unlock(&W->mu);
}

int dlcs_ckpt_writer_errors(void* w) {
  auto* W = static_cast<Writer*>(w);
  pthread_mutex_lock(&W->mu);
  int e = W->errors;
  pthread_mutex_unlock(&W->mu);
  return e;
}

void dlcs_ckpt_writer_destroy(void* w) {
  auto* W = static_cast<Writer*>(w);
  pthread_mutex_lock(&W->mu);
  while (W->pending > 0) pthread_cond_wait(&W->cv_done, &W->mu);
  W->stop = 1;
  pthread_cond_broadcast(&W->cv_submit);
  pthread_mutex_unlock(&W->mu);
  for (pthread_t t : W->threads) pthread_join(t, nullptr);
  pthread_mutex_destroy(&W->mu);
  pthread_cond_destroy(&W->cv_submit);
  pthread_cond_destroy(&W->cv_done);
  delete W;
}

}  // extern "C"
