// Native TCP rendezvous + barrier — the process-group bootstrap.
//
// The reference rendezvous is MASTER_ADDR/MASTER_PORT + NCCL process-group
// init (train_ffns.py:121-127), and its host-side sync experiment is
// multiprocessing.Barrier (test_mp_barrier_gpus.py:32-34). This is the
// native counterpart used by the framework's multi-host runtime: rank 0
// listens, peers dial in, everyone learns (rank, world_size), and barrier()
// is a coordinator round-trip. jax.distributed.initialize plays this role
// for the XLA runtime itself (runtime/init.py); this engine covers
// host-side coordination outside XLA (e.g. multi-process tests, launcher
// handshakes) without any torch/NCCL dependency.
//
// C ABI only; bound via ctypes (runtime/native.py).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Rendezvous {
  int world_size = 0;
  int rank = -1;
  int listen_fd = -1;               // coordinator only
  std::vector<int> peer_fds;        // coordinator: world_size-1 peers
  int coord_fd = -1;                // non-coordinator: socket to rank 0

  ~Rendezvous() {                   // every delete path closes its fds
    for (int fd : peer_fds) ::close(fd);
    if (coord_fd >= 0) ::close(coord_fd);
    if (listen_fd >= 0) ::close(listen_fd);
  }
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// recv with a wall-clock deadline; returns 0 ok, 1 socket error, 2 timeout.
int recv_all_deadline(int fd, void* buf, size_t n,
                      std::chrono::steady_clock::time_point deadline) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left <= 0) return 2;
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(left));
    if (pr == 0) return 2;
    if (pr < 0) {
      if (errno == EINTR) continue;  // signal (SIGCHLD etc.), not a failure
      return 1;
    }
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return 1;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Coordinator (rank 0): bind+listen on addr:port, accept world_size-1
// peers, assign ranks by arrival order. Returns handle or nullptr.
void* dlcs_rdzv_coordinator(const char* addr, int port, int world_size) {
  auto* R = new Rendezvous;
  R->world_size = world_size;
  R->rank = 0;
  R->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(R->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  ::inet_pton(AF_INET, addr, &sa.sin_addr);
  if (::bind(R->listen_fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
      ::listen(R->listen_fd, world_size) != 0) {
    delete R;  // destructor closes listen_fd
    return nullptr;
  }
  for (int i = 1; i < world_size; ++i) {
    int fd = ::accept(R->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      delete R;
      return nullptr;
    }
    int32_t hdr[2] = {i, world_size};  // assigned rank, world size
    if (!send_all(fd, hdr, sizeof(hdr))) {
      delete R;
      return nullptr;
    }
    R->peer_fds.push_back(fd);
  }
  return R;
}

// Peer: dial the coordinator, learn the assigned rank. Returns handle.
void* dlcs_rdzv_join(const char* addr, int port) {
  auto* R = new Rendezvous;
  R->coord_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  ::inet_pton(AF_INET, addr, &sa.sin_addr);
  // retry while the coordinator comes up
  for (int attempt = 0; attempt < 200; ++attempt) {
    if (::connect(R->coord_fd, reinterpret_cast<sockaddr*>(&sa),
                  sizeof(sa)) == 0)
      break;
    ::usleep(50 * 1000);
    ::close(R->coord_fd);
    R->coord_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  }
  int32_t hdr[2];
  if (!recv_all(R->coord_fd, hdr, sizeof(hdr))) {
    delete R;
    return nullptr;
  }
  R->rank = hdr[0];
  R->world_size = hdr[1];
  return R;
}

int dlcs_rdzv_rank(void* h) { return static_cast<Rendezvous*>(h)->rank; }
int dlcs_rdzv_world(void* h) {
  return static_cast<Rendezvous*>(h)->world_size;
}

// Barrier: peers send a token to the coordinator; once all arrived, the
// coordinator releases everyone. Returns 0 on success.
int dlcs_rdzv_barrier(void* h) {
  auto* R = static_cast<Rendezvous*>(h);
  char tok = 1;
  if (R->rank == 0) {
    for (int fd : R->peer_fds)
      if (!recv_all(fd, &tok, 1)) return 1;
    for (int fd : R->peer_fds)
      if (!send_all(fd, &tok, 1)) return 1;
    return 0;
  }
  if (!send_all(R->coord_fd, &tok, 1)) return 1;
  if (!recv_all(R->coord_fd, &tok, 1)) return 1;
  return 0;
}

// Barrier with failure detection: like dlcs_rdzv_barrier, but any peer that
// fails to arrive within timeout_ms is detected instead of hanging forever
// (the reference's join() has no timeout, train_ffns.py:190-191).
// Returns 0 ok, 1 socket error (peer died), 2 timeout (peer wedged).
// After a nonzero return the handle is desynchronized (tokens may remain
// buffered on some sockets) and must not be reused for further barriers —
// detection hands off to recovery: tear the group down and re-rendezvous.
int dlcs_rdzv_barrier_timeout(void* h, int timeout_ms) {
  auto* R = static_cast<Rendezvous*>(h);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  char tok = 1;
  if (R->rank == 0) {
    for (int fd : R->peer_fds) {
      int rc = recv_all_deadline(fd, &tok, 1, deadline);
      if (rc != 0) return rc;
    }
    for (int fd : R->peer_fds)
      if (!send_all(fd, &tok, 1)) return 1;
    return 0;
  }
  if (!send_all(R->coord_fd, &tok, 1)) return 1;
  return recv_all_deadline(R->coord_fd, &tok, 1, deadline);
}

void dlcs_rdzv_destroy(void* h) { delete static_cast<Rendezvous*>(h); }

}  // extern "C"
