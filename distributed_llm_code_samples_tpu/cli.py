"""CLI driver — flag-for-flag parity with the reference's entrypoint.

Reference surface (``train_ffns.py:342-391``): seven flags, a method
dispatch table, per-method wall-clock timing, param-count/GB report,
before/after 5x5 param corners, and a soft cross-strategy ``allclose``
verification. Extensions beyond the reference: ``--method 5`` (hybrid
DDP x TP), mesh-shape flags for it (BASELINE config 4), ``--dtype``,
``--scan``, ``--strict`` (make verification hard-failing), and
``--fake_devices`` (run the multi-device methods on a virtual CPU mesh,
replacing the reference's hard multi-GPU dependency).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native distributed FFN-stack training "
                    "(reference-parity CLI, train_ffns.py:342-351)")
    # the reference's seven flags, same short names and defaults (:344-350)
    p.add_argument("-s", "--num_steps", type=int, default=1)
    p.add_argument("-bs", "--batch_size", type=int, default=8)
    p.add_argument("-n", "--seq_len", type=int, default=1024)
    p.add_argument("-l", "--layers", type=int, default=1)
    p.add_argument("-d", "--model_size", type=int, default=4)
    p.add_argument("-m", "--method", type=int, default=0,
                   choices=range(14),
                   help="0=all(1-4), 1=single, 2=DDP, 3=FSDP, 4=TP, "
                        "5=hybrid DDP x TP, 6=pipeline (ppermute send/recv), "
                        "7=MoE expert parallelism (all_to_all), "
                        "8=transformer blocks (Megatron TP; --heads), "
                        "9=all(1-8,10-13) with every strategy "
                        "cross-verified against its oracle, 10=MoE "
                        "transformer (GShard: data-parallel attention + "
                        "expert-parallel FFN), 11=language model on the "
                        "real cross-entropy objective (vocab-parallel "
                        "Megatron TP; --vocab --heads), 12=MoE language "
                        "model (GShard blocks + real loss + router aux; "
                        "--experts --vocab --heads), 13=long-context LM "
                        "(sequence dim sharded over the seq axis: ring "
                        "attention or Ulysses via --seq_impl; "
                        "--attn flash fuses the per-hop block compute)")
    p.add_argument("-r", "--random_seed", type=int, default=0,
                   help="!=0 makes runs reproducible (train_ffns.py:350)")
    # TPU-build extensions
    p.add_argument("--dp", type=int, default=0,
                   help="data-axis size for --method 5 (0 = devices//tp)")
    p.add_argument("--tp", type=int, default=2,
                   help="model-axis size for --method 5 and 8")
    p.add_argument("--microbatches", type=int, default=0,
                   help="pipeline microbatches for --method 6 (0 = n_stages)")
    p.add_argument("--pp_schedule",
                   choices=["gpipe", "1f1b", "interleaved"],
                   default="gpipe",
                   help="pipeline schedule for --method 6: gpipe (two "
                        "wavefronts, stash of M microbatches), 1f1b "
                        "(f/b interleave, stash bounded by stage depth), "
                        "or interleaved (Megatron virtual stages: "
                        "--pp_chunks non-contiguous layer chunks per "
                        "device, bubble cut by 1/chunks)")
    p.add_argument("--pp_chunks", type=int, default=0,
                   help="virtual-stage chunks per device for "
                        "--pp_schedule interleaved (0 = 2; stages x "
                        "chunks must divide --layers)")
    p.add_argument("--pp_family", choices=["ffn", "transformer", "lm"],
                   default="ffn",
                   help="model family for --method 6: the reference's FFN "
                        "stack, pre-LN transformer blocks, or the full "
                        "LM (embed/head staged, real loss; --vocab) "
                        "(--heads; microbatches split the batch dim)")
    p.add_argument("--experts", type=int, default=8,
                   help="expert count for --method 7/10/12 (MoE)")
    p.add_argument("--heads", type=int, default=4,
                   help="attention heads for --method 8/10/11/12 and "
                        "--method 6 with --pp_family transformer/lm")
    p.add_argument("--vocab", type=int, default=256,
                   help="vocabulary size for --method 11/12 and "
                        "--method 6 with --pp_family lm (method 11 needs "
                        "it divisible by the model-axis size)")
    p.add_argument("--kv_heads", type=int, default=0,
                   help="with --method 11, 9, or 6 + --pp_family lm: "
                        "grouped-query attention with this many KV heads "
                        "(0 = full MHA; wk/wv and the KV cache shrink by "
                        "heads/kv_heads; must divide --heads and the "
                        "model-axis size must divide it)")
    p.add_argument("--attn", choices=["oracle", "rope", "flash"],
                   default="oracle",
                   help="attention implementation for the transformer/LM "
                        "methods (8, 11, and 6 with --pp_family "
                        "transformer/lm): the quadratic hand-VJP oracle, "
                        "rotary positions, or the fused Pallas flash "
                        "kernels (interpret mode off-TPU)")
    p.add_argument("--head", choices=["oracle", "fused"],
                   default="oracle",
                   help="LM head+loss implementation for --method "
                        "11/12/13: the materialized-logits hand-VJP "
                        "xent, or the fused Pallas head "
                        "(ops/pallas_xent.py - no [N, V] logits in HBM; "
                        "vocab-parallel merge under method 11)")
    p.add_argument("--lr", type=float, default=None,
                   help="override LR (default 1e-5, train_ffns.py:29)")
    p.add_argument("--optimizer",
                   choices=["sgd", "momentum", "adam", "adamw"],
                   default="sgd",
                   help="update rule for --method 2 (DDP) or 3 (FSDP, "
                        "state sharded with the params): sgd is the "
                        "reference's stateless inline update; momentum/"
                        "adam/adamw carry hand-written optimizer state")
    p.add_argument("--clip_norm", type=float, default=0.0,
                   help="with --method 2 or 3: clip gradients to this "
                        "global L2 norm before the optimizer update "
                        "(0 = off)")
    p.add_argument("--seq_impl", choices=["ring", "ulysses"],
                   default="ring",
                   help="with --method 13: the cross-shard attention "
                        "scheme — ring (KV blocks rotating over "
                        "ppermute) or ulysses (two all_to_alls re-shard "
                        "heads<->sequence)")
    p.add_argument("--tp_sp", action="store_true",
                   help="with --method 4 or 8: Megatron sequence-parallel "
                        "TP (token-sharded activations; all_gather + "
                        "reduce_scatter instead of all_reduce)")
    p.add_argument("--comm", choices=["psum", "pallas_ring"],
                   default="psum",
                   help="with --method 2 (DDP) or 3 (FSDP): collective "
                        "transport — psum (XLA collectives, async-split "
                        "by the scheduler) or pallas_ring (the hand-"
                        "scheduled make_async_remote_copy ring kernels: "
                        "DDP grad all-reduce; FSDP param all-gathers + "
                        "grad reduce-scatters)")
    p.add_argument("--zero1", action="store_true",
                   help="with --method 2: shard the optimizer state "
                        "across the data axis (ZeRO-1; reduce_scatter + "
                        "all_gather instead of all_reduce)")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32")
    p.add_argument("--mixed", action="store_true",
                   help="bf16 mixed precision for the FFN methods "
                        "(1/2/3/4/5, incl. --zero1/--tp_sp): bf16 matmul "
                        "inputs on the MXU, f32 params/grads/accumulation; "
                        "FSDP additionally gathers its param shards in "
                        "bf16 (half the collective bytes). Distinct from "
                        "--dtype bfloat16, which stores the params "
                        "themselves in bf16")
    p.add_argument("--scan", action="store_true",
                   help="lax.scan over layers instead of unrolling")
    p.add_argument("--accum", type=int, default=1,
                   help="gradient-accumulation chunks per step for "
                        "--method 1/2 (exact: SUM semantics, ~1/accum "
                        "activation memory)")
    p.add_argument("--pallas", action="store_true",
                   help="use the fused Pallas FFN kernels for the "
                        "single-device method (interpret mode off-TPU)")
    p.add_argument("--strict", action="store_true",
                   help="make the cross-strategy verification hard-failing "
                        "(the reference only soft-asserts, :386-391)")
    p.add_argument("--fake_devices", type=int, default=0,
                   help="run on N virtual CPU devices "
                        "(xla_force_host_platform_device_count)")
    p.add_argument("--profile_dir", default=None,
                   help="profile each method's run into this directory "
                        "(Perfetto/TensorBoard trace, process 0 only — "
                        "the reference's torch_profile_rank_0 surface, "
                        "train_ffns.py:129-141, on by flag instead of by "
                        "commented-out decorator)")
    p.add_argument("--checkpoint_dir", default=None,
                   help="enable checkpoint/resume: save params + seed "
                        "schedule here (per-method subdirs); a re-run with "
                        "the same dir resumes from the latest checkpoint")
    p.add_argument("--checkpoint_backend",
                   choices=["npz", "orbax", "native"], default="npz",
                   help="checkpoint array I/O: npz (portable), orbax "
                        "(multi-host sharded), native (async C++ writer — "
                        "training overlaps the disk write)")
    p.add_argument("--checkpoint_every", type=int, default=0,
                   help="save every N steps (0 = final only); for methods "
                        "that shard the seed schedule (2, 3, 5, 7, 10) "
                        "pick N divisible by the sharding-axis size")
    p.add_argument("--no_resume", action="store_true",
                   help="ignore existing checkpoints (restart from step 0)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="run the strategy under deterministic fault load "
                        "(runtime/chaos.py): comma-separated "
                        "KIND@STEP[:ARG] entries plus optional seed=N, "
                        "KIND in {nan_grad, inf_grad, hang, kill, "
                        "corrupt_ckpt}. The run goes through the failure "
                        "supervisor (restart + verified-checkpoint "
                        "recovery); requires --checkpoint_dir and a "
                        "single --method")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="with --chaos or --spike_factor: the "
                        "supervisor's restart budget")
    p.add_argument("--guardrails", action="store_true",
                   help="compile the in-graph anomaly guardrail into the "
                        "training step (runtime/guardrails.py, methods "
                        "1/2/3/11): a non-finite update is jnp.where-"
                        "skipped inside the compiled chunk — params and "
                        "optimizer state untouched, zero restarts — and "
                        "per-chunk skip counters flow to --metrics_dir "
                        "as `anomaly` records. With --mixed (methods "
                        "2/3) adds dynamic loss scaling")
    p.add_argument("--loss_scale", type=float, default=0.0,
                   help="with --guardrails --mixed (methods 2/3): "
                        "initial dynamic loss scale (0 = auto 2^15; "
                        "grows 2x per 200 clean steps, halves on "
                        "overflow)")
    p.add_argument("--spike_factor", type=float, default=0.0,
                   help="with --checkpoint_dir: arm the loss-spike "
                        "guard — a segment whose param-update norm "
                        "exceeds this multiple of the previous "
                        "segment's raises for the supervisor's "
                        "in-process rollback rung instead of being "
                        "checkpointed (0 = off; the PaLM rewind-on-"
                        "spike practice)")
    p.add_argument("--max_rollbacks", type=int, default=2,
                   help="with --chaos or --spike_factor: budget for the "
                        "supervisor's "
                        "in-process rollback rung (rewind to the last "
                        "verified checkpoint without a restart) before "
                        "escalating to full restarts")
    p.add_argument("--metrics_dir", default=None,
                   help="write the unified telemetry stream here "
                        "(runtime/telemetry.py): one schema-versioned "
                        "JSONL record per logged step (loss/grad-norm "
                        "where the family defines them, tokens/s, step "
                        "wall-time, MFU from the hand FLOP count, "
                        "per-device HBM high-water) plus every "
                        "recovery/chaos event; fold it into a "
                        "human-readable report with the `report` "
                        "subcommand")
    p.add_argument("--log_every", type=int, default=0,
                   help="with --metrics_dir: emit one metrics record "
                        "every N steps by driving the run in N-step "
                        "programs (0 = one record for the whole run); "
                        "steps inside a chunk stay dispatch-only — "
                        "device readbacks batch at this cadence. With "
                        "--checkpoint_dir the records follow the "
                        "checkpoint segments instead (--checkpoint_every)")
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "report":
        # subcommand dispatch ahead of the flag parser: fold a
        # --metrics_dir run (+ supervise attempt log + optional profile
        # dir) into one human-readable run report
        from .report import report_main
        return report_main(argv[1:])
    if argv and argv[0] == "generate":
        # serving entrypoint: continuous-batching decode over the paged
        # KV engine (decode/engine.py), same dispatch pattern as report
        from .decode.generate_cli import generate_main
        return generate_main(argv[1:])
    if argv and argv[0] == "fleetstat":
        # live ops plane: render the router's atomic fleet status doc
        # (jax-free — the operator's terminal pays no backend import)
        from .fleetstat import fleetstat_main
        return fleetstat_main(argv[1:])
    p = build_parser()
    args = p.parse_args(argv)
    if args.mixed and args.pallas:
        # train_single would raise the same deep in the run; fail at the
        # flag surface instead (the Pallas block has its own precision
        # story inside the kernel)
        p.error("--mixed cannot combine with --pallas: the fused Pallas "
                "block carries its own residual/precision policy")

    if args.fake_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.fake_devices}").strip()

    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from . import LR
    from .data import make_seed_schedule
    from .models import (init_ffn_stack, init_moe_stack, init_transformer,
                         params_size_gb)
    from .parallel import (make_mesh, guard_multi_device, STRATEGIES,
                           DATA_AXIS, MODEL_AXIS, PIPE_AXIS, EXPERT_AXIS,
                           SEQ_AXIS)

    chaos_plan = None
    if args.chaos:
        if not args.checkpoint_dir:
            print("error: --chaos requires --checkpoint_dir (recovery "
                  "resumes from published checkpoints)", file=sys.stderr)
            return 2
        if args.method in (0, 9):
            print("error: --chaos applies to a single --method (not 0/9):"
                  " restarts would desync the cross-strategy verification",
                  file=sys.stderr)
            return 2
        from .runtime.chaos import (FaultPlan, IN_SEGMENT_KINDS,
                                    PUBLISH_KINDS)
        try:
            chaos_plan = FaultPlan.parse(args.chaos)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        train_kinds = IN_SEGMENT_KINDS + PUBLISH_KINDS
        bad_kinds = [f.kind for f in chaos_plan.faults
                     if f.kind not in train_kinds]
        if bad_kinds:
            # a decode fault would silently never fire in a training
            # run — the same parse-rejection discipline as generate's
            # validate_decode_plan, pointed the other way
            print(f"error: --chaos kind(s) {bad_kinds} are decode "
                  f"faults; the train CLI accepts {train_kinds} (use "
                  "the generate subcommand for serving faults)",
                  file=sys.stderr)
            return 2
    if args.guardrails and args.method not in (0, 1, 2, 3, 9, 11):
        # 0/9 sweeps are allowed: the per-method loop arms the guard on
        # the strategies with the surface (1/2/3/11) and the guard is
        # value-transparent on clean runs, so the cross-strategy
        # differentials keep their power
        print("error: --guardrails applies to --method 1, 2, 3, or 11 "
              "(or the 0/9 sweeps, which guard those strategies)",
              file=sys.stderr)
        return 2
    if args.guardrails and args.zero1:
        print("error: --guardrails does not support --zero1: "
              "train_ddp_zero1 has no guard surface (its re-assembled "
              "params are typed shard-varying)", file=sys.stderr)
        return 2
    if args.loss_scale < 0:
        print(f"error: --loss_scale must be >= 0 (got {args.loss_scale})",
              file=sys.stderr)
        return 2
    if args.loss_scale > 0 and not (args.guardrails and args.mixed
                                    and args.method in (0, 2, 3, 9)):
        # 0/9 sweeps allowed like --guardrails itself: the per-method
        # loop applies the scale to the methods that scale (2/3)
        print("error: --loss_scale applies with --guardrails --mixed on "
              "--method 2 or 3 (or the 0/9 sweeps; dynamic scaling "
              "protects the bf16 backward)", file=sys.stderr)
        return 2
    if args.spike_factor < 0:
        print(f"error: --spike_factor must be >= 0 "
              f"(got {args.spike_factor})", file=sys.stderr)
        return 2
    if args.spike_factor and not args.checkpoint_dir:
        print("error: --spike_factor requires --checkpoint_dir (the "
              "spike guard compares checkpoint-segment deltas and the "
              "rollback rung rewinds to a published checkpoint)",
              file=sys.stderr)
        return 2
    if args.spike_factor and not args.checkpoint_every:
        # with the default (whole-run) segmentation there is only one
        # segment: no baseline ever forms and the guard NEVER fires —
        # refusing beats silently-unarmed spike protection
        print("error: --spike_factor requires --checkpoint_every > 0: "
              "the spike guard compares successive segment deltas, and "
              "one whole-run segment has nothing to compare",
              file=sys.stderr)
        return 2
    if args.max_rollbacks < 0:
        print(f"error: --max_rollbacks must be >= 0 "
              f"(got {args.max_rollbacks})", file=sys.stderr)
        return 2
    if args.comm != "psum" and args.zero1:
        print("error: --comm pallas_ring does not apply to --zero1 "
              "(ZeRO-1's reduce_scatter/all_gather pair keeps the XLA "
              "transport); drop one of the flags", file=sys.stderr)
        return 2
    if args.comm != "psum" and args.method not in (0, 2, 3, 9):
        print("error: --comm applies to --method 2 (DDP) or 3 (FSDP)",
              file=sys.stderr)
        return 2
    if args.head != "oracle" and args.method not in (9, 11, 12, 13):
        # same pattern as the --comm guard: inapplicable flags exit 2
        # instead of silently running the oracle head (ADVICE r4)
        print("error: --head fused applies to --method 11 (LM TP), "
              "12 (MoE LM EP), 13 (sequence-parallel LM), or the "
              "--method 9 sweep (which verifies them)", file=sys.stderr)
        return 2
    if args.method == 13 and args.kv_heads:
        print("error: --method 13 (sequence-parallel LM) supports full "
              "MHA only (no --kv_heads): the ring vmaps equal q/kv "
              "heads", file=sys.stderr)
        return 2
    if args.method == 13 and args.attn == "rope":
        print("error: --attn rope is not supported by --method 13 "
              "(the ring's per-hop programs take oracle or flash)",
              file=sys.stderr)
        return 2

    if args.accum < 1:
        print(f"error: --accum must be >= 1 (got {args.accum})",
              file=sys.stderr)
        return 2
    if args.accum > 1 and args.method not in (1, 2):
        # methods 0/9 would cross-verify chunked-accumulation runs against
        # full-batch strategies at the tight tolerance (different f32
        # reduction order => spurious differential failures); other
        # methods would silently ignore the flag
        print("error: --accum applies to --method 1 or 2 only",
              file=sys.stderr)
        return 2
    if args.tp_sp and args.method not in (4, 8):
        print("error: --tp_sp applies to --method 4 or 8 only",
              file=sys.stderr)
        return 2
    if args.zero1 and args.method != 2:
        print("error: --zero1 applies to --method 2 only", file=sys.stderr)
        return 2
    if args.pp_chunks and not (args.method == 6
                               and args.pp_schedule == "interleaved"):
        print("error: --pp_chunks applies to --method 6 with "
              "--pp_schedule interleaved only", file=sys.stderr)
        return 2
    if args.pp_chunks < 0:
        print(f"error: --pp_chunks must be >= 0 (got {args.pp_chunks})",
              file=sys.stderr)
        return 2
    if args.method == 6 and args.pp_schedule == "interleaved":
        # mirror train_pp's chunking check up front: exit 2 with a clean
        # message instead of the trainer's ValueError traceback
        chunks = args.pp_chunks or 2
        stages = jax.device_count()
        if args.layers % (stages * chunks):
            print(f"error: --layers {args.layers} not divisible into "
                  f"{stages} stages x {chunks} chunks "
                  f"(--pp_schedule interleaved)", file=sys.stderr)
            return 2
    if args.pp_family != "ffn" and args.method != 6:
        # methods 0/9 verify PP against the FFN single-device oracle
        print("error: --pp_family applies to --method 6 only",
              file=sys.stderr)
        return 2
    if args.attn != "oracle" and not (
            args.method in (8, 11, 13)
            or (args.method == 6 and args.pp_family in ("transformer",
                                                        "lm"))):
        print("error: --attn applies to --method 8, 11, 13, or 6 with "
              "--pp_family transformer/lm", file=sys.stderr)
        return 2
    if args.optimizer != "sgd" and args.method not in (2, 3):
        # methods 0/9 cross-check against strategies that would still run
        # inline SGD — a guaranteed spurious differential failure
        print("error: --optimizer applies to --method 2 or 3 only",
              file=sys.stderr)
        return 2
    if args.clip_norm and args.method not in (2, 3):
        print("error: --clip_norm applies to --method 2 or 3 only",
              file=sys.stderr)
        return 2
    if args.clip_norm < 0:
        print(f"error: --clip_norm must be >= 0 (got {args.clip_norm})",
              file=sys.stderr)
        return 2
    if args.kv_heads < 0:
        print(f"error: --kv_heads must be >= 0 (got {args.kv_heads})",
              file=sys.stderr)
        return 2
    if args.kv_heads and not (
            args.method in (9, 11)
            or (args.method == 6 and args.pp_family == "lm")):
        print("error: --kv_heads applies to the LM family only "
              "(--method 11, 9, or 6 with --pp_family lm)",
              file=sys.stderr)
        return 2
    if args.kv_heads and args.heads % args.kv_heads:
        # mirrors init_lm's n_heads % n_kv_heads check — repeated here
        # only so an arg-only mistake exits 2 with a clean message
        # instead of that ValueError's traceback
        print(f"error: --heads {args.heads} not divisible by "
              f"--kv_heads {args.kv_heads}", file=sys.stderr)
        return 2
    if args.kv_heads and args.method in (9, 11):
        # the companion constraint the help text promises ("the model-axis
        # size must divide it"): mirrored up front so e.g. MQA
        # (--kv_heads 1) with the default --tp 2 exits 2 cleanly instead
        # of dying mid-run in _validate_tp's ValueError traceback
        tp_n = min(args.tp, jax.device_count())
        if tp_n > 1 and args.kv_heads % tp_n:
            print(f"error: --kv_heads {args.kv_heads} not divisible by "
                  f"the model-axis size {tp_n} (min(--tp, devices)) "
                  f"required by --method {args.method}", file=sys.stderr)
            return 2
    if (args.zero1 and args.optimizer != "sgd" and args.checkpoint_dir
            and args.checkpoint_every):
        # ZeRO-1's per-rank state shards have no opt_state surface yet;
        # segment boundaries would re-init them (train_ddp checkpoints
        # its optimizer state and has no such restriction)
        print("error: --checkpoint_every does not checkpoint ZeRO-1's "
              "sharded optimizer state; with --zero1 only whole-run "
              "checkpoints (0) are supported", file=sys.stderr)
        return 2

    lr = LR if args.lr is None else args.lr
    dtype = jnp.float32 if args.dtype == "float32" else jnp.bfloat16
    unroll = not args.scan

    # banner (train_ffns.py:353)
    print(f"ARGS:\n num_steps: {args.num_steps}\n BS: {args.batch_size}\n"
          f" N: {args.seq_len}\n D: {args.model_size}\n"
          f" FFN: {4 * args.model_size}\n")

    seeds = make_seed_schedule(args.num_steps, args.random_seed)
    key = jax.random.PRNGKey(args.random_seed)

    def family_of(method: int) -> str:
        if method == 6 and args.pp_family != "ffn":
            return args.pp_family  # transformer or lm
        return {7: "moe", 8: "transformer", 10: "moe_transformer",
                11: "lm", 12: "moe_lm", 13: "lm"}.get(method, "ffn")

    _family_params = {}

    def params_for(method: int):
        fam = family_of(method)
        if fam not in _family_params:
            if fam == "moe":
                _family_params[fam] = init_moe_stack(
                    key, args.model_size, args.layers, args.experts,
                    dtype=dtype)
            elif fam == "transformer":
                _family_params[fam] = init_transformer(
                    key, args.model_size, args.layers, dtype=dtype)
            elif fam == "moe_transformer":
                from .models import init_moe_transformer
                _family_params[fam] = init_moe_transformer(
                    key, args.model_size, args.layers, args.experts,
                    dtype=dtype)
            elif fam == "lm":
                from .models import init_lm
                _family_params[fam] = init_lm(
                    key, args.vocab, args.model_size, args.layers,
                    max_seq_len=args.seq_len, dtype=dtype,
                    n_heads=args.heads,
                    n_kv_heads=args.kv_heads or None)
            elif fam == "moe_lm":
                from .models import init_moe_lm
                _family_params[fam] = init_moe_lm(
                    key, args.vocab, args.model_size, args.layers,
                    args.experts, max_seq_len=args.seq_len, dtype=dtype)
            else:
                _family_params[fam] = init_ffn_stack(
                    key, args.model_size, args.layers, dtype=dtype)
        return _family_params[fam]

    params = params_for(args.method if args.method != 9 else 1)
    print(f"PARAMS: {params.num_params():_} "
          f"(size {params_size_gb(params)} GB)\n\n")
    corner = ((lambda w: w[0, 0]) if args.method in (7, 10, 12)
              else (lambda w: w[0]))
    print("initial layers_params[0]", params.w1[0].shape, params.w2[0].shape)
    print("initial layers_params[0]", corner(params.w1)[:5, :5],
          corner(params.w2)[:5, :5])

    n_dev = jax.device_count()
    tokens = args.batch_size * args.seq_len  # seq folded into batch (:379)

    def mesh_for(method: int):
        if method == 1:
            return None
        guard_multi_device()
        if method in (2, 3):
            return make_mesh({DATA_AXIS: n_dev})
        if method == 4:
            return make_mesh({MODEL_AXIS: n_dev})
        if method == 6:
            return make_mesh({PIPE_AXIS: n_dev})
        if method in (7, 10, 12):
            return make_mesh({EXPERT_AXIS: n_dev})
        if method in (8, 11):
            # model axis sized by --tp (like method 5): all-devices would
            # demand n_heads divisible by every possible device count
            return make_mesh({MODEL_AXIS: min(args.tp, n_dev)})
        if method == 13:
            # seq axis over the largest device count dividing seq_len
            # (and, for Ulysses, the head count it scatters)
            n = max(k for k in range(1, n_dev + 1)
                    if n_dev % k == 0 and args.seq_len % k == 0
                    and (args.seq_impl == "ring" or args.heads % k == 0))
            return make_mesh({SEQ_AXIS: n})
        return make_mesh({DATA_AXIS: hybrid_dp(), MODEL_AXIS: args.tp})

    def hybrid_dp() -> int:
        # one derivation for both the method-5 mesh and its method-9
        # verification oracle — they must never drift apart
        return args.dp or max(1, n_dev // args.tp)

    if args.log_every < 0:
        print(f"error: --log_every must be >= 0 (got {args.log_every})",
              file=sys.stderr)
        return 2
    if args.log_every and not args.metrics_dir:
        print("error: --log_every requires --metrics_dir",
              file=sys.stderr)
        return 2
    metrics = None
    peak = None
    if args.metrics_dir:
        from .runtime.telemetry import (TelemetryWriter,
                                        hand_flops_per_step,
                                        hbm_high_water, peak_flops)
        device_kind = jax.devices()[0].device_kind
        peak = peak_flops(device_kind)
        metrics = TelemetryWriter(args.metrics_dir, meta={
            "argv": list(argv),
            "num_steps": args.num_steps, "batch_size": args.batch_size,
            "seq_len": args.seq_len, "model_size": args.model_size,
            "layers": args.layers, "method": args.method,
            "tokens_per_step": tokens, "log_every": args.log_every,
            "device_kind": device_kind, "n_devices": n_dev,
            "chaos": args.chaos,
            "checkpoint_dir": args.checkpoint_dir})

    def make_probe(fam):
        """Logged-step loss/grad-norm probe: one extra jitted fwd(+bwd)
        at the LOGGING cadence only (never per step). Families without a
        scalar objective report null loss; families without a probe
        report both null."""
        import jax.numpy as jnp

        def gnorm_of(grads):
            return jnp.sqrt(sum(
                jnp.vdot(g, g).real
                for g in jax.tree_util.tree_leaves(grads)))

        if fam == "ffn":
            from .data import batch_from_seed
            from .parallel.ddp import grads_for_batch

            @jax.jit
            def probe(p, seed):
                x, dy = batch_from_seed(seed, tokens, args.model_size,
                                        p.w1.dtype)
                return None, gnorm_of(grads_for_batch(p, x, dy))

            return probe
        if fam == "lm":
            from .data import lm_batch_from_seed
            from .models.lm import lm_loss

            @jax.jit
            def probe(p, seed):
                tok, tgt = lm_batch_from_seed(seed, args.batch_size,
                                              args.seq_len, p.vocab)
                loss, grads = jax.value_and_grad(lm_loss)(
                    p, tok, tgt, args.heads)
                return loss, gnorm_of(grads)

            return probe
        return None

    if args.method == 0:
        selected = [1, 2, 3, 4]
    elif args.method == 9:
        selected = [1, 2, 3, 4, 5, 6, 7, 8, 10, 11, 12, 13]
    else:
        selected = [args.method]
    results = {}
    for m in selected:
        name, fn = STRATEGIES[m]
        params = params_for(m)
        mesh = mesh_for(m)
        kwargs = dict(lr=lr, unroll=unroll)
        if m in (1, 2, 3, 4, 5) and args.mixed:
            kwargs["mixed"] = True  # zero1/tp_sp swaps below keep it
        if m in (2, 3) and args.comm != "psum" and not args.zero1:
            kwargs["comm"] = args.comm
        if m in (1, 2) and args.accum > 1:
            kwargs["accum"] = args.accum  # train_ddp_zero1 accepts it too
        if m in (2, 3) and (args.optimizer != "sgd" or args.zero1
                            or args.clip_norm):
            from .optim import OPTIMIZERS, clipped
            opt = OPTIMIZERS[args.optimizer]()
            if args.clip_norm:
                # FSDP and ZeRO-1 run the update on gradient shards; the
                # true global norm needs a psum over the sharding axis
                sharded_update = m == 3 or args.zero1
                opt = clipped(opt, args.clip_norm,
                              axis=DATA_AXIS if sharded_update else None)
            kwargs["optimizer"] = opt
            if args.zero1:
                from .parallel import train_ddp_zero1
                name, fn = "train_ddp_zero1", train_ddp_zero1
        if m == 4 and args.tp_sp:
            from .parallel import train_tp_sp
            name, fn = "train_tp_sp", train_tp_sp
        if m == 6:
            kwargs = dict(lr=lr, schedule=args.pp_schedule)
            if args.pp_schedule == "interleaved":
                kwargs["interleave"] = args.pp_chunks or 2
            if args.microbatches:
                kwargs["n_microbatches"] = args.microbatches
            if args.pp_family == "transformer":
                from .parallel import train_transformer_pp
                name, fn = "train_transformer_pp", train_transformer_pp
                kwargs.update(seq_len=args.seq_len, n_heads=args.heads)
            elif args.pp_family == "lm":
                from .parallel import train_lm_pp
                name, fn = "train_lm_pp", train_lm_pp
                kwargs.update(seq_len=args.seq_len, n_heads=args.heads)
            if args.pp_family != "ffn" and args.attn != "oracle":
                kwargs["attn_impl"] = args.attn
        if m == 7:
            kwargs = dict(lr=lr)  # EP's expert loop has its own structure
        if m in (8, 10, 11, 12):
            kwargs = dict(lr=lr, seq_len=args.seq_len, n_heads=args.heads)
            if args.tp_sp and m == 8:
                kwargs["sequence_parallel"] = True
            if m in (8, 11) and args.attn != "oracle":
                kwargs["attn_impl"] = args.attn
            if m in (11, 12) and args.head != "oracle":
                kwargs["head_impl"] = args.head
        if m == 13:
            kwargs = dict(lr=lr, seq_len=args.seq_len,
                          n_heads=args.heads, seq_impl=args.seq_impl)
            if args.attn == "flash":
                kwargs["attn_impl"] = "flash"
            if args.head != "oracle":
                kwargs["head_impl"] = args.head
        if m == 1 and args.pallas:
            kwargs["use_pallas"] = True
            kwargs["interpret"] = jax.default_backend() != "tpu"
        if args.guardrails and m in (1, 2, 3, 11):
            from .runtime.guardrails import GuardrailConfig
            scale0 = 0.0
            if args.mixed and m in (2, 3):
                # dynamic loss scaling protects the bf16 backward; 2^15
                # is the conventional warm start (halves on overflow)
                scale0 = (args.loss_scale if args.loss_scale > 0
                          else 2.0 ** 15)
            kwargs["guard"] = GuardrailConfig(loss_scale=scale0)
        if mesh is not None:
            kwargs["mesh"] = mesh
        if args.profile_dir:
            # wrap fn itself so BOTH the direct and the checkpointing
            # branches profile (each checkpoint segment gets its own
            # timestamped trace run in the same directory)
            from .utils.profiling import profile_rank_0
            fn = profile_rank_0(os.path.join(args.profile_dir, name))(fn)
        probe = model_flops = None
        if metrics is not None:
            fam = family_of(m)
            model_flops = hand_flops_per_step(
                fam, tokens=tokens, model_size=args.model_size,
                n_layers=args.layers, seq_len=args.seq_len,
                vocab=args.vocab)
            attempt_log = None
            if chaos_plan is not None or args.spike_factor > 0:
                # supervise's per-attempt JSONL (failure.py default
                # path) — recorded ABSOLUTE so `report` folds it from
                # any working directory without being told
                attempt_log = os.path.abspath(os.path.join(
                    args.checkpoint_dir, name, "supervise.jsonl"))
            metrics.meta({"strategy": name, "family": fam,
                          "model_flops_per_step": model_flops,
                          "attempt_log": attempt_log,
                          "note": "first logged chunk includes compile"})
            probe = make_probe(fam)

        # strategies that split seeds strided across a data-ish axis
        # (data or expert; model/pipe axes replicate seeds) need every
        # chunk length divisible by it — ONE derivation shared by the
        # checkpoint segmenting and the metrics chunking below, so the
        # two can never drift
        seed_stride = 1
        if mesh is not None:
            seed_stride = (mesh.shape.get(DATA_AXIS, 1)
                           * mesh.shape.get(EXPERT_AXIS, 1))

        t0 = time.time()
        if args.checkpoint_dir:
            from .checkpoint import run_with_checkpointing
            ck_kwargs = dict(kwargs)
            opt = ck_kwargs.pop("optimizer", None)
            # guard threads per segment at the checkpoint layer (counter
            # continuity + anomaly events), not per trainer call
            guard_cfg = ck_kwargs.pop("guard", None)
            stateful_opt = opt is not None and not opt.stateless
            restore_shardings = None
            if m == 3 and stateful_opt and mesh is not None:
                # resume straight onto the 1/n FSDP layout — never
                # materialize full params + Adam moments on one device
                from .parallel.fsdp import checkpoint_shardings
                restore_shardings = checkpoint_shardings(params, opt, mesh)
            if metrics is not None:
                # bridge checkpoint/supervise events into the telemetry
                # stream AND synthesize one step record per published
                # segment (wall-time between publishes / segment length;
                # readbacks only at this cadence)
                last_pub = {"t": time.perf_counter()}

                def on_event(rec, _name=name, _flops=model_flops):
                    ev = rec.get("event")
                    if ev == "anomaly":
                        # schema v2 kinds get their own record stream
                        # (guardrail counters / ladder rungs), not the
                        # generic event envelope
                        metrics.anomaly(dict(rec, strategy=_name))
                        return
                    if ev == "rollback":
                        metrics.rollback(dict(rec, strategy=_name))
                        return
                    metrics.event(dict(rec, strategy=_name))
                    if ev != "published":
                        return
                    now = time.perf_counter()
                    a, b = rec.get("steps", (rec["step"], rec["step"]))
                    dt, last_pub["t"] = now - last_pub["t"], now
                    metrics.step(step=int(rec["step"]), strategy=_name,
                                 step_time_s=dt / max(1, b - a + 1),
                                 tokens=tokens, model_flops=_flops,
                                 peak=peak, hbm=hbm_high_water())

                ck_kwargs["on_event"] = on_event
            runner = run_with_checkpointing
            if chaos_plan is not None or args.spike_factor > 0:
                # fault load (and any armed spike guard — its remedy IS
                # the supervisor's rollback rung, so a real spike in a
                # chaos-free run must not escape as a raw traceback)
                # goes through the failure supervisor: a raised fault
                # rolls back in-process or costs one restart, and the
                # next attempt resumes from the last VERIFIED
                # checkpoint; kill@s takes the whole process, so its
                # recovery is the next invocation of this same command
                from .runtime.failure import supervise as runner
                ck_kwargs.update(max_restarts=args.max_restarts,
                                 max_rollbacks=args.max_rollbacks)
                if chaos_plan is not None:
                    ck_kwargs.update(chaos=chaos_plan, nonfinite="raise")
            out = runner(
                fn, params, seeds, tokens, args.model_size,
                ckpt_dir=os.path.join(args.checkpoint_dir, name),
                every=args.checkpoint_every, resume=not args.no_resume,
                seeds_divisor=seed_stride,
                backend=args.checkpoint_backend,
                optimizer=opt,
                # train_ddp threads (params, opt_state) through segments;
                # ZeRO-1's sharded state has no such surface yet
                thread_state=stateful_opt and not args.zero1,
                stateful=stateful_opt and args.zero1,
                guard=guard_cfg, spike_factor=args.spike_factor,
                # seed-poison injection only works where the data layer
                # carries it into a float gradient (the FFN family);
                # integer-token families keep the host-level poison so
                # the fault actually fires (rollback rung, not skip)
                in_graph_chaos=(guard_cfg is not None
                                and family_of(m) == "ffn"),
                restore_shardings=restore_shardings, **ck_kwargs)
        elif metrics is not None:
            # metrics-chunked driving: the schedule runs as log_every-step
            # compiled programs; steps inside a chunk stay dispatch-only
            # and every readback (wall-clock fence, probe, HBM stats)
            # batches at the chunk boundary — the logged step.
            chunk = args.log_every if args.log_every > 0 else len(seeds)
            opt = kwargs.get("optimizer")
            if opt is not None and not getattr(opt, "stateless", False):
                # stateful optimizers carry state INSIDE each trainer
                # call; chunked calls would re-init it and change the
                # math — fall back to one whole-run record
                print(f"metrics: --log_every ignored for {name} with a "
                      "stateful optimizer (state is per-call; chunked "
                      "driving would re-initialize it); logging one "
                      "whole-run record", file=sys.stderr)
                chunk = len(seeds)
            elif chunk % seed_stride or (len(seeds) % chunk) % seed_stride:
                # every chunk (including the final partial one) must
                # divide across the strided seed split, exactly like
                # --checkpoint_every (run_with_checkpointing validates
                # the same invariant)
                print(f"metrics: --log_every {chunk} does not tile "
                      f"{len(seeds)} steps across the {seed_stride}-way "
                      f"seed stride of {name}; logging one whole-run "
                      "record", file=sys.stderr)
                chunk = len(seeds)
            g_cfg = kwargs.get("guard")
            gstate = None
            g_prev = {"skipped": 0, "overflows": 0}
            out = params
            done = 0
            while done < len(seeds):
                n_chunk = int(min(chunk, len(seeds) - done))
                tc = time.perf_counter()
                if g_cfg is not None:
                    # thread the guard state across chunks (scale and
                    # counters persist) and surface per-chunk deltas
                    out, gstate = fn(out, seeds[done:done + n_chunk],
                                     tokens, args.model_size,
                                     guard_state=gstate,
                                     return_guard=True, **kwargs)
                else:
                    out = fn(out, seeds[done:done + n_chunk], tokens,
                             args.model_size, **kwargs)
                jax.block_until_ready(out)
                dt = time.perf_counter() - tc
                done += n_chunk
                if g_cfg is not None:
                    from .runtime.guardrails import (anomaly_delta,
                                                     summarize)
                    g_cur = summarize(gstate)
                    delta = anomaly_delta(g_prev, g_cur, done,
                                          [done - n_chunk + 1, done])
                    if delta is not None:
                        metrics.anomaly(dict(delta, strategy=name))
                    g_prev = g_cur
                loss = gnorm = None
                if probe is not None:
                    try:
                        loss, gnorm = probe(
                            out, seeds[min(done, len(seeds) - 1)])
                    except Exception as e:  # noqa: BLE001 — never kill the run
                        print(f"metrics: probe disabled for {name} "
                              f"({type(e).__name__}: {str(e)[:120]})",
                              file=sys.stderr)
                        probe = None
                metrics.step(step=done, strategy=name, loss=loss,
                             grad_norm=gnorm,
                             step_time_s=dt / n_chunk, tokens=tokens,
                             model_flops=model_flops, peak=peak,
                             hbm=hbm_high_water())
        else:
            out = fn(params, seeds, tokens, args.model_size, **kwargs)
        jax.block_until_ready(out)
        t1 = time.time()
        results[m] = out
        corner_m = ((lambda w: w[0, 0]) if m in (7, 10, 12)
                    else (lambda w: w[0]))
        print(f"\n{name} takes {t1 - t0} seconds")
        print(f"final {name} layers_params[0]", out.w1[0].shape,
              out.w2[0].shape)
        print(f"final {name} layers_params[0]", corner_m(out.w1)[:5, :5],
              corner_m(out.w2)[:5, :5])

    failed = False
    if args.method in (0, 9):
        # the reference compares DDP vs FSDP (:386-391); we also pin TP to
        # the single-device oracle (same data schedule). The Pallas kernels'
        # tiled f32 accumulation order differs from plain XLA, so loosen
        # the tolerance when they computed method 1; likewise --mixed,
        # where TP's bf16 contraction is split across shards (the psum
        # order composes with bf16 rounding).
        rtol, atol = ((1e-4, 1e-5) if args.pallas else
                      (2e-2, 1e-4) if args.mixed else (1e-5, 1e-7))
        checks = [("ddp", "fsdp", results[2], results[3], rtol, atol),
                  ("1dev", "tp", results[1], results[4], rtol, atol)]
        if args.method == 9:
            # every extension strategy against its oracle (the reference's
            # --method 0 idea extended to the full surface)
            from .parallel import (train_ddp, train_moe_dense,
                                   train_transformer_single)
            # hybrid(dp x tp) == DDP over a dp-sized mesh: TP is an exact
            # decomposition, so only the data axis affects the math
            dp = hybrid_dp()
            ddp_dp = train_ddp(params_for(2), seeds, tokens,
                               args.model_size,
                               make_mesh({DATA_AXIS: dp}), lr=lr,
                               unroll=unroll)
            checks.append(("hybrid", f"ddp({dp})", results[5], ddp_dp,
                           rtol, atol))
            # PP replicates the data; microbatch grads sum to the
            # full-batch grad => equals the single-device run
            checks.append(("pp", "1dev", results[6], results[1],
                           rtol, atol))
            # EP == the dense grouped-dispatch oracle, no mesh involved
            moe_dense = train_moe_dense(params_for(7), seeds, tokens,
                                        args.model_size, lr=lr,
                                        n_groups=n_dev)
            checks.append(("moe_ep", "moe_dense", results[7], moe_dense,
                           1e-4, 1e-5))
            # transformer TP replicates the data => equals transformer
            # single-device
            t_single = train_transformer_single(
                params_for(8), seeds, tokens, args.model_size, lr=lr,
                seq_len=args.seq_len, n_heads=args.heads)
            checks.append(("ttp", "t1dev", results[8], t_single,
                           1e-4, 1e-5))
            # GShard MoE transformer == its dense grouped oracle
            from .parallel import train_moe_transformer_dense
            mt_dense = train_moe_transformer_dense(
                params_for(10), seeds, tokens, args.model_size, lr=lr,
                seq_len=args.seq_len, n_heads=args.heads, n_groups=n_dev)
            checks.append(("moe_tf_ep", "moe_tf_dense", results[10],
                           mt_dense, 1e-4, 1e-5))
            # vocab-parallel LM TP replicates the data => equals the LM
            # single-device oracle on the real objective
            from .parallel import train_lm_single
            lm_single = train_lm_single(
                params_for(11), seeds, tokens, args.model_size, lr=lr,
                seq_len=args.seq_len, n_heads=args.heads)
            checks.append(("lm_tp", "lm_1dev", results[11], lm_single,
                           1e-4, 1e-5))
            # sequence-parallel LM replicates the data too (each shard
            # regenerates the batch and takes its token block) => equals
            # the same single-device oracle
            checks.append(("lm_seq", "lm_1dev", results[13], lm_single,
                           1e-4, 1e-5))
            # GShard MoE-LM == its dense grouped oracle (real loss + aux)
            from .parallel import train_moe_lm_dense
            moe_lm_dense = train_moe_lm_dense(
                params_for(12), seeds, tokens, args.model_size, lr=lr,
                seq_len=args.seq_len, n_heads=args.heads, n_groups=n_dev)
            checks.append(("moe_lm_ep", "moe_lm_dense", results[12],
                           moe_lm_dense, 1e-4, 1e-5))
        for la, lb, a, b, rt, at in checks:
            # leaves-with-paths rather than _fields: the LM family's params
            # nest (blocks is a NamedTuple inside LMParams)
            flat_a = jax.tree_util.tree_flatten_with_path(a)[0]
            flat_b = jax.tree_util.tree_leaves(b)
            for (path, leaf_a), leaf_b in zip(flat_a, flat_b):
                field = jax.tree_util.keystr(path)
                pa, pb = np.asarray(leaf_a), np.asarray(leaf_b)
                if not np.allclose(pa, pb, rtol=rt, atol=at):
                    print(f"SoftAssertionError: {la}{field} vs "
                          f"{lb}{field} max|diff|={np.abs(pa - pb).max()}")
                    failed = True
    if metrics is not None:
        metrics.close()  # drain the writer: records are on disk on exit
    return 1 if (failed and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
