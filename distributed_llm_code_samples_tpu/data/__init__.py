"""Deterministic seeds-as-dataset data layer.

Parity target: the reference's mock data pipeline (``train_ffns.py:144-151``,
``:350, :356-360``) where **data distribution = seed distribution**: the
dataset is never materialized centrally; each training step is defined by one
integer seed, and each strategy decides which ranks consume which seeds.
This is what makes cross-strategy differential testing possible.

TPU-native translation: counter-based RNG. A step's ``(x, dloss_dx)`` pair is
a pure function of its integer seed via ``jax.random.fold_in`` — so the same
seed produces bit-identical data on every rank, on every strategy, inside or
outside ``jit``/``shard_map``/``scan`` (the idiomatic equivalent of the
reference's re-seeded ``torch.Generator`` per step).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import DLOSS_DX_COEF

# Base key folded with each per-step seed; fixed, like the reference's fresh
# torch.Generator per step (train_ffns.py:145-148).
_DATA_KEY = 0

# In-graph fault-injection flags (runtime/chaos.py with guardrails on):
# the seed IS the dataset, so a fault that must fire INSIDE a compiled
# multi-step chunk rides the seed value itself — the chaos layer sets a
# high bit on the target step's seed and `batch_from_seed` turns it into
# a poisoned upstream gradient via `jnp.where`, deterministically, on
# every strategy, with no per-strategy plumbing. Schedule seeds live in
# [0, 100_000) (make_seed_schedule), so bits 28/29 are always free.
POISON_NAN_BIT = 1 << 29
POISON_INF_BIT = 1 << 28
_POISON_MASK = POISON_NAN_BIT | POISON_INF_BIT


def strip_poison(seed):
    """The underlying schedule seed, poison flags cleared (traced-safe)."""
    return jnp.bitwise_and(jnp.asarray(seed), jnp.int32(~_POISON_MASK))


def batch_from_seed(seed: jax.Array, batch_size: int, model_size: int,
                    dtype=jnp.float32):
    """One step's ``(x, dloss_dx)`` from its integer seed.

    ``x = normal([batch, d])``; the loss is mocked by a randomized upstream
    gradient ``dloss_dx = 0.1 * normal([batch, d])`` "coming from the right"
    (``train_ffns.py:12, :30, :149-150``). ``seed`` may be a traced scalar —
    this works inside ``lax.scan`` over a seed schedule.

    A seed carrying a poison flag (``POISON_NAN_BIT``/``POISON_INF_BIT``,
    set by ``runtime.chaos`` for in-graph fault injection) produces the
    *same* ``x`` as its base seed but a NaN/Inf ``dloss_dx`` — the
    poisoned-gradient step the in-graph guardrails
    (``runtime/guardrails.py``) must catch and skip.
    """
    seed = jnp.asarray(seed)
    base = strip_poison(seed)
    key = jax.random.fold_in(jax.random.PRNGKey(_DATA_KEY), base)
    kx, kd = jax.random.split(key)
    x = jax.random.normal(kx, (batch_size, model_size)).astype(dtype)
    dloss_dx = (DLOSS_DX_COEF *
                jax.random.normal(kd, (batch_size, model_size))).astype(dtype)
    nan_p = jnp.bitwise_and(seed, jnp.int32(POISON_NAN_BIT)) != 0
    inf_p = jnp.bitwise_and(seed, jnp.int32(POISON_INF_BIT)) != 0
    dloss_dx = jnp.where(nan_p, jnp.asarray(jnp.nan, dloss_dx.dtype),
                         dloss_dx)
    dloss_dx = jnp.where(inf_p, jnp.asarray(jnp.inf, dloss_dx.dtype),
                         dloss_dx)
    return x, dloss_dx


def mock_data(seeds, batch_size: int, model_size: int, dtype=jnp.float32):
    """Eager generator over the seed schedule — host-side analogue of the
    reference's ``mock_data`` (``train_ffns.py:144-151``). The jitted training
    paths use ``batch_from_seed`` inside the step instead."""
    for seed in np.asarray(seeds).tolist():
        yield batch_from_seed(jnp.int32(seed), batch_size, model_size, dtype)


def lm_batch_from_seed(seed: jax.Array, batch: int, seq_len: int,
                       vocab: int):
    """One LM step's ``(tokens, targets)`` from its integer seed: a
    deterministic ``[batch, seq_len + 1]`` token draw, split next-token
    style (``targets`` = ``tokens`` shifted left by one). Same counter-RNG
    contract as ``batch_from_seed`` — bit-identical on every rank, traced
    or eager — so the LM strategies keep the framework's seeds-as-dataset
    differential-testing story."""
    # poison flags are an FFN-family (float-gradient) injection; integer
    # token draws strip them so a poisoned schedule stays deterministic
    key = jax.random.fold_in(jax.random.PRNGKey(_DATA_KEY),
                             strip_poison(seed))
    toks = jax.random.randint(key, (batch, seq_len + 1), 0, vocab,
                              dtype=jnp.int32)
    return toks[:, :-1], toks[:, 1:]


_CORPUS = None


def load_text_corpus() -> np.ndarray:
    """The embedded REAL-text corpus as a ``uint8`` byte array (~237 KB of
    English prose: the concatenated license texts shipped with every
    Debian image under ``/usr/share/common-licenses`` — freely
    redistributable verbatim, vendored at
    ``data_assets/corpus.txt``). Byte-level vocab (256): every byte is a
    token, so no tokenizer is needed and the LM family trains on real
    text end to end (the capability synthetic seeds can't demonstrate)."""
    global _CORPUS
    if _CORPUS is None:
        import os
        path = os.path.join(os.path.dirname(__file__), "..",
                            "data_assets", "corpus.txt")
        with open(path, "rb") as f:
            _CORPUS = np.frombuffer(f.read(), dtype=np.uint8)
    return _CORPUS


def text_batch_from_seed(seed: jax.Array, batch: int, seq_len: int,
                         corpus=None):
    """One real-text LM step from its integer seed: ``batch`` random
    windows of ``seq_len + 1`` bytes gathered from the corpus, split
    next-token style like ``lm_batch_from_seed``. Same counter-RNG
    contract (``fold_in`` on the seed), so it is deterministic, traceable
    (works inside ``lax.scan`` over a seed schedule), and identical on
    every rank — real text slots into the seeds-as-dataset design
    unchanged. ``corpus`` defaults to the embedded one; pass any 1-D
    ``uint8``/int array to train on other bytes."""
    data = jnp.asarray(load_text_corpus() if corpus is None else corpus)
    key = jax.random.fold_in(jax.random.PRNGKey(_DATA_KEY), seed)
    starts = jax.random.randint(key, (batch,), 0,
                                data.shape[0] - seq_len)  # exclusive: the
    # last valid window start is len - seq_len - 1, so every seq_len+1
    # window (incl. the corpus's final byte as a target) is reachable
    idx = starts[:, None] + jnp.arange(seq_len + 1)[None, :]
    seqs = data[idx].astype(jnp.int32)
    return seqs[:, :-1], seqs[:, 1:]


def make_seed_schedule(num_steps: int, random_seed: int = 0) -> jnp.ndarray:
    """``num_steps`` integer seeds in ``[0, 100_000)`` (``train_ffns.py:360``).

    ``random_seed != 0`` makes the schedule reproducible across runs
    (``train_ffns.py:350, :356-359``); ``0`` draws from OS entropy like the
    reference's default generator.
    """
    if random_seed != 0:
        rng = np.random.default_rng(random_seed)
    else:
        rng = np.random.default_rng()
    return jnp.asarray(rng.integers(0, 100_000, size=(num_steps,)),
                       dtype=jnp.int32)


def shard_seeds_strided(seeds, n_ranks: int) -> jnp.ndarray:
    """Strided seed split: returns ``[steps_per_rank, n_ranks]`` where column
    ``r`` is rank ``r``'s schedule — rank ``r``'s step ``t`` consumes global
    seed ``seeds[t * n_ranks + r]``, exactly the reference's
    ``seeds.reshape((-1, nGPUs)).chunk(nGPUs, dim=1)`` (``train_ffns.py:182``).

    Getting this wrong silently breaks DDP == FSDP differential tests
    (SURVEY.md section 7, "hard parts").
    """
    seeds = jnp.asarray(seeds)
    if seeds.shape[0] % n_ranks != 0:
        raise ValueError(
            f"num_steps={seeds.shape[0]} not divisible by n_ranks={n_ranks} "
            "(reference asserts the same, train_ffns.py:175)")
    return seeds.reshape(-1, n_ranks)


def shard_seeds_elastic(seeds, n_ranks: int, accum: int) -> jnp.ndarray:
    """Global-batch-preserving re-stride for topology-elastic resume:
    ``[T] -> [T / (accum * n_ranks), accum, n_ranks]`` where slot
    ``[t, j, r]`` is global seed ``seeds[t*N + j*n_ranks + r]`` with
    ``N = accum * n_ranks`` — the original device count at save time.

    Rank ``r``'s optimizer update ``t`` gradient-accumulates over its
    ``accum`` seeds, so the union of seeds per update is exactly
    ``seeds[t*N : (t+1)*N]`` — the same global batch the N-device run
    consumed (``shard_seeds_strided`` semantics). A checkpoint saved
    under N devices therefore resumes onto ``n_ranks = N/accum``
    survivors with the SAME update sequence: the post-resume batch order
    is deterministic and the loss trajectory matches the uninterrupted
    N-device run (tests/test_elastic.py pins it).

    ``accum=1`` degrades to ``shard_seeds_strided`` with an extra
    singleton axis."""
    seeds = jnp.asarray(seeds)
    if accum < 1:
        raise ValueError(f"accum must be >= 1, got {accum}")
    n = accum * n_ranks
    if seeds.shape[0] % n != 0:
        raise ValueError(
            f"num_steps={seeds.shape[0]} not divisible by the "
            f"{n}-seed global batch ({accum} accum x {n_ranks} ranks) "
            "— elastic resume preserves the save-time global batch")
    return seeds.reshape(-1, accum, n_ranks)
