"""High-throughput decode engine: paged KV cache, continuous batching,
quantized KV, fused sampling (see ``decode/engine.py`` and DESIGN.md
section 15)."""

from .engine import DecodeEngine, EngineConfig
from .paged import (KV_DTYPES, PagedKV, SCRATCH_BLOCK, gather_layer,
                    init_pool, kv_bytes_per_token, write_chunk,
                    write_rows)
from .sampling import check_sampling, make_pick

__all__ = [
    "DecodeEngine", "EngineConfig",
    "KV_DTYPES", "PagedKV", "SCRATCH_BLOCK", "gather_layer", "init_pool",
    "kv_bytes_per_token", "write_chunk", "write_rows",
    "check_sampling", "make_pick",
]
