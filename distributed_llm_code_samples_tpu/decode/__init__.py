"""High-throughput decode engine: paged KV cache, continuous batching,
quantized KV, fused sampling (``decode/engine.py``, DESIGN.md section
15) — plus the round-10 serving reliability layer: in-graph logits
quarantine, pool-pressure preemption, snapshot-resume supervision, and
request-level admission control (``decode/supervise.py``, DESIGN.md
section 16)."""

from .draft import draft_tokens
from .engine import (AdmissionError, DecodeEngine, EngineConfig,
                     FLIGHT_FILENAME, HANDOFF_VERSION, POISON_ALL,
                     POISON_NONE, REQUEST_EVENTS, ServePolicy)
from .fleet import (EngineHandle, FleetRouter, HandoffRef,
                    TransportDead, TransportError, TransportTimeout)
from .paged import (KV_DTYPES, PagedKV, SCRATCH_BLOCK, copy_block,
                    corrupt_block, extract_blocks, fused_decode_attn,
                    gather_layer, implant_block, init_pool,
                    kv_bytes_per_token, pool_bytes, scrub_blocks,
                    write_chunk, write_rows)
from .prefix import PrefixCache, PrefixNode
from .sampling import check_sampling, check_speculation, make_pick
from .supervise import (SNAPSHOT_FILENAME, load_snapshot,
                        restore_engine_state, snapshot_state,
                        supervise_decode, write_snapshot)
from .worker import (ProcessEngineHandle, spawn_fleet_handles,
                     spawn_worker)

__all__ = [
    "AdmissionError", "DecodeEngine", "EngineConfig", "EngineHandle",
    "FLIGHT_FILENAME", "FleetRouter", "HANDOFF_VERSION", "HandoffRef",
    "ProcessEngineHandle", "TransportDead", "TransportError",
    "TransportTimeout", "spawn_fleet_handles", "spawn_worker",
    "POISON_ALL", "POISON_NONE", "REQUEST_EVENTS", "ServePolicy",
    "KV_DTYPES", "PagedKV", "SCRATCH_BLOCK", "copy_block",
    "corrupt_block", "draft_tokens", "extract_blocks",
    "fused_decode_attn",
    "gather_layer", "implant_block", "init_pool",
    "kv_bytes_per_token", "pool_bytes",
    "PrefixCache", "PrefixNode",
    "scrub_blocks", "write_chunk", "write_rows",
    "check_sampling", "check_speculation", "make_pick",
    "SNAPSHOT_FILENAME", "load_snapshot", "restore_engine_state",
    "snapshot_state", "supervise_decode", "write_snapshot",
]
