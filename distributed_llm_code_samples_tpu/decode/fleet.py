"""Fleet-scale serving: a host-side router over N decode-engine
replicas, with disaggregated prefill/decode and KV-handoff migration —
round 16: across a REAL process boundary.

One ``DecodeEngine`` is not "heavy traffic from millions of users":
aggregate tokens/s scales only with what a single engine holds, and a
long prefill still steals a step from every running decode on the same
engine. This module is data parallelism one level up — the dp axis of
the training meshes (SNIPPETS.md [3]'s dp x mp factorization) applied
at the REQUEST level — plus the DistServe/Splitwise disaggregation
argument: prefill is compute-bound and bursty, decode is memory-bound
and steady, so co-locating them trades throughput for interference.

The router drives every replica through ONE handle API, with three
transports behind it:

- **In-process** (``EngineHandle``): the engine lives in the router's
  process; the PR 10 fleet, unchanged in behavior, now expressed
  through the same driver surface the process transport uses.
- **In-process + wire docs** (``wire_dir=``): every live KV move
  serializes through the versioned npz wire format
  (``runtime/wire.py`` — per-array CRC-32, atomic publish) and imports
  from the file. Same process, real serialization boundary: the bench
  floor for the transport, and the cheap test surface for wire
  rejection.
- **Process workers** (``decode/worker.py``): each engine runs in its
  own OS process behind a socket protocol
  (``ProcessEngineHandle``); KV crosses as wire files, an engine kill
  is a real SIGKILL, and a silent worker is a real hung peer. The
  router's liveness ladder (per-call deadlines -> bounded
  ``failure.backoff_delay`` retries -> declare dead -> SIGKILL ->
  migrate-from-last-snapshot) is what turns "a process stopped
  answering" into "every request still completes token-identically".

The three routing/migration moves, each riding machinery earlier
rounds already built:

- **Routing** (``FleetRouter.submit``): least-loaded admission over the
  per-engine digests the handles report (queue depth, occupancy, pool
  utilization), session affinity, and **prefix affinity** — the router
  probes every engine's radix tree (``warm_blocks``) and sends a
  sharer where the prefix is warm, so PR 9's ~1-prefill property holds
  FLEET-wide. A full target spills to the next-best engine; all-full
  sheds at the door (the serving 503).

- **Disaggregated prefill/decode** (``prefill_engines=M``): M dedicated
  prefill engines run the chunked prefill; the moment a prompt
  completes, the sequence ships to a decode engine via the
  **single-sequence KV handoff** (``DecodeEngine.export_sequence`` /
  ``import_sequence``, handoff doc v3 over the wire format). Decode
  engines execute ZERO prefill dispatches.

- **Migration as the same primitive**: pool exhaustion moves the
  youngest running sequence to a peer with capacity (live, no replay);
  a dead engine — dropped object or SIGKILLed process — migrates its
  in-flight requests to survivors from the router's last snapshot of
  it, where replay fills the gap since that snapshot and continues
  token-identically. The sampling keys fold ``(seed, uid, position)``
  — never the slot OR the engine — so a migrated sequence's remaining
  tokens match the un-migrated oracle bit for bit at every kv_dtype.

**Chaos at the boundary** (``fleet_chaos=``, the ``--fleet_chaos``
grammar, ``runtime/chaos.py`` FLEET_KINDS): ``kill_worker@R[:IDX]``
SIGKILLs a decode worker at the start of round R; ``hang_worker@R[:S]``
makes one go silent (the liveness ladder must declare it dead);
``corrupt_wire@R`` bit-flips the next wire handoff in transit (the CRC
layer must reject it with a named reason and the request must be
replay-rerouted with no partial import). The tier-1 drill kills one of
three worker PROCESSES mid-stream and pins byte-identical output
against the unkilled oracle.

**Live weight hot-swap** (round 17, ``rolling_deploy`` /
``schedule_deploy``, DESIGN.md section 23): publish a checkpoint (the
trainer's existing atomic fsync+CRC publish) and roll it through the
serving fleet with ZERO shed — drain one engine at a time over the
same KV handoff (waiting/mid-prefill requests move by
``release_request`` replay), swap its double-buffered weights to the
ledger-verified step, re-admit. In-flight requests finish on their
pinned ``weights_version`` wherever they land; new admissions take
the deployed one. A CRC-rejected target step — or any mid-roll
failure, a dying worker included — rolls every swapped engine back
with one named-reason ``rolled_back`` deploy record: no engine left
mixed. Chaos ``corrupt_deploy@R`` drills the torn-checkpoint path.

Every router decision emits one schema-v11 ``router`` record; live
moves carry ``blocks``/``bytes``/``duration_s`` plus the pinned
``transport`` attribution ({mode, bytes, crc_verify_s, retries} —
``bytes`` is the SERIALIZED size, what actually crosses the boundary);
a CRC rejection emits a ``wire_rejected`` record naming the reason.
Each round additionally emits one ``fleet`` health record and each
deploy its lifecycle ``deploy`` records.
``report router eng0 ...`` folds them onto the merged timeline
(DESIGN.md sections 20-23).
"""

from __future__ import annotations

import collections
import os
import time

from ..runtime import wire
from ..runtime.telemetry import (ROUTER_POSTMORTEM_PREFIX,
                                 STATUS_FILENAME)
from ..runtime.wire import WireError
from .engine import AdmissionError, DecodeEngine
from .supervise import snapshot_state

# engine-id prefixes: prefill tier "p", decode tier "e" (unified
# engines are decode-tier — they can prefill too)
DECODE_PREFIX = "e"
PREFILL_PREFIX = "p"

# hang_worker's default silence floor (seconds) when the spec has no
# :SECS. The ACTUAL default is derived from the target handle's
# per-call deadline at fire time (2.5x covers the deadline, its
# bounded-backoff retry, and scheduling slack) so the liveness ladder
# is GUARANTEED to declare the worker dead before it wakes — a fixed
# constant shorter than the transport's deadline would just stall the
# run and never fire the ladder it exists to drill
HANG_WORKER_DEFAULT_S = 30.0


class TransportError(RuntimeError):
    """A worker transport call failed (the process boundary's failure
    surface). The router's liveness ladder converts these into a
    dead-host declaration + migrate-from-last-snapshot."""


class TransportTimeout(TransportError):
    """A call (or its bounded-backoff retries) overran its deadline —
    the silent-worker signature."""


class TransportDead(TransportError):
    """The peer is gone (EOF / reset / process exited)."""


class HandoffRef:
    """One exported sequence in transit: either the in-process document
    itself (``doc``) or a published wire file (``path``), plus the
    scalar facts the router records either way."""

    __slots__ = ("uid", "position", "blocks_written", "doc", "path")

    def __init__(self, uid: int, position: int, blocks_written: int,
                 doc: dict | None = None, path: str | None = None):
        self.uid = uid
        self.position = position
        self.blocks_written = blocks_written
        self.doc = doc
        self.path = path


class EngineHandle:
    """One IN-PROCESS fleet member: the engine, its role, its liveness,
    and the driver API the router speaks (``decode/worker.py``'s
    ``ProcessEngineHandle`` implements the same surface over a socket).
    A killed handle drops its engine object outright — the in-process
    simulation of a dead host — keeping only the last snapshot the
    router migrates from."""

    transport = "inproc"

    def __init__(self, eid: str, engine: DecodeEngine, role: str,
                 wire_dir: str | None = None):
        self.id = eid
        self.engine = engine
        self.role = role                    # "prefill" | "decode"
        self.alive = True
        self.retired = False                # drained out, not dead
        self.snapshot: dict | None = None   # last snapshot_state doc
        self.killed_at_round: int | None = None
        self.last_tokens = 0                # decode-record cadence state
        self.last_t = time.perf_counter()
        # wall time of THIS engine's slice of the last fleet round —
        # the per-engine number the interference bench reads (the
        # round-robin loop serializes engines in-process, so timing a
        # whole round would charge every engine for its neighbors)
        self.last_step_s = 0.0
        # wire_dir set => every export serializes through the versioned
        # wire format and every import reads + CRC-verifies the file
        # (the in-process floor for the process transport)
        self.wire_dir = wire_dir
        self._did = False
        self._seq = 0
        # staged handoffs awaiting commit_import (async migration):
        # uid -> (verified doc, wire stats, spool path or None, mode)
        self._staged: dict[int, tuple] = {}

    # -- identity / validation ----------------------------------------

    def model_meta(self) -> dict:
        return self.engine.model_meta()

    def validate_member(self) -> None:
        if self.engine.mesh is not None:
            raise ValueError("fleet replicas are single-device "
                             "(KV handoff has no TP path)")

    # -- weight lifecycle (round 17, DESIGN.md section 23) -------------

    @property
    def serving_version(self) -> int:
        return self.engine.serving_version

    def load_weights(self, version: int, ckpt_dir: str, step: int,
                     params=None) -> dict:
        """Install checkpoint step ``step`` as weights version
        ``version``. In-process the ROUTER loads the checkpoint once
        per deploy and passes the params object here (read-only across
        replicas — engine programs donate only the pool); the process
        transport sends the recipe and each worker restores from the
        shared checkpoint dir itself (weights never ride the
        socket)."""
        if params is None:
            from ..runtime.weights import VersionLedger
            params = VersionLedger(ckpt_dir).load(step,
                                                  self.engine.params)
        return self.engine.load_weights(version, params)

    def set_serving_version(self, version: int) -> None:
        self.engine.set_serving_version(version)

    # -- reads ---------------------------------------------------------

    @property
    def has_work(self) -> bool:
        return self.alive and bool(self.engine.waiting
                                   or self.engine.active)

    def digest(self, light: bool = False) -> dict:
        """The scheduler-state view every routing decision reads —
        computed live in-process; the process transport returns the
        digest riding each worker response (same keys, zero extra
        round-trips, the flag ignored there — cached is cached).
        ``light=True`` skips the per-slot list for the hot-path scalar
        reads (load keys, capacity probes, fleet records) — the O(1)
        admission-path discipline. ``tokens_generated`` rides every
        digest (one int) so the live status doc's last-interval
        throughput costs zero extra round-trips."""
        e = self.engine
        d = {
            "waiting": len(e.waiting),
            "active": e.active,
            "serving_version": e.serving_version,
            "tokens_generated": e.tokens_generated,
            "free_slots": sum(1 for s in e.slots if s is None),
            "free_blocks": len(e.free_blocks),
            "evictable": (e.prefix.evictable_blocks()
                          if e.prefix is not None else 0),
            "utilization": e.kv_pool_utilization(),
            # KV spill tier (round 23, schema v17): host-tier occupancy
            # + cumulative clean restores — zeros when the tier is off
            "spill_tier_blocks": (0 if e.spill is None
                                  else len(e.spill)),
            "spill_restores": e.restores,
            "head": ({"prompt_len": len(e.waiting[0].prompt),
                      "max_new": e.waiting[0].max_new}
                     if e.waiting else None),
            # per-tenant live counts (schema v13; empty single-tenant)
            # — the in-flight half of the status doc's tenants block,
            # riding the digest so it costs zero extra round-trips
            "tenants": e.tenant_load(),
        }
        if not light:
            d["slots"] = [{"uid": s.uid, "prompt_done": s.prompt_done,
                           "admit_index": s.admit_index,
                           "prompt_len": len(s.prompt),
                           "max_new": s.max_new}
                          for s in e.slots if s is not None]
        return d

    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        return self.engine._blocks_needed(prompt_len, max_new)

    def max_blocks_per_seq(self) -> int:
        return self.engine.cfg.max_blocks_per_seq

    def warm_blocks(self, prompt) -> int | None:
        """Radix-tree warm-path depth for ``prompt`` (None when the
        prefix cache is off) — the prefix-affinity probe, under the
        SERVING version's root: a fresh admission pins the serving
        version, so retired versions' cached blocks must not count as
        warm (they can never be its hits) and the new version's must.
        Host-side read only; probing never steps an engine."""
        if self.engine.prefix is None:
            return None
        return self.engine.prefix.warm_blocks(
            prompt, self.engine.serving_version)

    # -- scheduling ----------------------------------------------------

    def submit(self, prompt, max_new: int, uid: int,
               trace: str | None = None,
               tenant: str | None = None) -> dict:
        """Submit; returns the WAITING snapshot entry for the router's
        O(1) snapshot-append discipline (raises ``AdmissionError`` on a
        full queue — the caller's spillover path). ``trace`` is the
        router-minted trace id the engine records verbatim; ``tenant``
        the request's tenant tag (schema v13)."""
        self.engine.submit(prompt, max_new, uid=uid, trace=trace,
                           tenant=tenant)
        seq = next(s for s in reversed(self.engine.waiting)
                   if s.uid == uid)
        return {"uid": seq.uid, "prompt": seq.prompt, "out": seq.out,
                "max_new": seq.max_new, "retries": seq.retries,
                "t_submit": seq.t_submit,
                "submit_step": seq.submit_step,
                "t_first": None,       # no first token yet
                "weights_version": None,   # pins at admission
                "trace_id": seq.trace_id,
                "tenant": seq.tenant,
                "state": "WAITING"}

    def resume_request(self, uid: int, prompt, max_new: int, *, out=(),
                       retries: int = 0, t_submit=None,
                       t_first=None, weights_version=None,
                       trace=None, tenant=None) -> None:
        self.engine.resume_request(uid, prompt, max_new, out=out,
                                   retries=retries, t_submit=t_submit,
                                   t_first=t_first,
                                   weights_version=weights_version,
                                   trace=trace, tenant=tenant)

    def release_request(self, uid: int) -> dict:
        """The drain primitive's replay half (rolling deploy): pop one
        live request off the engine, returning its replay entry."""
        return self.engine.release_request(uid)

    def step_begin(self, prefill_only: bool = False) -> None:
        """First half of one fleet-round step. In-process the step runs
        here (synchronously); the process transport SENDS the step to
        the worker so all workers step concurrently and ``step_end``
        collects."""
        t0 = time.perf_counter()
        self._did = self.engine.step(prefill_only=prefill_only)
        self.last_step_s = time.perf_counter() - t0

    def step_end(self) -> bool:
        return self._did

    def fetch_snapshot(self) -> dict:
        return snapshot_state(self.engine)

    # -- the KV handoff ------------------------------------------------

    def export(self, uid: int, keep: bool = False) -> HandoffRef:
        """Export one resident fully-prefilled sequence. With a
        ``wire_dir`` the document is serialized + atomically published
        as a wire file (per-array CRC-32); otherwise the doc rides
        in-process. ``keep=True`` is the async-migration ship-half:
        the sequence STAYS resident and decoding while its snapshot
        crosses (``finish_export`` settles up at commit time)."""
        doc = self.engine.export_sequence(uid, keep=keep)
        ref = HandoffRef(uid, int(doc["position"]),
                         int(doc["blocks_written"]))
        if self.wire_dir is None:
            ref.doc = doc
        else:
            import os
            os.makedirs(self.wire_dir, exist_ok=True)
            self._seq += 1
            ref.path = os.path.join(
                self.wire_dir, f"handoff_{self.id}_{uid}_{self._seq}.npz")
            wire.write_doc(ref.path, doc)
        return ref

    def import_doc(self, ref: HandoffRef) -> dict:
        """Import a handoff; returns the transport attribution
        ({mode, crc_verify_s, and — off the wire — bytes}). A doc-
        passing move reports no bytes here: the caller computes the
        serialized size OUTSIDE its timed window (``_move``), so the
        in-process stall numbers stay an honest floor for the wire
        lane instead of quietly including a serialization of their
        own. Raises ``WireError`` (one-line named reason) on a
        torn/corrupted wire file, BEFORE any engine state is
        touched."""
        if ref.doc is not None:
            self.engine.import_sequence(ref.doc)
            return {"mode": "inproc", "crc_verify_s": None}
        stats: dict = {}
        doc = wire.read_doc(ref.path, stats)    # raises WireError
        self.engine.import_sequence(doc)
        import os
        try:
            # consumed; a REJECTED file is kept for post-mortem by the
            # router's bounded retention instead (renamed *.rejected,
            # oldest pruned past keep_rejected — FleetRouter._move)
            os.unlink(ref.path)
        except OSError:
            pass
        return {"mode": "wire", "bytes": stats["bytes"],
                "crc_verify_s": stats["crc_verify_s"]}

    # -- async migration (round 22, DESIGN.md section 28) --------------

    def export_keep(self, uid: int) -> HandoffRef:
        """Ship-half of an async migration: export WITHOUT evicting
        (the worker handle names this op the same way — the router
        calls one method on either transport)."""
        return self.export(uid, keep=True)

    def finish_export(self, uid: int) -> dict:
        """Commit-half of an async migration on the SOURCE: evict now
        and return the final token list (status ``"resident"``), or
        the abort status when the request finished/failed/was
        preempted during the ship window."""
        return self.engine.finish_export(uid)

    def stage_ref(self, ref: HandoffRef) -> dict:
        """Stage a shipped handoff on the TARGET for a later
        ``commit_import``: integrity-verify NOW (the wire CRC ladder
        for a file; a doc-mode ref is already in-memory) and park the
        verified document keyed by uid — a corrupt ship must be
        rejected at stage time, never after the source evicted."""
        if ref.doc is not None:
            uid = int(ref.doc["uid"])
            self._staged[uid] = (ref.doc, {}, None, "inproc")
            return {"uid": uid, "mode": "inproc", "bytes": 0,
                    "crc_verify_s": None}
        stats: dict = {}
        doc = wire.read_doc(ref.path, stats)    # raises WireError
        uid = int(doc["uid"])
        self._staged[uid] = (doc, stats, ref.path, "wire")
        return {"uid": uid, "mode": "wire", "bytes": stats["bytes"],
                "crc_verify_s": stats["crc_verify_s"]}

    def stage_bytes(self, data: bytes) -> dict:
        """Stage a handoff shipped as raw wire bytes (the TCP side
        channel) — the identical CRC discipline, off the stream."""
        stats: dict = {}
        doc = wire.deserialize_doc(data, stats)  # raises WireError
        uid = int(doc["uid"])
        self._staged[uid] = (doc, stats, None, "tcp")
        return {"uid": uid, "mode": "tcp", "bytes": stats["bytes"],
                "crc_verify_s": stats["crc_verify_s"]}

    def commit_import(self, uid: int, out=None) -> dict:
        """Import the staged doc. ``out`` (when given) patches the
        token list to the source's FINAL one first — ``emitted`` stays
        at the ship point, so the engine's replay contract teacher-
        forces the delta and rebuilds the window bit-identically (the
        catch-up)."""
        entry = self._staged.pop(int(uid), None)
        if entry is None:
            raise ValueError(f"no staged handoff for uid {uid}")
        doc, stats, path, mode = entry
        if out is not None:
            doc = {**doc, "out": [int(t) for t in out]}
        self.engine.import_sequence(doc)
        if path is not None:
            try:
                os.unlink(path)     # consumed
            except OSError:
                pass
        return {"mode": mode, "bytes": stats.get("bytes", 0),
                "crc_verify_s": stats.get("crc_verify_s"),
                "catchup_tokens": (len(doc["out"])
                                   - int(doc["emitted"]))}

    def discard_stage(self, uid: int) -> bool:
        """Drop a staged handoff (the abort path: the request finished
        or was preempted on the source mid-ship). Idempotent."""
        entry = self._staged.pop(int(uid), None)
        if entry is not None and entry[2] is not None:
            try:
                os.unlink(entry[2])
            except OSError:
                pass
        return entry is not None

    # -- drain/telemetry surfaces --------------------------------------

    def results(self) -> dict[int, list[int]]:
        return dict(self.engine.finished)

    def failed_map(self) -> dict[int, dict]:
        return {u: dict(i) for u, i in self.engine.failed.items()}

    def stats(self) -> dict:
        e = self.engine
        return {
            "engine_steps": e.global_step,
            "tokens_generated": e.tokens_generated,
            "prefill_dispatches": e.prefill_dispatches,
            "compiled_programs": e.compile_count,
            "dispatches": e.dispatch_count,
            "finished": len(e.finished),
            "prefix_hit_blocks": e.prefix_hit_blocks,
            "prefill_tokens_saved": e.prefill_tokens_saved,
        }

    def emit_decode(self) -> None:
        if self.engine.metrics is None:
            return
        now = time.perf_counter()
        delta = self.engine.tokens_generated - self.last_tokens
        dt = max(now - self.last_t, 1e-9)
        tps = round(delta / dt, 2) if delta > 0 else None
        self.engine.metrics.decode(self.engine.telemetry_record(tps))
        self.last_tokens = self.engine.tokens_generated
        self.last_t = now

    # -- transport attribution (round 18, DESIGN.md section 24) --------

    def rpc_stats(self) -> dict | None:
        """Per-op RPC cost attribution — None in-process: a method
        call has no socket, no marshal, no deadline, so reporting
        zeros would masquerade as a measured transport."""
        return None

    def evidence(self) -> dict:
        """The router-side view of this member for a dead-host
        postmortem: what the router knew when it declared death. The
        in-process handle has no call/backoff history (calls are
        plain method calls) — the last snapshot summary is the
        evidence."""
        snap = self.snapshot
        return {
            "transport": self.transport,
            "alive": self.alive,
            "last_snapshot_step": (None if snap is None
                                   else snap.get("step")),
            "last_snapshot_requests": (None if snap is None
                                       else len(snap.get("requests",
                                                         ()))),
        }

    # -- liveness ------------------------------------------------------

    def ping(self) -> None:
        """Heartbeat no-op in-process (the process transport's ping is
        a real round-trip with a short deadline)."""

    def warm(self, deadline_s: float = 600.0) -> int:
        """Pre-build the engine's full program set BEFORE it takes
        traffic (``DecodeEngine.warm``) — the autoscaler's
        spawn-then-warm discipline: a joining member must never pay
        its compiles under live load. Returns the engine's compile
        count; ``deadline_s`` is ignored in-process (the process
        transport bounds the RPC with it)."""
        return self.engine.warm()

    def hang(self, secs: float) -> None:
        raise ValueError(
            "hang_worker requires the process transport (an in-process "
            "engine cannot go silent without hanging the router) — run "
            "the fleet with --transport process")

    def kill(self) -> None:
        """Drop the engine object — the in-process dead host. Its pool,
        like a dead host's HBM, is unreachable afterwards."""
        self.alive = False
        self.engine = None

    def close(self) -> None:
        """Release transport resources (no-op in-process)."""


class FleetRouter:
    """N decode-engine replicas behind one admission point.

    ``make_engine(engine_id)`` is a factory returning a FRESH
    single-device engine per fleet member (attach a per-engine
    ``TelemetryWriter`` inside it; the router never shares one), OR
    pass pre-built ``handles=`` (the process transport:
    ``decode/worker.py`` spawns the workers and hands their
    ``ProcessEngineHandle``s over). All engines must share the
    numerics-relevant ``EngineConfig`` keys and the model — the
    handoff's own fingerprint check enforces it at migration time, and
    the router cross-checks fingerprints up front so a mismatched fleet
    fails at construction, not mid-drill.

    ``prefill_engines=M`` dedicates the first M members to prefill
    (disaggregation); ``0`` runs every engine unified. ``n_engines``
    may be 1 (the router degenerates to a pass-through — the honest
    N=1 baseline for the bench scaling rows); the CLI requires >= 2.

    ``snapshot_every`` is the router-held snapshot cadence in fleet
    rounds (the PR 5 discipline: a kill migrates from the LAST
    snapshot and replay fills the gap since it). ``wire_dir`` routes
    every in-process live move through the wire format (serialize +
    CRC-verify + import from the published file). ``fleet_chaos`` is a
    validated ``FaultPlan`` of FLEET_KINDS faults, fired on the
    router's round clock.
    """

    def __init__(self, make_engine, n_engines: int,
                 prefill_engines: int = 0, *, metrics=None,
                 snapshot_every: int = 1, session_affinity: bool = True,
                 prefix_affinity: bool = True, wire_dir: str | None = None,
                 handles: list | None = None, fleet_chaos=None,
                 keep_rejected: int = 8, status_dir: str | None = None,
                 status_every_s: float = 1.0,
                 async_migration: bool = False):
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        if not 0 <= prefill_engines < n_engines:
            raise ValueError(
                f"prefill_engines must leave >= 1 decode engine: got "
                f"{prefill_engines} of {n_engines}")
        if snapshot_every < 1:
            raise ValueError(f"snapshot_every must be >= 1, got "
                             f"{snapshot_every}")
        if handles is not None:
            if len(handles) != n_engines:
                raise ValueError(f"{len(handles)} handle(s) for "
                                 f"n_engines={n_engines}")
            self.handles = list(handles)
        else:
            self.handles = []
            for i in range(prefill_engines):
                eid = f"{PREFILL_PREFIX}{i}"
                self.handles.append(EngineHandle(
                    eid, make_engine(eid), "prefill", wire_dir=wire_dir))
            for i in range(n_engines - prefill_engines):
                eid = f"{DECODE_PREFIX}{i}"
                self.handles.append(EngineHandle(
                    eid, make_engine(eid), "decode", wire_dir=wire_dir))
        metas = [h.model_meta() for h in self.handles]
        if any(m != metas[0] for m in metas[1:]):
            raise ValueError("fleet engines disagree on model identity "
                             f"({metas}) — every replica must serve the "
                             "same weights")
        for h in self.handles:
            h.validate_member()
        self.by_id = {h.id: h for h in self.handles}
        self.metrics = metrics              # the ROUTER's own writer
        self.snapshot_every = snapshot_every
        self.session_affinity = session_affinity
        self.prefix_affinity = prefix_affinity
        self.fleet_chaos = fleet_chaos
        if fleet_chaos is not None:
            # every fault the plan can fire must be honorable by THIS
            # fleet — reject at construction, not rounds later at fire
            # time (the CLI's parse-rejection discipline, enforced once
            # here so library callers get it too)
            kinds = {f.kind for f in fleet_chaos.faults}
            wired = wire_dir is not None or any(
                h.transport == "process" for h in self.handles)
            if "corrupt_wire" in kinds and not wired:
                raise ValueError(
                    "corrupt_wire needs a wire boundary to corrupt: "
                    "run the fleet with --transport process (or an "
                    "in-process wire_dir)")
            decode_handles = [h for h in self.handles
                              if h.role == "decode"]
            if "hang_worker" in kinds and any(
                    h.transport != "process" for h in decode_handles):
                raise ValueError(
                    "hang_worker requires the process transport (an "
                    "in-process engine cannot go silent without "
                    "hanging the router) — run the fleet with "
                    "--transport process")
            for f in fleet_chaos.faults:
                if f.kind != "kill_worker":
                    continue
                idx = 0 if f.arg is None else int(f.arg)
                if idx >= len(decode_handles):
                    raise ValueError(
                        f"kill_worker index {idx} names e{idx}, but "
                        f"this fleet has {len(decode_handles)} decode "
                        "engine(s)")
                if len(decode_handles) == 1:
                    raise ValueError(
                        "kill_worker would kill the only decode "
                        "engine in this fleet (the survivors have "
                        "nowhere to migrate its requests)")
            # the round-22 network kinds drill the reconnect ladder,
            # which only the TCP family carries (AF_UNIX keeps the
            # round-16 EOF-is-dead semantics); slow_link only needs a
            # socket to be slow on
            if {"partition_worker", "drop_conn"} & kinds and any(
                    getattr(h, "family", None) != "tcp"
                    for h in decode_handles):
                raise ValueError(
                    "partition_worker/drop_conn drill the reconnect "
                    "ladder, which only the TCP transport carries — "
                    "run the fleet with --transport tcp")
            if "slow_link" in kinds and any(
                    h.transport != "process" for h in decode_handles):
                raise ValueError(
                    "slow_link injects socket latency and needs a "
                    "socket to inject it on — run the fleet with "
                    "--transport process (or tcp)")
        self.rounds = 0                     # fleet scheduling rounds
        self._next_uid = 0
        self._sessions: dict = {}           # session -> engine id
        # request book: what the router needs to place (and re-place)
        # a request — NOT a mirror of engine progress (the snapshot is)
        self.requests: dict[int, dict] = {}
        self._kills: dict[int, list[str]] = collections.defaultdict(list)
        # results carried off dead engines (their snapshot's finished/
        # failed maps; survivors re-complete anything newer)
        self._dead_finished: dict[int, list[int]] = {}
        self._dead_failed: dict[int, dict] = {}
        # decision counters (the payload/bench surface)
        self.routed = 0
        self.handoffs = 0
        self.migrations = 0
        self.sheds = 0
        self.kills = 0
        self.routed_by = {"least_loaded": 0, "session": 0, "prefix": 0}
        self.prefix_routed_hit_blocks = 0
        # migration-stall instrumentation (ROADMAP item 1's bench
        # criterion): every LIVE move (export -> import — prefill
        # handoff or pool-pressure migration) accumulates the blocks
        # and SERIALIZED bytes shipped and its wall-clock duration;
        # replay-migrations off a dead engine's snapshot ship no KV and
        # stay out of these (their records carry duration_s with
        # blocks/bytes 0 and transport mode "replay")
        self.handoff_blocks = 0
        self.handoff_bytes = 0
        self.handoff_durations: list[float] = []
        # wire-integrity accounting (round 16): rejected handoff files
        # (CRC/torn/version — each also emitted a ``wire_rejected``
        # router record with the one-line reason) and per-uid rejection
        # counts (the ``retries`` field of the next successful move)
        self.wire_rejects = 0
        self._uid_wire_rejects: dict[int, int] = {}
        self._corrupt_next_wire = False
        # -- async live migration (round 22, DESIGN.md section 28) --
        # opt-in: pool-pressure moves run the three-phase pipeline
        # (export_keep -> ship-during-step -> finish_export/commit)
        # instead of the synchronous export->import, so the source
        # engine never stalls for the ship; uid -> the pending move
        self.async_migration = async_migration
        self._pending_moves: dict[int, dict] = {}
        # reconnect accounting (schema v16 "reconnected" records):
        # every handle that can heal a dropped connection reports here
        self.reconnects_total = 0
        for h in self.handles:
            if hasattr(h, "on_reconnect"):
                h.on_reconnect = self._note_reconnect
        # bounded post-mortem retention for REJECTED wire docs (round
        # 17 satellite, mirroring checkpoint.keep_last): a rejected
        # handoff file is renamed *.rejected and the oldest are pruned
        # past this cap — a chaos loop of rejections must not grow a
        # worker's spool without bound. 0 keeps none.
        if keep_rejected < 0:
            raise ValueError(f"keep_rejected must be >= 0, got "
                             f"{keep_rejected}")
        self.keep_rejected = keep_rejected
        # -- live weight hot-swap (round 17, DESIGN.md section 23) --
        self._deploys: dict[int, tuple] = {}    # round -> (dir, step)
        self.deploys = 0
        self.deploy_rollbacks = 0
        # deploy-on-publish watcher (round 19, ROADMAP item 3
        # follow-on): poll the ledger's latest_verified on a wall-clock
        # cadence and roll forward when it advances past the fleet's
        # serving version — the trainer's atomic publish becomes the
        # deploy trigger, no operator in the loop (None = off)
        self._watch: tuple | None = None    # (ckpt_dir, poll_every_s)
        self._watch_t_last = 0.0
        # per-tenant admission accounting (round 19, schema v13): the
        # offered/shed half of the status doc's tenants block (the
        # in-flight half rides the digests); None tenants excluded
        self.tenant_offered: dict[str, int] = {}
        self.tenant_shed: dict[str, int] = {}
        # armed by corrupt_deploy chaos: the truncation fraction to
        # apply to the NEXT deploy's target checkpoint (None = off)
        self._corrupt_next_deploy: float | None = None
        # -- fleet trace spine + live ops plane (round 18, DESIGN.md
        # section 24) --
        # the router mints every request's fleet-unique trace id at
        # admission (host metadata only — no compiled program, no
        # extra round-trip); the nonce disambiguates routers across
        # processes/runs, the uid suffix within a run
        self._trace_nonce = os.urandom(4).hex()
        # live status doc: one atomic JSON per round via
        # wire.publish_json, throttled like the PR 12 spool snapshot
        # (the drain-end publish is forced so a finished run's doc is
        # always final). status_dir None (and no metrics writer) =
        # publishing off.
        if status_dir is None and metrics is not None:
            status_dir = os.path.dirname(metrics.path)
        self.status_dir = status_dir
        if status_every_s <= 0:
            raise ValueError(f"status_every_s must be > 0, got "
                             f"{status_every_s}")
        self.status_every_s = status_every_s
        self._status_t_last = 0.0       # monotonic: last publish
        self._status_tokens_last = 0    # fleet tokens at last publish
        self._status_wall_last: float | None = None
        # round wall clock (the denominator of the RPC overhead share)
        self.round_wall_s = 0.0
        # -- closed-loop autoscaling (round 20, DESIGN.md section 26) --
        # the controller (decode/autoscale.py) mirrors its live state
        # here after every tick for the status doc; the router itself
        # never decides to scale — it only provides the membership
        # primitives (add_engine/retire_engine) and the digests the
        # controller reads
        self.autoscale_state: dict | None = None
        # -- watchtower (round 21, DESIGN.md section 27) --
        # the live alert block (runtime/watch.py mirrors it here after
        # every tick, exactly like autoscale_state): the status doc's
        # ``alerts`` block and the router postmortem's
        # active-alerts-at-declaration evidence — null when no
        # watchtower drives this fleet
        self.watch_state: dict | None = None
        # spawned decode members continue the e-numbering — engine ids
        # are never reused (a retired/killed handle keeps its slot in
        # ``handles`` for the post-mortem book)
        self._decode_serial = sum(1 for h in self.handles
                                  if h.role == "decode")
        # per-tenant shed baseline consumed by _publish_status only
        # (the tps-interval pattern): the published doc's shed_delta
        # covers publish-to-publish exactly; an out-of-band
        # status_doc() read must not shorten it
        self._status_tenant_shed_last: dict[str, int] = {}

    # -- introspection -------------------------------------------------

    def alive_handles(self, role: str | None = None):
        return [h for h in self.handles if h.alive
                and (role is None or h.role == role)]

    def engine(self, eid: str) -> DecodeEngine:
        return self.by_id[eid].engine

    def close(self) -> None:
        """Release every handle's transport resources (shuts down
        worker processes under the process transport). Idempotent."""
        for h in self.handles:
            h.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- telemetry -----------------------------------------------------

    def _record(self, event: str, uid: int, source=None, target=None,
                reason=None, policy=None, trace_id=None,
                **extra) -> None:
        if self.metrics is None:
            return
        if trace_id is None:
            # every router record pins the request's trace id (v12);
            # callers on the shed path pass it explicitly — the
            # request book never learned a shed uid
            trace_id = self.requests.get(int(uid), {}).get("trace")
        if event == "migrated":
            # schema v16: every migrated record pins the async-
            # migration attribution, with honest defaults on the sync
            # and replay paths — ship_s null (nothing shipped while
            # decoding) and catchup_tokens = the replay length (the
            # full catch-up a replay-migration teacher-forces)
            extra.setdefault("ship_s", None)
            extra.setdefault("catchup_tokens",
                             int(extra.get("replay", 0)))
        self.metrics.router({"step": self.rounds, "uid": int(uid),
                             "event": event, "source": source,
                             "target": target, "reason": reason,
                             "policy": policy, "trace_id": trace_id,
                             **extra})

    def _note_reconnect(self, h, info: dict) -> None:
        """A handle healed a dropped connection (reconnect + sync +
        sequence-numbered replay): one schema-v16 ``reconnected``
        router record — uid -1, this is link-level, not per-request —
        so the drill can pin that a partition cost reconnects, never
        deaths."""
        self.reconnects_total += 1
        self._record("reconnected", -1, source=h.id,
                     reason=info.get("cause"),
                     attempts=info.get("attempts"),
                     gap_s=info.get("gap_s"),
                     replayed_ops=len(info.get("replayed", ())))

    def _event(self, record: dict) -> None:
        if self.metrics is not None:
            self.metrics.event(record)

    def _candidates(self, handles, prompt=None) -> list[dict]:
        """The per-engine scores a placement decision saw (schema-v9
        ``routed`` attribution): warm-block depth (null when the
        prefix probe didn't run — prefill-tier admission, affinity
        off, or no prompt), queue depth, active slots, pool
        utilization. Host-side reads only — probing never steps an
        engine."""
        out = []
        for h in handles:
            d = h.digest(light=True)
            warm = None
            if prompt is not None and self.prefix_affinity:
                warm = h.warm_blocks(prompt)
            out.append({
                "engine": h.id,
                "warm_blocks": warm,
                "queue_depth": d["waiting"],
                "active": d["active"],
                "pool_utilization": round(d["utilization"], 4),
            })
        return out

    def _fleet_record(self) -> dict:
        """One per-round fleet health record (schema-v9 ``fleet``
        kind): per-engine waiting/active/free-blocks/utilization and
        the load-imbalance scalar over alive decode engines
        (``(max - min) / max`` of ``active + waiting``; 0.0 balanced
        or idle, toward 1.0 when one engine holds everything)."""
        engines = {}
        loads = []
        for h in self.handles:
            if not h.alive:
                engines[h.id] = {"alive": False}
                continue
            d = h.digest(light=True)
            engines[h.id] = {
                "alive": True, "role": h.role,
                "waiting": d["waiting"], "active": d["active"],
                "free_blocks": d["free_blocks"],
                "utilization": round(d["utilization"], 4),
                "spill_tier_blocks": d.get("spill_tier_blocks", 0),
                "spill_restores": d.get("spill_restores", 0),
            }
            if h.role == "decode":
                loads.append(d["active"] + d["waiting"])
        imb = 0.0
        if len(loads) > 1 and max(loads) > 0:
            imb = round((max(loads) - min(loads)) / max(loads), 4)
        return {"step": self.rounds, "engines": engines,
                "load_imbalance": imb}

    # -- live ops plane (round 18, DESIGN.md section 24) ---------------

    def status_doc(self) -> dict:
        """The live fleet status document: one atomic, self-contained
        JSON snapshot of what an operator needs mid-run — per-engine
        liveness/role/serving-version/queue-depth/pool watermarks,
        deploy state, decision counters, and the throughput since the
        last publish. Built from the light digests (cached under the
        process transport — reading status never adds a round-trip)."""
        engines = {}
        tokens = 0
        in_flight: dict[str, int] = {}
        for h in self.handles:
            if not h.alive:
                # a RETIRED member drained out gracefully (scale-down)
                # — distinct from a death, which names the kill round
                engines[h.id] = ({"alive": False, "retired": True}
                                 if getattr(h, "retired", False)
                                 else {"alive": False,
                                       "killed_at_round":
                                           h.killed_at_round})
                continue
            d = h.digest(light=True)
            tokens += int(d.get("tokens_generated") or 0)
            for t, n in (d.get("tenants") or {}).items():
                in_flight[t] = in_flight.get(t, 0) + int(n)
            engines[h.id] = {
                "alive": True, "role": h.role,
                "serving_version": int(d["serving_version"]),
                "waiting": d["waiting"], "active": d["active"],
                "free_slots": d["free_slots"],
                "free_blocks": d["free_blocks"],
                "evictable_blocks": d["evictable"],
                "utilization": round(d["utilization"], 4),
                "spill_tier_blocks": d.get("spill_tier_blocks", 0),
                "spill_restores": d.get("spill_restores", 0),
                "last_step_s": round(h.last_step_s, 6),
            }
            fam = getattr(h, "family", None)
            if fam is not None:
                # the operator's "which boundary is this member
                # behind" tag (round 22): unix/tcp, with the member's
                # survived-reconnect count alongside under tcp
                engines[h.id]["family"] = fam
                engines[h.id]["reconnects"] = getattr(
                    h, "reconnects", 0)
        # the interval baseline is CONSUMED by _publish_status only —
        # an out-of-band status_doc() read (tests, an in-process
        # consumer) must not shorten the next published interval
        now = time.perf_counter()
        tps = None
        if self._status_wall_last is not None:
            dt = now - self._status_wall_last
            delta = tokens - self._status_tokens_last
            if dt > 0 and delta > 0:
                tps = round(delta / dt, 2)
        drained = all(not e.get("waiting") and not e.get("active")
                      for e in engines.values() if e.get("alive"))
        return {
            "version": 1,
            "t": time.time(),
            "round": self.rounds,
            "drained": drained,
            "engines": engines,
            "tokens_generated": tokens,
            "tokens_per_sec_last_interval": tps,
            "deploy": {
                "scheduled_rounds": sorted(self._deploys),
                "deploys": self.deploys,
                "rollbacks": self.deploy_rollbacks,
            },
            "counters": {
                "routed": self.routed, "handoffs": self.handoffs,
                "migrations": self.migrations, "sheds": self.sheds,
                "kills": self.kills,
                "wire_rejects": self.wire_rejects,
                "reconnects": self.reconnects_total,
            },
            # per-tenant ops counters (round 19, schema v13): in-flight
            # summed off the digests (zero extra round-trips), offered/
            # shed from the router's own admission book — empty dict on
            # a single-tenant fleet (the pre-v13 doc, plus this key)
            "tenants": {
                t: {"in_flight": in_flight.get(t, 0),
                    "offered": self.tenant_offered.get(t, 0),
                    "shed": self.tenant_shed.get(t, 0),
                    # sheds since the LAST PUBLISH (round 20): the
                    # operator's "is it shedding NOW" signal — the
                    # baseline is consumed by _publish_status exactly
                    # like the tps interval's
                    "shed_delta": (self.tenant_shed.get(t, 0)
                                   - self._status_tenant_shed_last
                                   .get(t, 0))}
                for t in sorted(set(in_flight)
                                | set(self.tenant_offered)
                                | set(self.tenant_shed))
            },
            # live autoscale state (round 20): mirrored by the
            # controller after every tick — null when no controller
            # drives this fleet
            "autoscale": self.autoscale_state,
            # live watchtower alerts (round 21): mirrored by the
            # watchtower after every tick — null when none watches
            "alerts": self.watch_state,
        }

    def _publish_status(self, force: bool = False) -> str | None:
        """Publish the status doc atomically (``wire.publish_json`` —
        a reader mid-drill sees the old doc or the new one, never a
        torn one), throttled to ``status_every_s`` like the PR 12
        spool snapshot: the ops plane must not put per-round fsyncs on
        the hot path. ``force`` (the drain-end publish) skips the
        throttle so a finished run's doc is final."""
        if self.status_dir is None:
            return None
        now = time.monotonic()
        if not force and now - self._status_t_last < self.status_every_s:
            return None
        self._status_t_last = now
        doc = self.status_doc()
        # consume the throughput-interval baseline HERE (the one
        # production caller): the next doc's tokens_per_sec covers
        # publish-to-publish exactly
        self._status_wall_last = time.perf_counter()
        self._status_tokens_last = doc["tokens_generated"]
        self._status_tenant_shed_last = dict(self.tenant_shed)
        os.makedirs(self.status_dir, exist_ok=True)
        return wire.publish_json(
            os.path.join(self.status_dir, STATUS_FILENAME), doc)

    def transport_stats(self) -> dict:
        """Per-worker RPC cost attribution (the process transport's
        measured overhead; in-process members report None — a method
        call has no transport to price): per-op call/handle duration
        percentiles, per-op overhead (router-side call minus
        worker-side handle = socket + JSON marshal), heartbeat RTTs,
        and the round wall clock the overhead share is computed
        against (``report``'s transport block)."""
        return {
            "round_wall_s": round(self.round_wall_s, 6),
            "rounds": self.rounds,
            "engines": {h.id: h.rpc_stats() for h in self.handles},
        }

    def emit_transport_stats(self) -> None:
        """One ``transport_stats`` event record on the router's stream
        (rides the schema-free event kind; ``report`` folds it into
        the transport block). Called at drain end by ``run()``; manual
        step() drivers call it themselves."""
        stats = self.transport_stats()
        if any(v for v in stats["engines"].values()):
            self._event({"event": "transport_stats", **stats})

    def _dump_router_postmortem(self, h, reason: str) -> str | None:
        """Atomically dump the router's own evidence on a dead-host
        declaration: the dying worker's flight recorder dies with the
        process, but the router still holds the last digests, the
        pending call ids, the per-op/backoff/ping history, and the
        declaration reason — published per engine
        (``router_postmortem_<id>.json`` next to the status doc /
        router stream) and rendered by ``report --postmortem``."""
        if self.status_dir is None:
            return None
        doc = {
            "version": 1,
            "engine": h.id,
            "round": self.rounds,
            "t": time.time(),
            "reason": reason,
            "evidence": h.evidence(),
            # active-alerts-at-declaration (round 21): what the
            # watchtower was ALREADY paging about when the router
            # declared this engine dead — null when none watches
            "alerts": self.watch_state,
        }
        os.makedirs(self.status_dir, exist_ok=True)
        return wire.publish_json(
            os.path.join(self.status_dir,
                         f"{ROUTER_POSTMORTEM_PREFIX}{h.id}.json"),
            doc)

    # -- routing -------------------------------------------------------

    def _load_key(self, h: EngineHandle):
        """Least-loaded ordering: queue depth first (waiting work is
        the latency the next request inherits), then slot occupancy,
        then pool pressure — engine id breaks ties deterministically."""
        d = h.digest(light=True)
        return (d["waiting"], d["active"],
                round(d["utilization"], 4), h.id)

    def _has_capacity(self, h: EngineHandle, prompt_len: int,
                      max_new: int) -> bool:
        """Can ``h`` take a handoff IMPORT right now (free slot + full
        block reservation)? Queue-based admission never needs this —
        submit/resume queue and the engine admits when space frees."""
        d = h.digest(light=True)
        if d["free_slots"] < 1:
            return False
        need = h.blocks_needed(prompt_len, max_new)
        if need > h.max_blocks_per_seq():
            return False
        return need <= d["free_blocks"] + d["evictable"]

    def _route(self, prompt, session, warm_by_id=None):
        """Pick the decode-tier engine for a fresh request. Precedence:
        session affinity (stickiness beats balance — the session's KV
        locality is on that engine), then prefix affinity (the engine
        with the deepest warm radix path wins, load breaking ties),
        then least-loaded. ``warm_by_id`` reuses warm-block counts a
        caller already probed (the candidates capture) so a
        telemetry-enabled submit walks each radix tree once, not
        twice."""
        handles = self.alive_handles("decode")
        if not handles:
            raise RuntimeError("no alive decode engine in the fleet")
        if self.session_affinity and session is not None:
            eid = self._sessions.get(session)
            if eid is not None and self.by_id[eid].alive:
                return self.by_id[eid], "session", 0
        if self.prefix_affinity:
            if warm_by_id is not None:
                warm = [(warm_by_id[h.id], h) for h in handles
                        if warm_by_id.get(h.id) is not None]
            else:
                warm = [(w, h) for h in handles
                        if (w := h.warm_blocks(prompt)) is not None]
            best = max((w for w, _ in warm), default=0)
            if best > 0:
                tied = [h for w, h in warm if w == best]
                return min(tied, key=self._load_key), "prefix", best
        return min(handles, key=self._load_key), "least_loaded", 0

    def submit(self, prompt, max_new: int, session=None,
               tenant: str | None = None) -> int:
        """Route one request into the fleet; returns its fleet-global
        uid. Disaggregated fleets admit through the least-loaded
        PREFILL engine (the decode target is chosen at handoff time,
        when the KV exists); unified fleets route by
        session/prefix/load. A full target spills over to the next
        engine by load; when every engine sheds, the request is shed
        fleet-wide (``AdmissionError``, one ``shed`` router record)."""
        # the uid is CONSUMED whether the request lands or sheds — a
        # shed record must never carry a number a later accepted
        # request reuses (the engine-side audit-trail discipline:
        # aliasing two requests per uid breaks the per-uid timeline)
        uid = self._next_uid
        self._next_uid += 1
        prompt = [int(t) for t in prompt]
        if tenant is not None:
            self.tenant_offered[tenant] = \
                self.tenant_offered.get(tenant, 0) + 1
        # the trace spine's mint point (schema v12): ONE fleet-unique
        # causal identity per admission, consumed like the uid whether
        # the request lands or sheds — it rides the engine submit, all
        # downstream request/span records, every router record, the
        # handoff doc (v5), and the snapshots (v7)
        trace = f"{self._trace_nonce}-{uid}"
        reason, hit_blocks = None, 0
        prefills = self.alive_handles("prefill")
        # decision attribution (schema v9): the per-engine scores this
        # placement saw, captured BEFORE any engine takes the request
        # (only when a router stream exists — the probe is host-cheap
        # but pointless without a record to ride); the routing decision
        # below REUSES the captured warm-block counts, so each radix
        # tree is walked once per submit either way
        candidates = None
        if prefills:
            order = sorted(prefills, key=self._load_key)
            reason = "least_loaded"
            if self.metrics is not None:
                candidates = self._candidates(order, prompt)
        else:
            warm_by_id = None
            if self.metrics is not None:
                candidates = self._candidates(
                    self.alive_handles("decode"), prompt)
                warm_by_id = {c["engine"]: c["warm_blocks"]
                              for c in candidates}
            target, reason, hit_blocks = self._route(prompt, session,
                                                     warm_by_id)
            others = sorted(
                (h for h in self.alive_handles("decode")
                 if h is not target), key=self._load_key)
            order = [target] + others
        shed_reasons = []
        shed_causes = []
        spilled = False
        for h in order:
            try:
                entry = h.submit(prompt, max_new, uid=uid, trace=trace,
                                 tenant=tenant)
            except AdmissionError as e:
                # the engine names WHY it shed (queue_full /
                # predicted_deadline_miss) — propagate it instead of
                # guessing, so the fleet-wide shed record and the
                # driver's per-tenant book attribute the real cause
                shed_causes.append(getattr(e, "reason", "queue_full"))
                shed_reasons.append(f"{h.id}: {shed_causes[-1]}")
                # spillover loses affinity — including the warm-block
                # count probed for the ORIGINAL target (the next engine
                # tried is cold; recording the stale count would credit
                # it with blocks it doesn't hold)
                reason, hit_blocks = "least_loaded", 0
                spilled = True
                continue
            self.requests[uid] = {"prompt": prompt, "max_new": max_new,
                                  "engine": h.id, "session": session,
                                  "trace": trace, "tenant": tenant,
                                  # admission round (round 21): the
                                  # watchtower's round-denominated
                                  # latency baseline for this uid
                                  "round": self.rounds}
            if session is not None and h.role == "decode":
                self._sessions[session] = h.id
            self.routed += 1
            self.routed_by[reason] = self.routed_by.get(reason, 0) + 1
            if reason == "prefix":
                self.prefix_routed_hit_blocks += hit_blocks
            # policy: what ACTUALLY placed the request — "spill" when
            # the probed target shed and the request landed on a later
            # engine by load (the affinity-era reason would credit a
            # policy that didn't place it)
            self._record("routed", uid, target=h.id, reason=reason,
                         policy=("spill" if spilled else reason),
                         prefix_hit_blocks=hit_blocks,
                         candidates=candidates)
            # the step-0 snapshot discipline: a kill before the first
            # cadence snapshot must still know this request exists.
            # O(1) per submit: append the one new WAITING entry
            # (returned by the handle's submit) to the existing
            # snapshot instead of re-serializing the whole engine — a
            # burst of n submissions must not pay O(n^2) host work on
            # the admission path; the cadence snapshot already lags by
            # design, and kill-migration only needs the request LISTED
            # (resume replays from `out`)
            if h.snapshot is None:
                h.snapshot = h.fetch_snapshot()
            else:
                h.snapshot["requests"].append(entry)
            return uid
        self.sheds += 1
        if tenant is not None:
            self.tenant_shed[tenant] = \
                self.tenant_shed.get(tenant, 0) + 1
        # the fleet-wide record names the PRIMARY target's cause (the
        # engine the router actually wanted — spillover engines only
        # corroborate), and the raised error carries it for the
        # driver's own per-reason book
        cause = shed_causes[0] if shed_causes else "queue_full"
        self._record("shed", uid, reason=cause, trace_id=trace)
        raise AdmissionError(
            f"every fleet engine shed request uid {uid}: "
            f"[{'; '.join(shed_reasons)}]", reason=cause)

    # -- the fleet round -----------------------------------------------

    def _fire_fleet_chaos(self) -> bool:
        """Fire fleet-transport faults due at the START of this round
        (``runtime/chaos.py`` FLEET_KINDS). Returns whether any
        fired."""
        if self.fleet_chaos is None:
            return False
        fired = False
        for f in self.fleet_chaos.fleet_due(self.rounds):
            fired = True
            if f.kind == "kill_worker":
                idx = 0 if f.arg is None else int(f.arg)
                eid = f"{DECODE_PREFIX}{idx}"
                if eid not in self.by_id:
                    raise ValueError(f"kill_worker index {idx} names "
                                     f"unknown engine {eid!r}")
                self.fleet_chaos._note(f, engine=eid)
                self.kill_engine(eid)
            elif f.kind == "hang_worker":
                cands = self.alive_handles("decode")
                if not cands:
                    continue
                if f.arg is None:
                    # derived default: strictly past the target's
                    # deadline + retry window, whatever it is tuned to
                    deadline = getattr(cands[0], "call_deadline_s", 0.0)
                    secs = max(HANG_WORKER_DEFAULT_S, 2.5 * deadline)
                else:
                    secs = float(f.arg)
                self.fleet_chaos._note(f, engine=cands[0].id,
                                       sleep_s=secs)
                cands[0].hang(secs)
            elif f.kind == "corrupt_wire":
                self.fleet_chaos._note(f)
                self._corrupt_next_wire = True
            elif f.kind == "corrupt_deploy":
                frac = 0.5 if f.arg is None else float(f.arg)
                self.fleet_chaos._note(f, frac=frac)
                self._corrupt_next_deploy = frac
            elif f.kind == "partition_worker":
                # drop the first alive decode worker's link BOTH ways;
                # the reconnect ladder must wait the partition out and
                # replay — zero deaths, one "reconnected" record
                cands = [h for h in self.alive_handles("decode")
                         if getattr(h, "family", None) == "tcp"]
                if not cands:
                    continue
                secs = 2.0 if f.arg is None else float(f.arg)
                self.fleet_chaos._note(f, engine=cands[0].id,
                                       secs=secs)
                cands[0].partition(secs)
            elif f.kind == "slow_link":
                # permanent injected latency from this round on — a
                # SLOW link, not a dead one: per-call deadlines must
                # absorb it without paging the liveness ladder
                cands = [h for h in self.alive_handles("decode")
                         if h.transport == "process"]
                if not cands:
                    continue
                ms = 50.0 if f.arg is None else float(f.arg)
                self.fleet_chaos._note(f, engine=cands[0].id, ms=ms)
                cands[0].slow_link(ms)
            elif f.kind == "drop_conn":
                # mid-message RST on the next send: the response is
                # lost in flight; reconnect + dedup-cache replay must
                # recover it with no duplicate side effects
                cands = [h for h in self.alive_handles("decode")
                         if getattr(h, "family", None) == "tcp"]
                if not cands:
                    continue
                self.fleet_chaos._note(f, engine=cands[0].id)
                cands[0].drop_conn()
        return fired

    def step(self) -> bool:
        """One fleet scheduling round: fire due chaos + kills (the
        round clock), step every alive engine once — CONCURRENTLY
        under the process transport (step_begin fans out, step_end
        collects; a worker that misses its deadline or drops its
        connection is declared dead mid-round and its requests migrate
        before the round continues) — heartbeat-ping the idle members,
        ship completed prefills to the decode tier, relieve pool
        pressure by migration, then refresh the router-held snapshots
        on cadence. Returns whether any engine ran work this round.

        The round's wall clock accumulates in ``round_wall_s`` (the
        denominator of the RPC overhead share) and the live status doc
        publishes at round end, throttled (DESIGN.md section 24)."""
        t0 = time.perf_counter()
        try:
            return self._step_round()
        finally:
            self.round_wall_s += time.perf_counter() - t0
            self._publish_status()

    def _step_round(self) -> bool:
        did = self._fire_fleet_chaos()
        killed = bool(self._kills.get(self.rounds))
        for eid in self._kills.pop(self.rounds, ()):
            self.kill_engine(eid)
        did = did or killed
        # rolling deploys fire on the same round clock as kills, AFTER
        # them (a deploy never drains onto an engine the same round is
        # about to kill) and BEFORE any engine steps, so the deploy's
        # drain sees the round's pre-step truth
        dep = self._deploys.pop(self.rounds, None)
        if dep is not None:
            self.rolling_deploy(dep[0], step=dep[1])
            did = True
        if self._poll_deploy_watch():
            did = True
        stepping, idle = [], []
        for h in self.handles:
            (stepping if h.has_work else idle).append(h)
        for h in stepping:
            if not h.alive:
                continue
            try:
                h.step_begin(prefill_only=(h.role == "prefill"))
            except TransportError as e:
                self._transport_death(h, e)
                did = True
        # async live migration phase 2 (round 22): ship pending
        # documents NOW, between the step fan-out and the collect —
        # the stage RPCs queue behind each worker's in-flight step, so
        # the whole fleet decodes while the KV crosses the wire
        if self._pending_moves:
            self._ship_pending_moves()
        for h in stepping:
            if not h.alive:
                continue
            try:
                did = h.step_end() or did
            except TransportError as e:
                self._transport_death(h, e)
                did = True
        # heartbeat liveness: members with no work this round still
        # answer a cheap ping (short deadline) — a dead IDLE worker is
        # declared now, not discovered when the router finally needs it
        # (it may hold finished results only its snapshot remembers)
        for h in idle:
            if not h.alive:
                continue
            try:
                h.ping()
            except TransportError as e:
                self._transport_death(h, e)
        before = self.handoffs + self.migrations
        self._handoff_completed_prefills()
        self._migrate_pool_pressure()
        # async live migration phase 3: settle every shipped move
        # (finish_export evicts on the source; the staged doc commits
        # with its ship-window delta patched in — one teacher-forced
        # catch-up on the target, zero source stall)
        if self._pending_moves:
            self._commit_pending_moves()
        did = did or (self.handoffs + self.migrations > before)
        self.rounds += 1
        if self.rounds % self.snapshot_every == 0:
            for h in self.handles:
                if h.alive:
                    h.snapshot = h.fetch_snapshot()
        # one fleet health record per round (schema v9): the
        # per-engine balance view the SLO/autoscaling layer reads.
        # ``step`` is the post-round clock — record N describes the
        # fleet after N rounds.
        if self.metrics is not None:
            self.metrics.fleet(self._fleet_record())
        return did

    def _placement_target(self, prompt_len: int, max_new: int,
                          exclude=()) -> EngineHandle | None:
        cands = [h for h in self.alive_handles("decode")
                 if h.id not in exclude
                 and self._has_capacity(h, prompt_len, max_new)]
        return min(cands, key=self._load_key) if cands else None

    def _move(self, source: EngineHandle, target: EngineHandle,
              uid: int):
        """One LIVE sequence move (export -> serialize/ship -> verify
        -> import), instrumented: returns ``(ref, blocks, bytes,
        duration_s, transport)`` and feeds the migration-stall
        accumulators. ``transport`` is the schema-v10 attribution
        ({mode, bytes, crc_verify_s, retries}); a CRC/torn/version
        rejection raises ``WireError`` with the target engine
        untouched (import validates before it allocates)."""
        t0 = time.perf_counter()
        ref = source.export(uid)
        if self._corrupt_next_wire and ref.path is not None:
            _corrupt_wire_file(ref.path)
            self._corrupt_next_wire = False
        try:
            if (getattr(source, "family", None) == "tcp"
                    or getattr(target, "family", None) == "tcp"):
                # the spool is (notionally) not shared across hosts:
                # stream the doc over the framed side channel instead
                # of handing the target a path it could not open
                data = source.fetch_wire(ref.path)
                st = target.stage_bytes(data)
                target.commit_import(uid)
                info = {"mode": "tcp", "bytes": st["bytes"],
                        "crc_verify_s": st["crc_verify_s"]}
            else:
                info = target.import_doc(ref)  # WireError on damage
        except WireError:
            # keep the damaged file for post-mortem — renamed so it can
            # never be re-consumed, pruned past keep_rejected so a
            # rejection loop can't grow the spool unboundedly (the
            # checkpoint keep_last stance, applied to the wire spool)
            if ref.path is not None:
                _retain_rejected(ref.path, self.keep_rejected)
            raise
        dur = time.perf_counter() - t0
        blocks = ref.blocks_written
        # an in-process doc move reports the SERIALIZED size too (the
        # satellite: bytes = what would cross a boundary, never the
        # nbytes sum) — computed HERE, outside the timed window, so the
        # floor's stall numbers don't include a serialization the
        # in-process transport never performs
        nbytes = (int(info["bytes"]) if "bytes" in info
                  else wire.doc_wire_bytes(ref.doc))
        self.handoff_blocks += blocks
        self.handoff_bytes += nbytes
        self.handoff_durations.append(dur)
        transport = {"mode": info["mode"], "bytes": nbytes,
                     "crc_verify_s": info.get("crc_verify_s"),
                     "retries": self._uid_wire_rejects.get(uid, 0)}
        return ref, blocks, nbytes, dur, transport

    def _replay_transport(self, uid: int) -> dict:
        """The transport attribution for a replay-migration: no KV
        ships (the source pool is unreachable or its export was
        rejected), so bytes are honestly 0 and the replay length on
        the record names the catch-up cost instead."""
        return {"mode": "replay", "bytes": 0, "crc_verify_s": None,
                "retries": self._uid_wire_rejects.get(uid, 0)}

    # -- async live migration (round 22, DESIGN.md section 28) ---------

    def _start_move(self, source, target, uid: int,
                    reason: str) -> None:
        """Phase 1 (end of round N): snapshot the sequence to the
        wire WITHOUT evicting (``export_keep``) — the source keeps
        decoding it through the whole ship window. Phases 2/3 run
        inside round N+1 (``_ship_pending_moves`` between the step
        fan-out and collect; ``_commit_pending_moves`` after)."""
        ref = source.export_keep(uid)
        if self._corrupt_next_wire and ref.path is not None:
            _corrupt_wire_file(ref.path)
            self._corrupt_next_wire = False
        self._pending_moves[uid] = {
            "uid": uid, "source": source, "target": target,
            "ref": ref, "reason": reason, "stage": None,
            "t0": time.perf_counter(), "state": "exported"}

    def _ship_pending_moves(self) -> None:
        """Phase 2: stage each exported document on its target while
        every worker decodes its in-flight step. Failures here abort
        with the SOURCE UNDISTURBED — nothing was evicted yet, so a
        corrupt ship costs one ``wire_rejected`` record and the
        request never stops decoding (no replay, no reroute)."""
        for uid, mv in list(self._pending_moves.items()):
            if mv["state"] != "exported":
                continue
            source, target, ref = mv["source"], mv["target"], mv["ref"]
            if not source.alive or not target.alive:
                self._abort_move(mv, "member died before ship")
                continue
            try:
                if (getattr(source, "family", None) == "tcp"
                        or getattr(target, "family", None) == "tcp"):
                    # the spool is (notionally) not shared across
                    # hosts: stream source spool -> router -> target
                    # over the sockets' framed side channel
                    data = source.fetch_wire(ref.path)
                    mv["stage"] = target.stage_bytes(data)
                else:
                    mv["stage"] = target.stage_ref(ref)
            except WireError as e:
                self.wire_rejects += 1
                self._uid_wire_rejects[uid] = \
                    self._uid_wire_rejects.get(uid, 0) + 1
                self._record("wire_rejected", uid, source=source.id,
                             target=target.id, reason=str(e))
                self._event({"event": "wire_rejected",
                             "uid": int(uid), "source": source.id,
                             "target": target.id,
                             "context": "async_ship",
                             "reason": str(e)})
                if ref.path is not None:
                    _retain_rejected(ref.path, self.keep_rejected)
                del self._pending_moves[uid]
                continue
            except TransportError as e:
                # the failing member's own step collect declares the
                # death; the move dissolves (the source still owns
                # the request and its snapshot still lists it)
                self._abort_move(mv, f"{type(e).__name__}: {e}")
                continue
            mv["state"] = "staged"

    def _abort_move(self, mv: dict, why: str) -> None:
        """Dissolve one pending move with the source outcome standing
        (it never evicted); drop any staged doc on the target."""
        uid = mv["uid"]
        if mv.get("stage") is not None and mv["target"].alive:
            try:
                mv["target"].discard_stage(uid)
            except (TransportError, ValueError):
                pass
        self._event({"event": "move_aborted", "uid": int(uid),
                     "source": mv["source"].id,
                     "target": mv["target"].id, "reason": why})
        self._pending_moves.pop(uid, None)

    def _drop_pending_moves(self, h) -> None:
        """A dying member dissolves every pending move it touches: as
        the SOURCE the sequence stayed resident through the ship
        window so the snapshot replay recovers it; as the TARGET the
        source still owns it — either way nothing is lost."""
        for uid, mv in list(self._pending_moves.items()):
            if mv["source"] is h or mv["target"] is h:
                self._abort_move(mv, f"member {h.id} died mid-move")

    def _commit_pending_moves(self) -> None:
        """Phase 3 (after the round's collect): settle every shipped
        move. ``finish_export`` evicts on the source and returns the
        FINAL token list; the staged doc commits with that list
        patched in — ``emitted`` stays at the ship point, so the
        target's engine teacher-forces exactly the ship-window delta
        (the one replay the moving request pays). An abort status
        (finished/failed/preempted mid-ship) just discards the stage.
        The recorded ``duration_s`` is the commit stall alone — the
        ship wall is ``ship_s``, overlapped with decoding by
        construction."""
        for uid, mv in list(self._pending_moves.items()):
            if mv["state"] != "staged":
                continue
            source, target = mv["source"], mv["target"]
            del self._pending_moves[uid]
            if not source.alive or not target.alive:
                self._abort_move({**mv}, "member died before commit")
                continue
            t_commit = time.perf_counter()
            try:
                delta = source.finish_export(uid)
            except TransportError:
                continue    # the source's death is being declared
            if delta.get("status") != "resident":
                try:
                    target.discard_stage(uid)
                except (TransportError, ValueError):
                    pass
                self._event({"event": "move_aborted", "uid": int(uid),
                             "source": source.id, "target": target.id,
                             "reason": (f"request "
                                        f"{delta.get('status')} "
                                        "during ship window")})
                continue
            try:
                info = target.commit_import(uid, out=delta["out"])
            except TransportError as e:
                self._transport_death(target, e)
                self._resume_from_delta(source, uid, delta,
                                        mv["reason"])
                continue
            except (WireError, ValueError, RuntimeError):
                self._resume_from_delta(source, uid, delta,
                                        mv["reason"])
                continue
            dur = time.perf_counter() - t_commit
            ship_s = time.perf_counter() - mv["t0"]
            ref, st = mv["ref"], mv["stage"]
            blocks = ref.blocks_written
            nbytes = int(st["bytes"]) or (
                wire.doc_wire_bytes(ref.doc)
                if ref.doc is not None else 0)
            self.handoff_blocks += blocks
            self.handoff_bytes += nbytes
            self.handoff_durations.append(dur)
            self.migrations += 1
            req = self.requests[uid]
            req["engine"] = target.id
            if req.get("session") is not None:
                self._sessions[req["session"]] = target.id
            self._record(
                "migrated", uid, source=source.id, target=target.id,
                reason=mv["reason"], position=int(delta["position"]),
                blocks=blocks, bytes=nbytes,
                duration_s=round(dur, 6), ship_s=round(ship_s, 6),
                catchup_tokens=int(info["catchup_tokens"]),
                transport={"mode": st["mode"], "bytes": nbytes,
                           "crc_verify_s": st.get("crc_verify_s"),
                           "retries": self._uid_wire_rejects.get(
                               uid, 0)})
            # the handoff snapshot-refresh discipline: neither side's
            # stale snapshot may lose or resurrect the moved request
            source.snapshot = source.fetch_snapshot()
            target.snapshot = target.fetch_snapshot()

    def _resume_from_delta(self, source, uid: int, delta: dict,
                           reason: str) -> None:
        """Commit fallback: the source already evicted, so the only
        correct continuation is a replay-resume from the FINAL token
        list ``finish_export`` returned — the full-catch-up
        degenerate case of the same teacher-forcing contract."""
        req = self.requests[uid]
        entry = None
        if source.snapshot is not None:
            entry = next((r for r in source.snapshot["requests"]
                          if int(r["uid"]) == uid), None)
        cands = [h for h in self.alive_handles("decode")
                 if h.id != source.id] or self.alive_handles("decode")
        dest = min(cands, key=self._load_key)
        t0 = time.perf_counter()
        dest.resume_request(
            uid, req["prompt"], req["max_new"], out=delta["out"],
            retries=(entry or {}).get("retries", 0),
            t_submit=(entry or {}).get("t_submit"),
            t_first=(entry or {}).get("t_first"),
            weights_version=(entry or {}).get("weights_version"),
            trace=req.get("trace"), tenant=req.get("tenant"))
        dur = time.perf_counter() - t0
        self.migrations += 1
        req["engine"] = dest.id
        if req.get("session") is not None:
            self._sessions[req["session"]] = dest.id
        self._record("migrated", uid, source=source.id,
                     target=dest.id, reason=f"{reason}_commit_failed",
                     replay=len(delta["out"]), blocks=0, bytes=0,
                     duration_s=round(dur, 6),
                     transport=self._replay_transport(uid))
        source.snapshot = source.fetch_snapshot()
        dest.snapshot = dest.fetch_snapshot()

    def _wire_rejected(self, source: EngineHandle, target: EngineHandle,
                       uid: int, err: WireError, context: str,
                       exclude=()) -> None:
        """A wire handoff failed integrity checks: record the named
        reason, then re-route the request by REPLAY from the source's
        last router-held snapshot (export already evicted it there —
        the stale snapshot still lists the request with its emitted
        tokens, and replay from ANY out-prefix regenerates the same
        continuation, so token identity survives the rejected file).
        The target engine was never touched (import validates before
        it allocates) and remains a legitimate replay destination."""
        self.wire_rejects += 1
        self._uid_wire_rejects[uid] = \
            self._uid_wire_rejects.get(uid, 0) + 1
        self._record("wire_rejected", uid, source=source.id,
                     target=target.id, reason=str(err))
        self._event({"event": "wire_rejected", "uid": int(uid),
                     "source": source.id, "target": target.id,
                     "context": context, "reason": str(err)})
        entry = None
        if source.snapshot is not None:
            entry = next((r for r in source.snapshot["requests"]
                          if int(r["uid"]) == uid), None)
        req = self.requests[uid]
        cands = [h for h in self.alive_handles("decode")
                 if h.id not in exclude]
        dest = min(cands or self.alive_handles("decode"),
                   key=self._load_key)
        t0 = time.perf_counter()
        if entry is not None:
            dest.resume_request(uid, entry["prompt"], entry["max_new"],
                                out=entry["out"],
                                retries=entry["retries"],
                                t_submit=entry.get("t_submit"),
                                t_first=entry.get("t_first"),
                                weights_version=entry.get(
                                    "weights_version"),
                                trace=entry.get("trace_id",
                                                req.get("trace")),
                                tenant=entry.get("tenant",
                                                 req.get("tenant")))
            replay = len(entry["out"])
        else:
            # no snapshot entry (a submit-then-immediate-move corner):
            # replay from the request book — more catch-up, same tokens
            dest.resume_request(uid, req["prompt"], req["max_new"],
                                trace=req.get("trace"),
                                tenant=req.get("tenant"))
            replay = 0
        dur = time.perf_counter() - t0
        req["engine"] = dest.id
        if req.get("session") is not None:
            # the reroute moved the session's KV locality with it — a
            # stale affinity entry would split the session across two
            # live engines (the success-path handoff updates it too)
            self._sessions[req["session"]] = dest.id
        self.migrations += 1
        self._record("migrated", uid, source=source.id, target=dest.id,
                     reason="wire_rejected", replay=replay, blocks=0,
                     bytes=0, duration_s=round(dur, 6),
                     transport=self._replay_transport(uid))
        # the uid is gone from the source engine (export evicted it):
        # refresh its snapshot so a later death can't resurrect it, and
        # the destination's so a later death can't lose it
        source.snapshot = source.fetch_snapshot()
        dest.snapshot = dest.fetch_snapshot()

    def _handoff_completed_prefills(self) -> None:
        """Ship every fully-prefilled sequence off the prefill tier.
        No decode capacity right now -> the sequence PARKS (the
        prefill tier steps with ``prefill_only=True``, so a parked
        sequence makes no decode progress there) and the handoff is
        retried next round; a burst larger than the decode tier's
        total capacity surfaces as ``run()``'s fleet-stalled error
        rather than silently decoding on the wrong tier — tier purity
        is what the dispatch-count proof pins."""
        for ph in self.alive_handles("prefill"):
            if ph.digest(light=True)["active"] < 1:
                continue        # nothing resident, nothing to ship
            ready = [s["uid"] for s in ph.digest()["slots"]
                     if s["prompt_done"]]
            for uid in ready:
                req = self.requests[uid]
                target = self._placement_target(len(req["prompt"]),
                                                req["max_new"])
                if target is None:
                    continue
                try:
                    ref, blocks, nbytes, dur, transport = \
                        self._move(ph, target, uid)
                except WireError as e:
                    self._wire_rejected(ph, target, uid, e,
                                        context="handoff")
                    continue
                self.handoffs += 1
                req["engine"] = target.id
                if req["session"] is not None:
                    self._sessions[req["session"]] = target.id
                self._record("handoff", uid, source=ph.id,
                             target=target.id, reason="prefill_done",
                             position=ref.position, blocks=blocks,
                             bytes=nbytes, duration_s=round(dur, 6),
                             transport=transport)
                # refresh BOTH snapshots now: a kill before the next
                # cadence snapshot must neither lose the moved request
                # (target's snapshot predates it) nor resurrect it on
                # the source (whose stale snapshot still lists it)
                ph.snapshot = ph.fetch_snapshot()
                target.snapshot = target.fetch_snapshot()

    def _migrate_pool_pressure(self) -> None:
        """A starved engine (head-of-line waiter has a free slot but
        not its block reservation) moves its YOUNGEST fully-prefilled
        running sequence to a peer with capacity — a LIVE handoff, no
        replay. The same victim policy as the engine's own preemption
        (the oldest resident keeps making progress), but the victim
        keeps running instead of losing its KV."""
        for h in self.alive_handles("decode"):
            # light digest for the steady-state early exits; the
            # per-slot list is only materialized in the rare
            # pool-starved case that actually picks a victim
            d = h.digest(light=True)
            if not d["waiting"] or d["free_slots"] < 1:
                continue                    # idle, or slot-starved
            head = d["head"]
            need = h.blocks_needed(head["prompt_len"], head["max_new"])
            if need <= d["free_blocks"] + d["evictable"]:
                continue                    # admission will take it
            victims = [(s["admit_index"], s["uid"], s["prompt_len"],
                        s["max_new"])
                       for s in h.digest()["slots"]
                       if s["prompt_done"]
                       and s["uid"] not in self._pending_moves]
            if not victims:
                continue
            _, uid, plen, mnew = max(victims)
            target = self._placement_target(plen, mnew,
                                            exclude=(h.id,))
            if target is None:
                continue
            if self.async_migration:
                # async live migration: snapshot now, ship during the
                # next round's decode step, commit after its collect —
                # the source never stalls on the wire
                self._start_move(h, target, uid,
                                 reason="pool_pressure")
                continue
            try:
                ref, blocks, nbytes, dur, transport = \
                    self._move(h, target, uid)
            except WireError as e:
                self._wire_rejected(h, target, uid, e,
                                    context="pool_pressure")
                continue
            self.migrations += 1
            self.requests[uid]["engine"] = target.id
            self._record("migrated", uid, source=h.id,
                         target=target.id, reason="pool_pressure",
                         position=ref.position, blocks=blocks,
                         bytes=nbytes, duration_s=round(dur, 6),
                         transport=transport)
            # the handoff snapshot-refresh discipline (see above)
            h.snapshot = h.fetch_snapshot()
            target.snapshot = target.fetch_snapshot()

    # -- failure (the chaos drill's surface) ---------------------------

    def schedule_kill(self, engine_id: str, at_round: int) -> None:
        """Arm a deterministic engine kill at the START of fleet round
        ``at_round`` (the round's snapshot cadence has NOT yet run —
        the last snapshot honestly lags by up to ``snapshot_every``
        rounds, and replay fills exactly that gap). Under the process
        transport this is a REAL SIGKILL of the worker process."""
        if engine_id not in self.by_id:
            raise ValueError(f"unknown engine id {engine_id!r} "
                             f"(fleet: {sorted(self.by_id)})")
        if at_round < 0:
            raise ValueError(f"kill round must be >= 0, got {at_round}")
        self._kills[at_round].append(engine_id)

    def _transport_death(self, h: EngineHandle, err: Exception) -> None:
        """The liveness ladder's verdict: a worker stopped answering
        (deadline + bounded-backoff retries exhausted, or its
        connection dropped). Declare it dead — SIGKILL the process so a
        zombie can't answer a stale request later — and migrate its
        requests from the last snapshot, exactly the kill path."""
        self._event({"event": "worker_dead", "engine": h.id,
                     "round": self.rounds,
                     "reason": f"{type(err).__name__}: {err}"})
        # the router's OWN evidence, dumped BEFORE the SIGKILL closes
        # the book: the dead worker's flight recorder died with it —
        # this is the half of the post-mortem only the router holds
        self._dump_router_postmortem(
            h, f"{type(err).__name__}: {err}")
        h.kill()
        h.killed_at_round = self.rounds
        self.kills += 1
        self._event({"event": "engine_killed", "engine": h.id,
                     "round": self.rounds})
        self._drop_pending_moves(h)
        self._recover_dead(h)

    def kill_engine(self, engine_id: str) -> int:
        """Kill one engine NOW and migrate its in-flight requests to
        the survivors from its last snapshot: finished/failed results
        ride over verbatim, every live request re-enters a survivor's
        queue for replay-resume (``resume_request`` — prompt
        re-prefilled, recorded tokens teacher-forced, so the rebuilt KV
        write history and the remaining tokens are bit-identical to the
        uninterrupted run's). Returns the number of migrated requests.
        In-process the engine object is dropped; under the process
        transport the worker is SIGKILLed — a real dead host either
        way, its pool unreachable."""
        h = self.by_id.get(engine_id)
        if h is None:
            raise ValueError(f"unknown engine id {engine_id!r}")
        if not h.alive:
            return 0
        # same evidence discipline as the liveness-ladder death: the
        # worker's own flight recorder is about to become unreachable
        self._dump_router_postmortem(h, "engine killed (scheduled "
                                        "kill / chaos)")
        h.kill()
        h.killed_at_round = self.rounds
        self.kills += 1
        self._event({"event": "engine_killed", "engine": h.id,
                     "round": self.rounds})
        self._drop_pending_moves(h)
        return self._recover_dead(h)

    def _recover_dead(self, h: EngineHandle) -> int:
        """Migrate a dead member's requests off its last router-held
        snapshot (replay-resume on survivors)."""
        snap = h.snapshot
        if snap is None:
            return 0
        self._dead_finished.update(
            {int(u): list(t) for u, t in snap["finished"].items()})
        self._dead_failed.update(
            {int(u): dict(i) for u, i in snap["failed"].items()})
        # a dead prefill engine's queue re-enters the prefill tier
        # while one exists (tier purity survives the kill); decode
        # requests always land on decode survivors
        survivors = (self.alive_handles("prefill")
                     if h.role == "prefill" else [])
        survivors = survivors or self.alive_handles("decode")
        if not survivors:
            raise RuntimeError("last decode engine killed: the fleet "
                               "has nowhere to migrate its requests")
        moved = 0
        for req in snap["requests"]:
            target = min(survivors, key=self._load_key)
            t0 = time.perf_counter()
            target.resume_request(
                req["uid"], req["prompt"], req["max_new"],
                out=req["out"], retries=req["retries"],
                t_submit=req.get("t_submit"),
                t_first=req.get("t_first"),
                weights_version=req.get("weights_version"),
                trace=req.get("trace_id", self.requests.get(
                    int(req["uid"]), {}).get("trace")),
                tenant=req.get("tenant", self.requests.get(
                    int(req["uid"]), {}).get("tenant")))
            dur = time.perf_counter() - t0
            self.requests[int(req["uid"])]["engine"] = target.id
            # a replay-migration ships no KV (the dead pool is
            # unreachable): blocks/bytes are honestly 0 and the replay
            # length names the catch-up cost instead; duration_s here
            # is the re-queue cost only — the replay itself shows up
            # in the request's own span stream
            self._record("migrated", req["uid"], source=h.id,
                         target=target.id, reason="engine_killed",
                         replay=len(req["out"]), blocks=0, bytes=0,
                         duration_s=round(dur, 6),
                         transport=self._replay_transport(
                             int(req["uid"])))
            # a survivor dying right after must re-migrate this too
            target.snapshot = target.fetch_snapshot()
            moved += 1
        self.migrations += moved
        return moved

    # -- elastic membership (round 20, DESIGN.md section 26) -----------

    def next_decode_eid(self) -> str:
        """Mint the next decode engine id. Spawned members continue
        the e-numbering and ids are NEVER reused — a retired e1 keeps
        its slot in the book and its replacement is e2, so every
        record ever written still names a unique member."""
        eid = f"{DECODE_PREFIX}{self._decode_serial}"
        self._decode_serial += 1
        return eid

    def add_engine(self, handle) -> None:
        """Admit one WARMED decode member into the live fleet (the
        autoscaler's scale-up half). The construction-time gates apply
        unchanged — model identity against the incumbents, the
        single-device membership check, and serving-version agreement
        — so an elastic join can never relax what ``__init__``
        enforces. The joining engine must already be warm
        (``EngineHandle.warm``): admission is instant and the next
        round routes to it."""
        if handle.id in self.by_id:
            raise ValueError(f"engine id {handle.id!r} already in the "
                             "fleet (ids are never reused)")
        if handle.role != "decode":
            raise ValueError("elastic members are decode-tier only "
                             f"(got role {handle.role!r})")
        incumbent = next((h for h in self.handles if h.alive), None)
        if incumbent is not None:
            if handle.model_meta() != incumbent.model_meta():
                raise ValueError(
                    "joining engine disagrees on model identity — "
                    "every replica must serve the same weights")
            fleet_v = self._fleet_serving_version()
            join_v = int(handle.digest(light=True)["serving_version"])
            if join_v != fleet_v:
                raise ValueError(
                    f"joining engine serves weights version {join_v} "
                    f"but the fleet serves {fleet_v} — load the "
                    "current checkpoint before add_engine")
        handle.validate_member()
        if hasattr(handle, "on_reconnect"):
            handle.on_reconnect = self._note_reconnect
        self.handles.append(handle)
        self.by_id[handle.id] = handle
        # the step-0 snapshot discipline: a kill before the first
        # cadence snapshot must still know this member's requests
        handle.snapshot = handle.fetch_snapshot()

    def retire_engine(self, engine_id: str) -> int:
        """Remove one decode member from the live fleet with ZERO shed
        (the autoscaler's scale-down half): drain it through the
        rolling-deploy primitive — live residents ship their KV to
        peers, everything else replay-resumes, nothing touches a queue
        limit — then close its transport gracefully. The handle stays
        in ``handles`` marked ``retired`` (distinct from dead: no kill
        round, nothing to post-mortem). Returns the number of drained
        requests. Refuses to retire the last alive decode engine —
        the min-floor is the controller's invariant, this is the
        router's own."""
        h = self.by_id.get(engine_id)
        if h is None:
            raise ValueError(f"unknown engine id {engine_id!r}")
        if not h.alive:
            raise ValueError(f"engine {engine_id!r} is not alive")
        if h.role != "decode":
            raise ValueError("only decode members retire (the "
                             "prefill tier is static)")
        if len(self.alive_handles("decode")) <= 1:
            raise ValueError("refusing to retire the only alive "
                             "decode engine (scale-to-zero is "
                             "structurally impossible)")
        drained = self._drain_engine(h)
        h.close()
        h.alive = False
        h.retired = True
        # a drained book must never resurrect requests the peers now
        # hold — retirement is not a death, there is nothing to
        # migrate from
        h.snapshot = None
        if h.transport == "inproc":
            h.engine = None     # release the pool, like a dead host's
        return drained

    # -- live weight hot-swap (round 17, DESIGN.md section 23) ---------

    def schedule_deploy(self, ckpt_dir: str, at_round: int,
                        step: int | None = None) -> None:
        """Arm a rolling deploy at the START of fleet round
        ``at_round``: the newest published step under ``ckpt_dir``
        (or the explicit ``step``) is verified by the CRC ladder and
        rolled through the fleet engine by engine — drain by
        migration, swap, re-admit. Fires after that round's kills (a
        deploy never drains onto an engine the round kills) and
        before any engine steps."""
        if at_round < 0:
            raise ValueError(f"deploy round must be >= 0, got "
                             f"{at_round}")
        if at_round in self._deploys:
            raise ValueError(f"a deploy is already scheduled for "
                             f"round {at_round}")
        self._deploys[at_round] = (ckpt_dir, step)

    def deploy_watch(self, ckpt_dir: str, poll_every_s: float) -> None:
        """Arm the deploy-on-publish watcher: poll ``ckpt_dir``'s
        ``latest_verified`` every ``poll_every_s`` seconds of wall
        clock (between rounds — the poll is a directory listing plus a
        CRC ladder, never on the per-step hot path) and roll the fleet
        forward whenever it advances past the current serving version.
        The trainer's existing atomic publish IS the trigger: publish a
        checkpoint mid-serve and the fleet takes it with zero shed (the
        ``rolling_deploy`` contract, CRC rollback included)."""
        if poll_every_s <= 0:
            raise ValueError(f"deploy_watch poll cadence must be > 0, "
                             f"got {poll_every_s}")
        self._watch = (ckpt_dir, float(poll_every_s))
        self._watch_t_last = 0.0

    def _poll_deploy_watch(self) -> bool:
        """The watcher's per-round check (throttled): a verified step
        newer than the fleet's serving version triggers a rolling
        deploy NOW. Runs after scheduled deploys so an explicit
        ``schedule_deploy`` always wins its round."""
        if self._watch is None:
            return False
        ckpt_dir, every = self._watch
        now = time.monotonic()
        if now - self._watch_t_last < every:
            return False
        self._watch_t_last = now
        from ..runtime.weights import VersionLedger
        newest = VersionLedger(ckpt_dir).latest_verified()
        if newest is None or newest <= self._fleet_serving_version():
            return False
        self.rolling_deploy(ckpt_dir, step=newest)
        return True

    def _deploy_record(self, event: str, from_v, to_v, **extra) -> None:
        """One schema-v11 ``deploy`` record (started / engine_swapped
        / completed / rolled_back) on the router's own stream."""
        if self.metrics is not None:
            self.metrics.deploy({"step": self.rounds, "event": event,
                                 "from_version": from_v,
                                 "to_version": to_v, **extra})

    def _rollback_swapped(self, swapped, from_v: int) -> None:
        """Flip already-swapped engines back to ``from_v`` — guarded:
        a SECOND worker dying during the rollback must not let the
        exception escape with no rolled_back record and the fleet
        mixed (a dead engine isn't mixed; it takes the ordinary
        dead-host path — declare, SIGKILL, migrate-from-snapshot)."""
        for s in swapped:
            if not s.alive:
                continue
            try:
                s.set_serving_version(from_v)
            except TransportError as e:
                self._transport_death(s, e)

    def _find_dead(self, suspect) -> "EngineHandle":
        """Which alive handle actually stopped answering? Ping sweep,
        the suspect first (cheap short-deadline heartbeat, the idle-
        member liveness probe); falls back to the suspect when every
        ping answers (a transient that already cleared — declaring
        the suspect dead is then the conservative verdict)."""
        order = [suspect] + [x for x in self.handles
                             if x.alive and x is not suspect]
        for cand in order:
            if not cand.alive:
                continue
            try:
                cand.ping()
            except TransportError:
                return cand
        return suspect

    def _fleet_serving_version(self) -> int:
        vers = sorted({int(h.digest(light=True)["serving_version"])
                       for h in self.handles if h.alive})
        if len(vers) != 1:
            raise RuntimeError(
                f"fleet engines disagree on serving version ({vers}) "
                "— an aborted deploy left a mixed fleet behind")
        return vers[0]

    def rolling_deploy(self, ckpt_dir: str,
                       step: int | None = None) -> dict:
        """Publish new weights into the serving fleet with ZERO shed
        and zero restarts: for each engine in turn, DRAIN it (every
        fully-prefilled resident ships to a peer over the existing KV
        handoff — the PR 10 primitive IS the drain; waiting and
        mid-prefill requests move by replay-resume), swap its weights
        to the ledger-verified target version, and re-admit it. The
        fleet serves BOTH versions mid-deploy: drained requests keep
        their ``weights_version`` pin and finish on the old weights
        wherever they land (every engine double-buffers the old
        version), while new admissions pin the new one.

        Failure is first-class: a target step the CRC ladder rejects —
        or any load failure mid-roll, including a worker dying — rolls
        EVERY already-swapped engine back to the old serving version
        (its weights never left) and emits one ``rolled_back`` deploy
        record whose reason is the one-line named cause plus the
        ``latest_verified_step`` fallback: deploy aborted, no engine
        left mixed, nothing shed."""
        from ..checkpoint import CorruptCheckpointError
        from ..runtime.weights import VersionLedger
        t0 = time.perf_counter()
        ledger = VersionLedger(ckpt_dir)
        from_v = self._fleet_serving_version()
        if self._corrupt_next_deploy is not None:
            # chaos corrupt_deploy: tear the target checkpoint BEFORE
            # the ledger reads it — the CRC ladder must reject it
            frac = self._corrupt_next_deploy
            self._corrupt_next_deploy = None
            tgt = step if step is not None else ledger.latest_step()
            if tgt is not None:
                from ..runtime.chaos import truncate_checkpoint
                truncate_checkpoint(ledger.step_path(tgt), frac=frac)
        target = step if step is not None else ledger.latest_step()

        def rolled_back(reason: str) -> dict:
            import sys
            self.deploy_rollbacks += 1
            fb = ledger.latest_verified()
            line = (f"deploy of step_{target} rolled back: {reason} — "
                    f"fleet stays on version {from_v} (latest "
                    f"verified step: {fb})")
            # the operator-visible one-liner (the checkpoint layer's
            # stderr-notice precedent); the durable copy is the
            # ``rolled_back`` deploy record below
            print(f"fleet: {line}", file=sys.stderr)
            self._deploy_record(
                "rolled_back", from_v, target, reason=line,
                latest_verified=fb,
                duration_s=round(time.perf_counter() - t0, 6))
            self._event({"event": "deploy_rolled_back",
                         "round": self.rounds, "from_version": from_v,
                         "to_version": target, "reason": line})
            return {"status": "rolled_back", "reason": line,
                    "from_version": from_v, "to_version": target,
                    "latest_verified": fb}

        if target is None:
            return rolled_back(
                f"no checkpoint published under {ckpt_dir}")
        ok, why = ledger.verify(target)
        if not ok:
            return rolled_back(f"checkpoint step_{target} rejected "
                               f"({why})")
        if target == from_v:
            return {"status": "noop", "from_version": from_v,
                    "to_version": target}
        self._deploy_record("started", from_v, target,
                            ckpt_dir=ckpt_dir)
        params = None
        swapped: list = []
        drained_total = 0
        h = None
        try:
            for h in [x for x in self.handles if x.alive]:
                if h.transport != "process" and params is None:
                    # in-process: the router loads the checkpoint ONCE
                    # and shares the (read-only, never-donated) params
                    # across replicas; process workers restore from
                    # the shared dir themselves — weights never ride
                    # the socket
                    params = ledger.load(target, h.engine.params)
                drained_total += self._drain_engine(h)
                t1 = time.perf_counter()
                h.load_weights(target, ckpt_dir, target, params=params)
                h.set_serving_version(target)
                swapped.append(h)
                self._deploy_record(
                    "engine_swapped", from_v, target, engine=h.id,
                    duration_s=round(time.perf_counter() - t1, 6))
                h.snapshot = h.fetch_snapshot()
        except TransportError as e:
            # the drain touches PEERS too (imports, resumes) — blame
            # the handle that actually stopped answering, not the one
            # being drained: a misattributed death would SIGKILL a
            # healthy worker and leave the real corpse marked alive
            dead = self._find_dead(h)
            self._transport_death(dead, e)
            self._rollback_swapped(swapped, from_v)
            return rolled_back(
                f"worker {dead.id} died mid-deploy "
                f"({type(e).__name__}: {e}); {len(swapped)} swapped "
                "engine(s) rolled back")
        except (CorruptCheckpointError, ValueError, RuntimeError,
                OSError) as e:
            # the mid-roll failure path: engines already swapped flip
            # their serving version back (the old weights never left —
            # that IS the double buffer), so no engine admits on a
            # version the fleet just refused
            self._rollback_swapped(swapped, from_v)
            return rolled_back(
                f"{type(e).__name__}: {e}; {len(swapped)} swapped "
                "engine(s) rolled back")
        self.deploys += 1
        dur = round(time.perf_counter() - t0, 6)
        self._deploy_record("completed", from_v, target,
                            duration_s=dur, engines=len(swapped),
                            drained=drained_total)
        return {"status": "completed", "from_version": from_v,
                "to_version": target, "engines": len(swapped),
                "drained": drained_total, "duration_s": dur}

    def _drain_engine(self, h) -> int:
        """Empty one engine for its swap: fully-prefilled residents
        move LIVE (export -> import, KV ships, zero replay) to a
        decode peer with capacity; everything else — waiting,
        mid-prefill, or no peer capacity — moves by replay-resume
        (``release_request`` + a peer's ``resume_request``, pin
        attached). Nothing is shed: replay-resume bypasses queue
        limits exactly as kill-migration does. With no alive peer the
        engine swaps IN PLACE — the double-buffered pins keep its
        in-flight requests on their own version regardless."""
        peers = [p for p in self.handles if p.alive and p is not h]
        if not peers:
            return 0
        snap = h.fetch_snapshot()
        h.snapshot = snap
        moved = 0
        for req in snap["requests"]:
            uid = int(req["uid"])
            live = (req.get("state") == "RUNNING"
                    and req.get("prefilled", 0) >= len(req["prompt"]))
            if live:
                target = self._placement_target(
                    len(req["prompt"]), req["max_new"],
                    exclude=(h.id,))
                if target is not None:
                    try:
                        ref, blocks, nbytes, dur, transport = \
                            self._move(h, target, uid)
                    except WireError as e:
                        self._wire_rejected(h, target, uid, e,
                                            context="deploy_drain",
                                            exclude=(h.id,))
                        moved += 1
                        continue
                    self.migrations += 1
                    book = self.requests[uid]
                    book["engine"] = target.id
                    if book.get("session") is not None:
                        self._sessions[book["session"]] = target.id
                    self._record("migrated", uid, source=h.id,
                                 target=target.id,
                                 reason="deploy_drain",
                                 position=ref.position, blocks=blocks,
                                 bytes=nbytes,
                                 duration_s=round(dur, 6),
                                 transport=transport)
                    # refresh BOTH sides per move (the handoff
                    # discipline): a death later in this drain must
                    # neither lose the moved request nor resurrect it
                    # from the source's drain-start snapshot
                    h.snapshot = h.fetch_snapshot()
                    target.snapshot = target.fetch_snapshot()
                    moved += 1
                    continue
            # replay drain (tier-preserving: prefill work re-enters
            # the prefill tier while one exists)
            entry = h.release_request(uid)
            survivors = ([p for p in peers if p.role == h.role]
                         or [p for p in peers if p.role == "decode"]
                         or peers)
            dest = min(survivors, key=self._load_key)
            t1 = time.perf_counter()
            dest.resume_request(
                uid, entry["prompt"], entry["max_new"],
                out=entry["out"], retries=entry["retries"],
                t_submit=entry.get("t_submit"),
                t_first=entry.get("t_first"),
                weights_version=entry.get("weights_version"),
                trace=entry.get("trace_id"),
                tenant=entry.get("tenant"))
            dur = time.perf_counter() - t1
            self.migrations += 1
            book = self.requests[uid]
            book["engine"] = dest.id
            if book.get("session") is not None:
                self._sessions[book["session"]] = dest.id
            self._record("migrated", uid, source=h.id, target=dest.id,
                         reason="deploy_drain",
                         replay=len(entry["out"]), blocks=0, bytes=0,
                         duration_s=round(dur, 6),
                         transport=self._replay_transport(uid))
            h.snapshot = h.fetch_snapshot()
            dest.snapshot = dest.fetch_snapshot()
            moved += 1
        return moved

    # -- drain ---------------------------------------------------------

    @property
    def has_work(self) -> bool:
        return any(h.has_work for h in self.handles)

    def _pending_kills(self) -> bool:
        scheduled = any(self.by_id[eid].alive
                        for ids in self._kills.values() for eid in ids)
        chaos = self.fleet_chaos is not None and any(
            not f.fired for f in self.fleet_chaos.faults
            if f.kind in ("kill_worker", "hang_worker"))
        return scheduled or chaos

    def run(self, log_every: int = 0) -> dict[int, list[int]]:
        """Drain the fleet: round until every request finished or
        failed (scheduled kills past the drain point are dropped — a
        dead-on-arrival fault has nothing to kill). ``log_every``
        emits one ``decode`` cadence record per engine through ITS OWN
        writer every that-many rounds (the engines are stepped
        manually, so the router owns the cadence ``DecodeEngine.run``
        normally would)."""
        while self.has_work:
            did = self.step()
            if log_every > 0 and self.rounds % log_every == 0:
                self._emit_decode_records()
            if not did and self.has_work and not self._pending_kills():
                raise RuntimeError(
                    "fleet stalled: waiting requests but no engine ran "
                    "work and no kill is pending")
        self._emit_decode_records()
        # drain-end ops-plane flush: the transport block lands on the
        # router stream and the status doc publishes FINAL (forced
        # past the throttle — a finished run's doc must say drained)
        self.emit_transport_stats()
        self._publish_status(force=True)
        return self.results()

    def _emit_decode_records(self) -> None:
        for h in self.handles:
            if not h.alive:
                continue
            try:
                h.emit_decode()
            except TransportError as e:
                self._transport_death(h, e)

    def results(self) -> dict[int, list[int]]:
        """Merged per-uid outcomes across the whole fleet, dead
        engines' pre-kill completions included. A request completed on
        a dead engine AFTER its last snapshot re-completes on a
        survivor (replay is deterministic), so the merge can never see
        two different answers for one uid."""
        out = dict(self._dead_finished)
        for h in self.handles:
            if h.alive:
                out.update(h.results())
        return out

    def failed(self) -> dict[int, dict]:
        out = dict(self._dead_failed)
        for h in self.handles:
            if h.alive:
                out.update(h.failed_map())
        return out

    # -- the payload/bench surface -------------------------------------

    def fleet_stats(self) -> dict:
        """Fleet-level counters + per-engine summaries — the generate
        CLI payload block and the bench rows' raw material."""
        per_engine = {}
        for h in self.handles:
            if not h.alive:
                per_engine[h.id] = {"alive": False,
                                    "retired": getattr(h, "retired",
                                                       False),
                                    "killed_at_round": h.killed_at_round}
                continue
            per_engine[h.id] = {"alive": True, "role": h.role,
                                "serving_version": int(
                                    h.digest(light=True)
                                    ["serving_version"]),
                                **h.stats()}
        stats = {
            "engines": per_engine,
            "rounds": self.rounds,
            "routed": self.routed,
            "routed_by": dict(self.routed_by),
            "handoffs": self.handoffs,
            "migrations": self.migrations,
            "sheds": self.sheds,
            "kills": self.kills,
            "prefix_routed_hit_blocks": self.prefix_routed_hit_blocks,
            # the migration-stall surface (live moves only): blocks +
            # SERIALIZED wire bytes shipped and the per-move wall-clock
            # list's summary (bench_decode.py's fleet_handoff_* rows
            # read the raw accumulators off the router instead)
            "handoff_blocks": self.handoff_blocks,
            "handoff_bytes": self.handoff_bytes,
            "wire_rejects": self.wire_rejects,
            # network-boundary robustness (round 22): links that
            # dropped and were healed by reconnect-and-replay instead
            # of being declared dead
            "reconnects": self.reconnects_total,
            # live weight hot-swap (round 17): completed rolling
            # deploys and CRC/mid-roll rollbacks
            "deploys": self.deploys,
            "deploy_rollbacks": self.deploy_rollbacks,
            # transport cost attribution (round 18): per-worker RPC
            # op percentiles + the round wall clock (None per engine
            # in-process — nothing to price)
            "transport": self.transport_stats(),
        }
        if self.handoff_durations:
            import numpy as np
            stats["handoff_stall_p90_ms"] = round(float(np.percentile(
                np.asarray(self.handoff_durations), 90)) * 1e3, 3)
        return stats


def _retain_rejected(path: str, keep: int) -> None:
    """Bounded post-mortem retention for a REJECTED wire doc: rename
    it ``*.rejected`` (so no retry can re-consume the damaged bytes)
    and prune the spool's oldest rejected files past ``keep`` — the
    ``checkpoint.keep_last`` discipline applied to the wire spool. A
    chaos loop of rejections must never grow a worker's spool without
    bound."""
    import os
    try:
        os.replace(path, path + ".rejected")
    except OSError:
        return
    spool = os.path.dirname(path) or "."
    try:
        rejected = [os.path.join(spool, name)
                    for name in os.listdir(spool)
                    if name.endswith(".rejected")]
    except OSError:
        return

    def age(p):
        try:
            return (os.path.getmtime(p), p)
        except OSError:
            return (0.0, p)

    rejected.sort(key=age)
    for old in (rejected if keep <= 0 else rejected[:-keep]):
        try:
            os.unlink(old)
        except OSError:
            pass


def _corrupt_wire_file(path: str) -> None:
    """The ``corrupt_wire`` chaos mechanics: flip a run of bytes just
    past the middle of a published wire file — inside the array payload
    region for any realistic KV doc — simulating in-transit damage that
    slipped past rename atomicity. The per-array CRC (or, for damage
    landing on container structure, the npz parse itself) must reject
    the import. The flipped run is 128 bytes: a zip member's local
    header + extra-field padding (bytes NO checksum covers) can span
    ~70 bytes, and an 8-byte flip that happened to land entirely
    inside that dead zone once sailed through every integrity check —
    the run must be wider than any possible gap so it always reaches
    CRC-covered payload."""
    import os
    size = os.path.getsize(path)
    off = max(1, int(size * 0.55))
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(128)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
