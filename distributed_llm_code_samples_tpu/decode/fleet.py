"""Fleet-scale serving: a host-side router over N decode-engine
replicas, with disaggregated prefill/decode and KV-handoff migration.

One ``DecodeEngine`` is not "heavy traffic from millions of users":
aggregate tokens/s scales only with what a single engine holds, and a
long prefill still steals a step from every running decode on the same
engine. This module is data parallelism one level up — the dp axis of
the training meshes (SNIPPETS.md [3]'s dp x mp factorization) applied
at the REQUEST level — plus the DistServe/Splitwise disaggregation
argument: prefill is compute-bound and bursty, decode is memory-bound
and steady, so co-locating them trades throughput for interference.

The three moves, each riding machinery earlier rounds already built:

- **Routing** (``FleetRouter.submit``): least-loaded admission over the
  live per-engine state the schema-v5 telemetry already pins (queue
  depth, occupancy, pool utilization), session affinity (a session's
  requests stay on one engine), and **prefix affinity** — the router
  probes every engine's radix tree (``PrefixCache.warm_blocks``; the
  in-process form of a shadow index, with zero mirror drift) and sends
  a sharer to the engine whose tree is warm, so PR 9's ~1-prefill
  property holds FLEET-wide, not per-engine. A full target spills to
  the next-best engine; all-full sheds at the door (the serving 503).

- **Disaggregated prefill/decode** (``prefill_engines=M``): M dedicated
  prefill engines run the chunked prefill; the moment a prompt
  completes, the sequence ships to a decode engine via the
  **single-sequence KV handoff** (``DecodeEngine.export_sequence`` /
  ``import_sequence`` — PR 5's snapshot serialization generalized from
  whole-engine metadata to one uid's written blocks + int8 scales +
  position, restored under the foreign pool's block numbering). Decode
  engines therefore execute ZERO prefill dispatches — a prompt burst
  lands on the prefill tier and running decodes never stall behind it.

- **Migration as the same primitive**: pool exhaustion moves the
  youngest running sequence to a peer with capacity via the same
  export/import (live, no replay); an engine KILL migrates its
  in-flight requests to survivors from its last **snapshot**
  (``supervise.snapshot_state`` — the in-memory form of PR 5's crash
  document), where replay fills the gap since that snapshot and
  continues token-identically. The sampling keys fold
  ``(seed, uid, position)`` — never the slot OR the engine — so a
  migrated sequence's remaining tokens match the un-migrated oracle
  bit for bit at every kv_dtype.

Every router decision emits one schema-v9 ``router`` record (routed /
handoff / migrated / shed with source/target engine ids, the pinned
``policy`` that placed it, the candidate scores the decision saw, and
— on live moves — ``blocks``/``bytes``/``duration_s`` measured around
export/import, the migration-stall instrumentation); each scheduling
round additionally emits one ``fleet`` health record (per-engine
waiting/active/free-blocks/utilization + a load-imbalance scalar).
``report router eng0 eng1 ...`` folds them onto the merged timeline
with a fleet-level latency/shed summary above the per-engine blocks,
and ``report --slo TTFT:ITL`` turns the merged streams into goodput
numbers (DESIGN.md section 21).

The router is deliberately HOST-side and in-process: engines are
stepped round-robin (one fleet round steps every engine once), so on
CPU the parallel-speedup claim is made as a dispatch/step-count proxy
(aggregate tokens per fleet ROUND — what wall clock would show if the
replicas ran on their own chips), never as fake wall-clock. Multi-host
transport (the doc is one dict of numpy arrays — npz on a wire) is
ROADMAP follow-up.
"""

from __future__ import annotations

import collections
import time

from .engine import AdmissionError, DecodeEngine
from .supervise import snapshot_state

# engine-id prefixes: prefill tier "p", decode tier "e" (unified
# engines are decode-tier — they can prefill too)
DECODE_PREFIX = "e"
PREFILL_PREFIX = "p"


class EngineHandle:
    """One fleet member: the engine, its role, and its liveness. A
    killed handle drops its engine object outright — the in-process
    simulation of a dead host — keeping only the last snapshot the
    router migrates from."""

    __slots__ = ("id", "engine", "role", "alive", "snapshot",
                 "killed_at_round", "last_tokens", "last_t",
                 "last_step_s")

    def __init__(self, eid: str, engine: DecodeEngine, role: str):
        self.id = eid
        self.engine = engine
        self.role = role                    # "prefill" | "decode"
        self.alive = True
        self.snapshot: dict | None = None   # last snapshot_state doc
        self.killed_at_round: int | None = None
        self.last_tokens = 0                # decode-record cadence state
        self.last_t = time.perf_counter()
        # wall time of THIS engine's slice of the last fleet round —
        # the per-engine number the interference bench reads (the
        # round-robin loop serializes engines in-process, so timing a
        # whole round would charge every engine for its neighbors)
        self.last_step_s = 0.0

    @property
    def has_work(self) -> bool:
        return self.alive and bool(self.engine.waiting
                                   or self.engine.active)


class FleetRouter:
    """N ``DecodeEngine`` replicas behind one admission point.

    ``make_engine(engine_id)`` is a factory returning a FRESH
    single-device engine per fleet member (attach a per-engine
    ``TelemetryWriter`` inside it; the router never shares one). All
    engines must share the numerics-relevant ``EngineConfig`` keys and
    the model — the handoff's own fingerprint check enforces it at
    migration time, and the router cross-checks fingerprints up front
    so a mismatched fleet fails at construction, not mid-drill.

    ``prefill_engines=M`` dedicates the first M members to prefill
    (disaggregation); ``0`` runs every engine unified. ``n_engines``
    may be 1 (the router degenerates to a pass-through — the honest
    N=1 baseline for the bench scaling rows); the CLI requires >= 2.

    ``snapshot_every`` is the in-memory snapshot cadence in fleet
    rounds (the PR 5 discipline: a kill migrates from the LAST
    snapshot and replay fills the gap since it).
    """

    def __init__(self, make_engine, n_engines: int,
                 prefill_engines: int = 0, *, metrics=None,
                 snapshot_every: int = 1, session_affinity: bool = True,
                 prefix_affinity: bool = True):
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        if not 0 <= prefill_engines < n_engines:
            raise ValueError(
                f"prefill_engines must leave >= 1 decode engine: got "
                f"{prefill_engines} of {n_engines}")
        if snapshot_every < 1:
            raise ValueError(f"snapshot_every must be >= 1, got "
                             f"{snapshot_every}")
        self.handles: list[EngineHandle] = []
        for i in range(prefill_engines):
            eid = f"{PREFILL_PREFIX}{i}"
            self.handles.append(EngineHandle(eid, make_engine(eid),
                                             "prefill"))
        for i in range(n_engines - prefill_engines):
            eid = f"{DECODE_PREFIX}{i}"
            self.handles.append(EngineHandle(eid, make_engine(eid),
                                             "decode"))
        metas = [h.engine.model_meta() for h in self.handles]
        if any(m != metas[0] for m in metas[1:]):
            raise ValueError("fleet engines disagree on model identity "
                             f"({metas}) — every replica must serve the "
                             "same weights")
        for h in self.handles:
            if h.engine.mesh is not None:
                raise ValueError("fleet replicas are single-device "
                                 "(KV handoff has no TP path)")
        self.by_id = {h.id: h for h in self.handles}
        self.metrics = metrics              # the ROUTER's own writer
        self.snapshot_every = snapshot_every
        self.session_affinity = session_affinity
        self.prefix_affinity = prefix_affinity
        self.rounds = 0                     # fleet scheduling rounds
        self._next_uid = 0
        self._sessions: dict = {}           # session -> engine id
        # request book: what the router needs to place (and re-place)
        # a request — NOT a mirror of engine progress (the snapshot is)
        self.requests: dict[int, dict] = {}
        self._kills: dict[int, list[str]] = collections.defaultdict(list)
        # results carried off dead engines (their snapshot's finished/
        # failed maps; survivors re-complete anything newer)
        self._dead_finished: dict[int, list[int]] = {}
        self._dead_failed: dict[int, dict] = {}
        # decision counters (the payload/bench surface)
        self.routed = 0
        self.handoffs = 0
        self.migrations = 0
        self.sheds = 0
        self.kills = 0
        self.routed_by = {"least_loaded": 0, "session": 0, "prefix": 0}
        self.prefix_routed_hit_blocks = 0
        # migration-stall instrumentation (round 15, ROADMAP item 1's
        # bench criterion): every LIVE move (export_sequence ->
        # import_sequence — prefill handoff or pool-pressure migration)
        # accumulates the blocks/bytes shipped and its wall-clock
        # duration; replay-migrations off a dead engine's snapshot ship
        # no KV and stay out of these (their own records carry a
        # duration_s with blocks/bytes 0)
        self.handoff_blocks = 0
        self.handoff_bytes = 0
        self.handoff_durations: list[float] = []

    # -- introspection -------------------------------------------------

    def alive_handles(self, role: str | None = None):
        return [h for h in self.handles if h.alive
                and (role is None or h.role == role)]

    def engine(self, eid: str) -> DecodeEngine:
        return self.by_id[eid].engine

    # -- telemetry -----------------------------------------------------

    def _record(self, event: str, uid: int, source=None, target=None,
                reason=None, policy=None, **extra) -> None:
        if self.metrics is None:
            return
        self.metrics.router({"step": self.rounds, "uid": int(uid),
                             "event": event, "source": source,
                             "target": target, "reason": reason,
                             "policy": policy, **extra})

    def _event(self, record: dict) -> None:
        if self.metrics is not None:
            self.metrics.event(record)

    def _candidates(self, handles, prompt=None) -> list[dict]:
        """The per-engine scores a placement decision saw (schema-v9
        ``routed`` attribution): warm-block depth (null when the
        prefix probe didn't run — prefill-tier admission, affinity
        off, or no prompt), queue depth, active slots, pool
        utilization. Host-side reads only — probing never steps an
        engine."""
        out = []
        for h in handles:
            e = h.engine
            warm = None
            if (prompt is not None and self.prefix_affinity
                    and e.prefix is not None):
                warm = e.prefix.warm_blocks(prompt)
            out.append({
                "engine": h.id,
                "warm_blocks": warm,
                "queue_depth": len(e.waiting),
                "active": e.active,
                "pool_utilization": round(e.kv_pool_utilization(), 4),
            })
        return out

    def _fleet_record(self) -> dict:
        """One per-round fleet health record (schema-v9 ``fleet``
        kind): per-engine waiting/active/free-blocks/utilization and
        the load-imbalance scalar over alive decode engines
        (``(max - min) / max`` of ``active + waiting``; 0.0 balanced
        or idle, toward 1.0 when one engine holds everything)."""
        engines = {}
        loads = []
        for h in self.handles:
            if not h.alive:
                engines[h.id] = {"alive": False}
                continue
            e = h.engine
            engines[h.id] = {
                "alive": True, "role": h.role,
                "waiting": len(e.waiting), "active": e.active,
                "free_blocks": len(e.free_blocks),
                "utilization": round(e.kv_pool_utilization(), 4),
            }
            if h.role == "decode":
                loads.append(e.active + len(e.waiting))
        imb = 0.0
        if len(loads) > 1 and max(loads) > 0:
            imb = round((max(loads) - min(loads)) / max(loads), 4)
        return {"step": self.rounds, "engines": engines,
                "load_imbalance": imb}

    # -- routing -------------------------------------------------------

    def _load_key(self, h: EngineHandle):
        """Least-loaded ordering: queue depth first (waiting work is
        the latency the next request inherits), then slot occupancy,
        then pool pressure — engine id breaks ties deterministically."""
        e = h.engine
        return (len(e.waiting), e.active,
                round(e.kv_pool_utilization(), 4), h.id)

    def _has_capacity(self, h: EngineHandle, prompt_len: int,
                      max_new: int) -> bool:
        """Can ``h`` take a handoff IMPORT right now (free slot + full
        block reservation)? Queue-based admission never needs this —
        submit/resume queue and the engine admits when space frees."""
        e = h.engine
        if not any(s is None for s in e.slots):
            return False
        need = e._blocks_needed(prompt_len, max_new)
        if need > e.cfg.max_blocks_per_seq:
            return False
        avail = len(e.free_blocks)
        if e.prefix is not None:
            avail += e.prefix.evictable_blocks()
        return need <= avail

    def _route(self, prompt, session, warm_by_id=None):
        """Pick the decode-tier engine for a fresh request. Precedence:
        session affinity (stickiness beats balance — the session's KV
        locality is on that engine), then prefix affinity (the engine
        with the deepest warm radix path wins, load breaking ties),
        then least-loaded. ``warm_by_id`` reuses warm-block counts a
        caller already probed (the candidates capture) so a
        telemetry-enabled submit walks each radix tree once, not
        twice."""
        handles = self.alive_handles("decode")
        if not handles:
            raise RuntimeError("no alive decode engine in the fleet")
        if self.session_affinity and session is not None:
            eid = self._sessions.get(session)
            if eid is not None and self.by_id[eid].alive:
                return self.by_id[eid], "session", 0
        if self.prefix_affinity:
            if warm_by_id is not None:
                warm = [(warm_by_id[h.id], h) for h in handles
                        if warm_by_id.get(h.id) is not None]
            else:
                warm = [(h.engine.prefix.warm_blocks(prompt), h)
                        for h in handles if h.engine.prefix is not None]
            best = max((w for w, _ in warm), default=0)
            if best > 0:
                tied = [h for w, h in warm if w == best]
                return min(tied, key=self._load_key), "prefix", best
        return min(handles, key=self._load_key), "least_loaded", 0

    def submit(self, prompt, max_new: int, session=None) -> int:
        """Route one request into the fleet; returns its fleet-global
        uid. Disaggregated fleets admit through the least-loaded
        PREFILL engine (the decode target is chosen at handoff time,
        when the KV exists); unified fleets route by
        session/prefix/load. A full target spills over to the next
        engine by load; when every engine sheds, the request is shed
        fleet-wide (``AdmissionError``, one ``shed`` router record)."""
        # the uid is CONSUMED whether the request lands or sheds — a
        # shed record must never carry a number a later accepted
        # request reuses (the engine-side audit-trail discipline:
        # aliasing two requests per uid breaks the per-uid timeline)
        uid = self._next_uid
        self._next_uid += 1
        prompt = [int(t) for t in prompt]
        reason, hit_blocks = None, 0
        prefills = self.alive_handles("prefill")
        # decision attribution (schema v9): the per-engine scores this
        # placement saw, captured BEFORE any engine takes the request
        # (only when a router stream exists — the probe is host-cheap
        # but pointless without a record to ride); the routing decision
        # below REUSES the captured warm-block counts, so each radix
        # tree is walked once per submit either way
        candidates = None
        if prefills:
            order = sorted(prefills, key=self._load_key)
            reason = "least_loaded"
            if self.metrics is not None:
                candidates = self._candidates(order, prompt)
        else:
            warm_by_id = None
            if self.metrics is not None:
                candidates = self._candidates(
                    self.alive_handles("decode"), prompt)
                warm_by_id = {c["engine"]: c["warm_blocks"]
                              for c in candidates}
            target, reason, hit_blocks = self._route(prompt, session,
                                                     warm_by_id)
            others = sorted(
                (h for h in self.alive_handles("decode")
                 if h is not target), key=self._load_key)
            order = [target] + others
        shed_reasons = []
        spilled = False
        for h in order:
            try:
                h.engine.submit(prompt, max_new, uid=uid)
            except AdmissionError as e:
                shed_reasons.append(f"{h.id}: queue_full")
                # spillover loses affinity — including the warm-block
                # count probed for the ORIGINAL target (the next engine
                # tried is cold; recording the stale count would credit
                # it with blocks it doesn't hold)
                reason, hit_blocks = "least_loaded", 0
                spilled = True
                continue
            self.requests[uid] = {"prompt": prompt, "max_new": max_new,
                                  "engine": h.id, "session": session}
            if session is not None and h.role == "decode":
                self._sessions[session] = h.id
            self.routed += 1
            self.routed_by[reason] = self.routed_by.get(reason, 0) + 1
            if reason == "prefix":
                self.prefix_routed_hit_blocks += hit_blocks
            # policy: what ACTUALLY placed the request — "spill" when
            # the probed target shed and the request landed on a later
            # engine by load (the affinity-era reason would credit a
            # policy that didn't place it)
            self._record("routed", uid, target=h.id, reason=reason,
                         policy=("spill" if spilled else reason),
                         prefix_hit_blocks=hit_blocks,
                         candidates=candidates)
            # the step-0 snapshot discipline: a kill before the first
            # cadence snapshot must still know this request exists.
            # O(1) per submit: append the one new WAITING entry to the
            # handle's existing snapshot instead of re-serializing the
            # whole engine (a burst of n submissions must not pay
            # O(n^2) host work on the admission path) — the cadence
            # snapshot already lags by design, and kill-migration only
            # needs the request LISTED (resume replays from `out`)
            if h.snapshot is None:
                h.snapshot = snapshot_state(h.engine)
            else:
                seq = next(s for s in reversed(h.engine.waiting)
                           if s.uid == uid)
                h.snapshot["requests"].append(
                    {"uid": seq.uid, "prompt": seq.prompt,
                     "out": seq.out, "max_new": seq.max_new,
                     "retries": seq.retries, "t_submit": seq.t_submit,
                     "submit_step": seq.submit_step,
                     "t_first": None,       # no first token yet
                     "state": "WAITING"})
            return uid
        self.sheds += 1
        self._record("shed", uid, reason="queue_full")
        raise AdmissionError(
            f"every fleet engine shed request uid {uid}: "
            f"[{'; '.join(shed_reasons)}]")

    # -- the fleet round -----------------------------------------------

    def step(self) -> bool:
        """One fleet scheduling round: fire due kills (the chaos
        clock), step every alive engine once, ship completed prefills
        to the decode tier, relieve pool pressure by migration, then
        refresh the in-memory snapshots on cadence. Returns whether any
        engine ran work this round."""
        killed = bool(self._kills.get(self.rounds))
        for eid in self._kills.pop(self.rounds, ()):
            self.kill_engine(eid)
        did = killed
        for h in self.handles:
            if h.has_work:
                t0 = time.perf_counter()
                did = h.engine.step(prefill_only=(h.role == "prefill")) \
                    or did
                h.last_step_s = time.perf_counter() - t0
        before = self.handoffs + self.migrations
        self._handoff_completed_prefills()
        self._migrate_pool_pressure()
        did = did or (self.handoffs + self.migrations > before)
        self.rounds += 1
        if self.rounds % self.snapshot_every == 0:
            for h in self.handles:
                if h.alive:
                    h.snapshot = snapshot_state(h.engine)
        # one fleet health record per round (schema v9): the
        # per-engine balance view the SLO/autoscaling layer reads.
        # ``step`` is the post-round clock — record N describes the
        # fleet after N rounds.
        if self.metrics is not None:
            self.metrics.fleet(self._fleet_record())
        return did

    def _placement_target(self, prompt_len: int, max_new: int,
                          exclude=()) -> EngineHandle | None:
        cands = [h for h in self.alive_handles("decode")
                 if h.id not in exclude
                 and self._has_capacity(h, prompt_len, max_new)]
        return min(cands, key=self._load_key) if cands else None

    @staticmethod
    def _doc_bytes(doc: dict) -> int:
        """Wire bytes of one handoff document's KV payload (values +
        int8 scales at the storage dtype) — the ``bytes`` a multi-host
        transport would actually ship (ROADMAP item 1's criterion;
        the scheduler-state envelope is noise next to the arrays)."""
        n = 0
        for key in ("k", "v", "k_scale", "v_scale"):
            arr = doc.get(key)
            if arr is not None:
                n += int(arr.nbytes)
        return n

    def _move(self, source: EngineHandle, target: EngineHandle,
              uid: int):
        """One LIVE sequence move (export -> import), instrumented:
        returns ``(doc, blocks, bytes, duration_s)`` and feeds the
        migration-stall accumulators (blocks shipped/s, stall p90 —
        the wall clock is the CPU proxy for a wire transport's
        serialize+ship+implant cost)."""
        t0 = time.perf_counter()
        doc = source.engine.export_sequence(uid)
        target.engine.import_sequence(doc)
        dur = time.perf_counter() - t0
        blocks = int(doc["blocks_written"])
        nbytes = self._doc_bytes(doc)
        self.handoff_blocks += blocks
        self.handoff_bytes += nbytes
        self.handoff_durations.append(dur)
        return doc, blocks, nbytes, dur

    def _handoff_completed_prefills(self) -> None:
        """Ship every fully-prefilled sequence off the prefill tier.
        No decode capacity right now -> the sequence PARKS (the
        prefill tier steps with ``prefill_only=True``, so a parked
        sequence makes no decode progress there) and the handoff is
        retried next round; a burst larger than the decode tier's
        total capacity surfaces as ``run()``'s fleet-stalled error
        rather than silently decoding on the wrong tier — tier purity
        is what the dispatch-count proof pins."""
        for ph in self.alive_handles("prefill"):
            ready = [s.uid for s in ph.engine.slots
                     if s is not None and s.prompt_done]
            for uid in ready:
                req = self.requests[uid]
                target = self._placement_target(len(req["prompt"]),
                                                req["max_new"])
                if target is None:
                    continue
                doc, blocks, nbytes, dur = self._move(ph, target, uid)
                self.handoffs += 1
                req["engine"] = target.id
                if req["session"] is not None:
                    self._sessions[req["session"]] = target.id
                self._record("handoff", uid, source=ph.id,
                             target=target.id, reason="prefill_done",
                             position=doc["position"], blocks=blocks,
                             bytes=nbytes, duration_s=round(dur, 6))
                # refresh BOTH snapshots now: a kill before the next
                # cadence snapshot must neither lose the moved request
                # (target's snapshot predates it) nor resurrect it on
                # the source (whose stale snapshot still lists it)
                ph.snapshot = snapshot_state(ph.engine)
                target.snapshot = snapshot_state(target.engine)

    def _migrate_pool_pressure(self) -> None:
        """A starved engine (head-of-line waiter has a free slot but
        not its block reservation) moves its YOUNGEST fully-prefilled
        running sequence to a peer with capacity — a LIVE handoff, no
        replay. The same victim policy as the engine's own preemption
        (the oldest resident keeps making progress), but the victim
        keeps running instead of losing its KV."""
        for h in self.alive_handles("decode"):
            e = h.engine
            if not e.waiting:
                continue
            head = e.waiting[0]
            if not any(s is None for s in e.slots):
                continue                    # slot-starved, not pool
            need = e._blocks_needed(len(head.prompt), head.max_new)
            avail = len(e.free_blocks)
            if e.prefix is not None:
                avail += e.prefix.evictable_blocks()
            if need <= avail:
                continue                    # admission will take it
            victims = [(s.admit_index, s.uid, len(s.prompt), s.max_new)
                       for s in e.slots
                       if s is not None and s.prompt_done]
            if not victims:
                continue
            _, uid, plen, mnew = max(victims)
            target = self._placement_target(plen, mnew,
                                            exclude=(h.id,))
            if target is None:
                continue
            doc, blocks, nbytes, dur = self._move(h, target, uid)
            self.migrations += 1
            self.requests[uid]["engine"] = target.id
            self._record("migrated", uid, source=h.id,
                         target=target.id, reason="pool_pressure",
                         position=doc["position"], blocks=blocks,
                         bytes=nbytes, duration_s=round(dur, 6))
            # the handoff snapshot-refresh discipline (see above)
            h.snapshot = snapshot_state(e)
            target.snapshot = snapshot_state(target.engine)

    # -- failure (the chaos drill's surface) ---------------------------

    def schedule_kill(self, engine_id: str, at_round: int) -> None:
        """Arm a deterministic engine kill at the START of fleet round
        ``at_round`` (the round's snapshot cadence has NOT yet run —
        the last snapshot honestly lags by up to ``snapshot_every``
        rounds, and replay fills exactly that gap)."""
        if engine_id not in self.by_id:
            raise ValueError(f"unknown engine id {engine_id!r} "
                             f"(fleet: {sorted(self.by_id)})")
        if at_round < 0:
            raise ValueError(f"kill round must be >= 0, got {at_round}")
        self._kills[at_round].append(engine_id)

    def kill_engine(self, engine_id: str) -> int:
        """Kill one engine NOW and migrate its in-flight requests to
        the survivors from its last snapshot: finished/failed results
        ride over verbatim, every live request re-enters a survivor's
        queue for replay-resume (``resume_request`` — prompt
        re-prefilled, recorded tokens teacher-forced, so the rebuilt KV
        write history and the remaining tokens are bit-identical to the
        uninterrupted run's). Returns the number of migrated requests.
        The engine object is dropped — its pool, like a dead host's
        HBM, is unreachable."""
        h = self.by_id.get(engine_id)
        if h is None:
            raise ValueError(f"unknown engine id {engine_id!r}")
        if not h.alive:
            return 0
        snap = h.snapshot
        h.alive = False
        h.killed_at_round = self.rounds
        h.engine = None
        self.kills += 1
        self._event({"event": "engine_killed", "engine": h.id,
                     "round": self.rounds})
        if snap is None:
            return 0
        self._dead_finished.update(
            {int(u): list(t) for u, t in snap["finished"].items()})
        self._dead_failed.update(
            {int(u): dict(i) for u, i in snap["failed"].items()})
        # a dead prefill engine's queue re-enters the prefill tier
        # while one exists (tier purity survives the kill); decode
        # requests always land on decode survivors
        survivors = (self.alive_handles("prefill")
                     if h.role == "prefill" else [])
        survivors = survivors or self.alive_handles("decode")
        if not survivors:
            raise RuntimeError("last decode engine killed: the fleet "
                               "has nowhere to migrate its requests")
        moved = 0
        for req in snap["requests"]:
            target = min(survivors, key=self._load_key)
            t0 = time.perf_counter()
            target.engine.resume_request(
                req["uid"], req["prompt"], req["max_new"],
                out=req["out"], retries=req["retries"],
                t_submit=req.get("t_submit"),
                t_first=req.get("t_first"))
            dur = time.perf_counter() - t0
            self.requests[int(req["uid"])]["engine"] = target.id
            # a replay-migration ships no KV (the dead pool is
            # unreachable): blocks/bytes are honestly 0 and the replay
            # length names the catch-up cost instead; duration_s here
            # is the re-queue cost only — the replay itself shows up
            # in the request's own span stream
            self._record("migrated", req["uid"], source=h.id,
                         target=target.id, reason="engine_killed",
                         replay=len(req["out"]), blocks=0, bytes=0,
                         duration_s=round(dur, 6))
            # a survivor dying right after must re-migrate this too
            target.snapshot = snapshot_state(target.engine)
            moved += 1
        self.migrations += moved
        return moved

    # -- drain ---------------------------------------------------------

    @property
    def has_work(self) -> bool:
        return any(h.has_work for h in self.handles)

    def _pending_kills(self) -> bool:
        return any(self.by_id[eid].alive for ids in self._kills.values()
                   for eid in ids)

    def run(self, log_every: int = 0) -> dict[int, list[int]]:
        """Drain the fleet: round until every request finished or
        failed (scheduled kills past the drain point are dropped — a
        dead-on-arrival fault has nothing to kill). ``log_every``
        emits one ``decode`` cadence record per engine through ITS OWN
        writer every that-many rounds (the engines are stepped
        manually, so the router owns the cadence ``DecodeEngine.run``
        normally would)."""
        while self.has_work:
            did = self.step()
            if log_every > 0 and self.rounds % log_every == 0:
                self._emit_decode_records()
            if not did and self.has_work and not self._pending_kills():
                raise RuntimeError(
                    "fleet stalled: waiting requests but no engine ran "
                    "work and no kill is pending")
        self._emit_decode_records()
        return self.results()

    def _emit_decode_records(self) -> None:
        now = time.perf_counter()
        for h in self.handles:
            if not h.alive or h.engine.metrics is None:
                continue
            delta = h.engine.tokens_generated - h.last_tokens
            dt = max(now - h.last_t, 1e-9)
            tps = round(delta / dt, 2) if delta > 0 else None
            h.engine.metrics.decode(h.engine.telemetry_record(tps))
            h.last_tokens = h.engine.tokens_generated
            h.last_t = now

    def results(self) -> dict[int, list[int]]:
        """Merged per-uid outcomes across the whole fleet, dead
        engines' pre-kill completions included. A request completed on
        a dead engine AFTER its last snapshot re-completes on a
        survivor (replay is deterministic), so the merge can never see
        two different answers for one uid."""
        out = dict(self._dead_finished)
        for h in self.handles:
            if h.alive:
                out.update(h.engine.finished)
        return out

    def failed(self) -> dict[int, dict]:
        out = dict(self._dead_failed)
        for h in self.handles:
            if h.alive:
                out.update(h.engine.failed)
        return out

    # -- the payload/bench surface -------------------------------------

    def fleet_stats(self) -> dict:
        """Fleet-level counters + per-engine summaries — the generate
        CLI payload block and the bench rows' raw material."""
        per_engine = {}
        for h in self.handles:
            if not h.alive:
                per_engine[h.id] = {"alive": False,
                                    "killed_at_round": h.killed_at_round}
                continue
            e = h.engine
            per_engine[h.id] = {
                "alive": True, "role": h.role,
                "engine_steps": e.global_step,
                "tokens_generated": e.tokens_generated,
                "prefill_dispatches": e.prefill_dispatches,
                "compiled_programs": e.compile_count,
                "dispatches": e.dispatch_count,
                "finished": len(e.finished),
                "prefix_hit_blocks": e.prefix_hit_blocks,
                "prefill_tokens_saved": e.prefill_tokens_saved,
            }
        stats = {
            "engines": per_engine,
            "rounds": self.rounds,
            "routed": self.routed,
            "routed_by": dict(self.routed_by),
            "handoffs": self.handoffs,
            "migrations": self.migrations,
            "sheds": self.sheds,
            "kills": self.kills,
            "prefix_routed_hit_blocks": self.prefix_routed_hit_blocks,
            # the migration-stall surface (live moves only): blocks +
            # wire bytes shipped and the per-move wall-clock list's
            # summary (bench_decode.py's fleet_handoff_* rows read the
            # raw accumulators off the router instead)
            "handoff_blocks": self.handoff_blocks,
            "handoff_bytes": self.handoff_bytes,
        }
        if self.handoff_durations:
            import numpy as np
            stats["handoff_stall_p90_ms"] = round(float(np.percentile(
                np.asarray(self.handoff_durations), 90)) * 1e3, 3)
        return stats
