"""In-graph fused sampling for the decode engine.

The lockstep decoders pick host-free already (``models.lm.sample_pick``),
but their RNG folds only ``(seed, position)`` — fine when the whole
batch is one request, wrong for continuous batching, where a slot's
draw must not depend on *which* slot (or which neighbors) a sequence
landed in. The engine's contract folds the **sequence uid** too:

    key = fold_in(fold_in(fold_in(PRNGKey(0x5A3D), seed), uid), position)

``position`` is the global index of the token being generated (prompt
positions count from 0), so a sequence's continuation is a pure
function of ``(engine seed, uid, its own tokens)`` — continuous-batching
output is token-identical to decoding the same sequence alone, which is
exactly what tests/test_decode_engine.py pins. Same counter-RNG stance
as the data layer (``data.batch_from_seed``): no carried RNG state.

The pick itself is fused into the compiled step: temperature scaling,
top-k truncation, top-p (nucleus) truncation, then a Gumbel-max
categorical draw (an exact sample from the truncated softmax). Greedy
(``temperature == 0``) is a plain argmax — bit-compatible with
``models.lm.generate``'s pick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# the engine's sampling domain (distinct from sample_pick's 0x5A3)
_BASE_KEY = 0x5A3D


def check_sampling(temperature: float, top_k: int, top_p: float,
                   vocab: int) -> None:
    """Shared flag validation (engine + CLI): ``temperature == 0`` is
    greedy; ``top_k == 0`` / ``top_p == 0`` disable those truncations."""
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0 (0 = greedy), got "
                         f"{temperature}")
    if top_k < 0 or top_k > vocab:
        raise ValueError(f"top_k={top_k} outside [0, vocab={vocab}]")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p={top_p} outside [0, 1]")
    if temperature == 0 and (top_k or top_p):
        raise ValueError("top_k/top_p require temperature > 0 "
                         "(greedy ignores them)")


def check_speculation(speculate: int, temperature: float) -> None:
    """Shared validation (engine + CLI) for the speculative-decoding
    knob: verification is GREEDY — the verify step accepts a drafted
    token iff it equals the argmax pick, which is what keeps the
    engine's token-identity proofs intact (an accepted token IS the
    token the non-speculative engine would have emitted). Sampled
    decoding would need rejection sampling over the full distribution
    (a different acceptance rule with a different identity story), so
    ``speculate > 0`` requires ``temperature == 0``."""
    if speculate < 0:
        raise ValueError(f"speculate must be >= 0 (0 = off), got "
                         f"{speculate}")
    if speculate and temperature != 0:
        raise ValueError(
            "speculative decoding verifies greedily: speculate > 0 "
            f"requires temperature == 0, got {temperature} (sampled "
            "decoding runs non-speculatively)")


def _nucleus_mask(z: jax.Array, top_p: float) -> jax.Array:
    """Mask logits outside the top-p nucleus: keep the smallest
    descending-probability prefix whose mass reaches ``top_p`` (the
    token that crosses the threshold is kept, so at least the argmax
    always survives). ``z [S, V]`` -> ``z`` with -inf outside."""
    s = z.shape[0]
    order = jnp.argsort(-z, axis=-1)                    # descending
    probs = jax.nn.softmax(z, axis=-1)
    sorted_p = jnp.take_along_axis(probs, order, axis=-1)
    before = jnp.cumsum(sorted_p, axis=-1) - sorted_p   # mass ahead of i
    keep_sorted = before < top_p
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(s)[:, None], order].set(keep_sorted)
    return jnp.where(keep, z, -jnp.inf)


def make_pick(temperature: float, top_k: int, top_p: float, vocab: int,
              seed: int):
    """Build the fused ``pick(logits [S, V], uids [S], positions [S])
    -> [S] int32`` for the engine's compiled steps. All arguments are
    static (one pick per engine config); ``uids``/``positions`` are
    runtime operands, so one compiled program serves every slot mix."""
    check_sampling(temperature, top_k, top_p, vocab)
    if temperature == 0:
        return lambda z, uids, positions: jnp.argmax(
            z, axis=-1).astype(jnp.int32)
    base = jax.random.fold_in(jax.random.PRNGKey(_BASE_KEY), seed)

    def pick(logits, uids, positions):
        z = logits.astype(jnp.float32) / temperature
        if top_k:
            kth = lax.top_k(z, top_k)[0][:, -1:]
            z = jnp.where(z < kth, -jnp.inf, z)
        if top_p:
            z = _nucleus_mask(z, top_p)

        def draw(z_row, uid, pos):
            key = jax.random.fold_in(jax.random.fold_in(base, uid), pos)
            g = jax.random.gumbel(key, z_row.shape, jnp.float32)
            # -inf + gumbel stays -inf: truncated tokens never win
            return jnp.argmax(z_row + g)

        return jax.vmap(draw)(z, uids, positions).astype(jnp.int32)

    return pick
