"""Block/paged KV cache: the decode engine's memory layout.

The lockstep decoder (``models.lm.generate``) allocates one contiguous
``[T_max]`` cache lane per sequence, so a batch of mixed-length
sequences pays for its longest member and freeing a finished sequence
means rebuilding the batch (a recompile). This module is the
PagedAttention-style answer in the repo's first-principles idiom: the
cache is a static-shape **pool of fixed-size blocks**
(``k/v [L, n_blocks, H_kv, block, dh]``) and each sequence names its
blocks through a per-slot int32 **block table** — the KV read is a
gather (``models.attention.gather_paged_kv``), the write is a scatter,
and freeing a sequence is a host-side table edit. Shapes never depend
on sequence length, so one compiled decode step serves every occupancy.

Physical block 0 is reserved as the **scratch block**: unassigned table
slots and padded bucket rows point at it, so padded writes land
somewhere harmless instead of needing a masked scatter, and gathers of
short sequences read bytes the causal mask then hides. Nothing is ever
read from it unmasked.

Quantization (``kv_dtype``):

- ``"f32"`` — exact; the bit-for-bit baseline.
- ``"bf16"`` — cast on write, upcast on read (exact mantissa truncation;
  2x fewer KV bytes).
- ``"int8"`` — symmetric per-(layer, block, kv-head) scales
  (``k_scale/v_scale [L, n_blocks, H_kv]`` f32, ``scale = amax/127``).
  A write re-quantizes the touched block over its *valid* rows only
  (stale rows from a freed sequence never inflate the scale), which is
  lossy but deterministic: a block's stored bytes depend only on its own
  sequence's write history, so continuous batching stays token-identical
  to sequential decode at any dtype (tests/test_decode_engine.py).

All functions are pure jnp with static shapes; the layer index is a
Python int (the engine unrolls layers at trace time, like
``models.lm.decode_step``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

KV_DTYPES = ("f32", "bf16", "int8")

# physical block 0 is the scratch block (see module docstring)
SCRATCH_BLOCK = 0


class PagedKV(NamedTuple):
    """The block pool. ``k/v [L, n_blocks, H_kv, block, dh]`` in the
    storage dtype; ``k_scale/v_scale [L, n_blocks, H_kv]`` f32 per-block
    dequantization scales (``None`` unless ``kv_dtype="int8"``)."""
    k: jax.Array
    v: jax.Array
    k_scale: jax.Array | None
    v_scale: jax.Array | None

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]


def storage_dtype(kv_dtype: str):
    if kv_dtype not in KV_DTYPES:
        raise ValueError(f"kv_dtype {kv_dtype!r} not in {KV_DTYPES}")
    return {"f32": jnp.float32, "bf16": jnp.bfloat16,
            "int8": jnp.int8}[kv_dtype]


def kv_bytes_per_token(kv_dtype: str, n_layers: int, kv_heads: int,
                       head_dim: int) -> float:
    """Stored KV bytes per cached token position — the roofline's
    ``kv_bytes`` knob. int8 adds the amortized per-block scale pair
    (negligible; counted as 0 here, the bench reports block overheads
    separately)."""
    per_elt = {"f32": 4, "bf16": 2, "int8": 1}[kv_dtype]
    return 2 * n_layers * kv_heads * head_dim * per_elt


def pool_bytes(pool: PagedKV) -> tuple[int, int]:
    """``(kv_bytes, scale_bytes)`` actually held by the pool arrays —
    the device-side truth ``decode_static_report`` cross-checks against
    the roofline's hand prediction (``kv_bytes_per_token * n_blocks *
    block_size``; the two MUST agree exactly, or the roofline prices a
    layout the engine doesn't run)."""
    kv = int(pool.k.nbytes) + int(pool.v.nbytes)
    sc = (0 if pool.k_scale is None
          else int(pool.k_scale.nbytes) + int(pool.v_scale.nbytes))
    return kv, sc


def init_pool(n_layers: int, n_blocks: int, kv_heads: int,
              block_size: int, head_dim: int,
              kv_dtype: str = "f32") -> PagedKV:
    """Zero-filled pool. ``n_blocks`` includes the reserved scratch
    block, so at least 2 are required for any real sequence."""
    if n_blocks < 2:
        raise ValueError(f"n_blocks must be >= 2 (block {SCRATCH_BLOCK} "
                         f"is the reserved scratch block), got {n_blocks}")
    shape = (n_layers, n_blocks, kv_heads, block_size, head_dim)
    dt = storage_dtype(kv_dtype)

    def scale():
        # distinct arrays per field: the engine donates the whole pool
        # into its compiled steps, and XLA rejects donating one buffer
        # through two arguments
        return (jnp.zeros((n_layers, n_blocks, kv_heads), jnp.float32)
                if kv_dtype == "int8" else None)

    return PagedKV(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt),
                   k_scale=scale(), v_scale=scale())


def _quantize(x: jax.Array, valid: jax.Array):
    """Symmetric int8 quantization of one (or a batch of) blocks.
    ``x [..., block, dh]`` f32, ``valid [..., block]`` bool row mask.
    Returns ``(q int8, scale [...])`` with ``scale = amax/127`` over the
    valid rows; an all-invalid (or all-zero) block gets scale 0 and
    zero codes."""
    masked = jnp.where(valid[..., None], jnp.abs(x), 0.0)
    amax = jnp.max(masked, axis=(-2, -1))
    scale = amax / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)[..., None, None]
    q = jnp.clip(jnp.round(x / safe), -127, 127).astype(jnp.int8)
    q = jnp.where((scale > 0)[..., None, None], q, jnp.int8(0))
    return q, scale


def _dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    """``x_hat = q * scale``; ``q [..., block, dh]``, ``scale [...]``."""
    return q.astype(jnp.float32) * scale[..., None, None]


def write_rows(pool: PagedKV, layer: int, phys: jax.Array,
               off: jax.Array, k_new: jax.Array, v_new: jax.Array,
               kv_dtype: str) -> PagedKV:
    """Scatter ``N`` new KV rows into the pool: row ``i`` lands at
    ``(layer, phys[i], :, off[i], :)``. ``k_new/v_new [N, H_kv, dh]``
    f32. For f32/bf16 this is one masked-free scatter; for int8 each
    touched block is read back, dequantized, re-quantized over its valid
    rows ``0..off[i]`` (blocks fill in order, so everything at or below
    the newest offset is live) and written whole. Duplicate ``phys``
    entries are only ever the scratch block (padded bucket rows) — last
    writer wins there, and nothing reads it unmasked."""
    hkv = pool.k.shape[2]
    heads = jnp.arange(hkv)
    # "requant" tags the KV write in traces/HLO (utils/trace_analysis
    # SCOPES: decode/requant, prefill/requant). At f32/bf16 the region
    # is the plain scatter; the name stays "requant" because the int8
    # read-modify-requantize is the cost the attribution exists to
    # separate — the cheap dtypes show the region near zero.
    if kv_dtype != "int8":
        dt = pool.k.dtype
        idx = (layer, phys[:, None], heads[None, :], off[:, None])
        with jax.named_scope("requant"):
            return pool._replace(
                k=pool.k.at[idx].set(k_new.astype(dt)),
                v=pool.v.at[idx].set(v_new.astype(dt)))
    # int8: read-modify-requantize the touched blocks
    blk = pool.block_size
    rows = jnp.arange(blk)
    valid = rows[None, :] <= off[:, None]               # [N, block]
    valid = jnp.broadcast_to(valid[:, None, :], (off.shape[0], hkv, blk))

    def requant(pool_side, scale_side, new):
        old = _dequantize(pool_side[layer, phys],      # [N, Hkv, blk, dh]
                          scale_side[layer, phys])
        ins = rows[None, None, :, None] == off[:, None, None, None]
        cur = jnp.where(ins, new[:, :, None, :], old)
        q, scale = _quantize(cur, valid)
        return (pool_side.at[layer, phys].set(q),
                scale_side.at[layer, phys].set(scale))

    with jax.named_scope("requant"):
        k, ks = requant(pool.k, pool.k_scale, k_new)
        v, vs = requant(pool.v, pool.v_scale, v_new)
    return PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)


def write_chunk(pool: PagedKV, layer: int, table: jax.Array, pos0,
                k_new: jax.Array, v_new: jax.Array,
                kv_dtype: str) -> PagedKV:
    """Write one sequence's prefill chunk: ``k_new/v_new [C, H_kv, dh]``
    f32 at global positions ``pos0 .. pos0+C-1`` through ``table
    [max_blocks]``. The engine's power-of-two chunk buckets never
    straddle a block boundary (chunk starts are multiples of the chunk
    size and ``block_size`` is a power of two >= or <= every bucket), so
    a chunk either part-fills exactly one block (``C < block``) or
    covers ``C/block`` whole blocks — the two static cases below."""
    c = k_new.shape[0]
    blk = pool.block_size
    positions = pos0 + jnp.arange(c)
    phys = table[positions // blk]
    off = positions % blk
    if kv_dtype != "int8" or c < blk:
        # int8 c<blk touches ONE block; write_rows' per-row requant
        # converges because every row shares (phys, valid-hi) — requant
        # once with all rows inserted
        if kv_dtype == "int8":
            return _int8_partial_chunk(pool, layer, phys[0], off, k_new,
                                       v_new)
        return write_rows(pool, layer, phys, off, k_new, v_new, kv_dtype)
    # int8, whole blocks: quantize each block outright (no old content)
    if c % blk:
        raise ValueError(f"chunk {c} > block {blk} must be a whole "
                         "multiple (power-of-two buckets guarantee it)")
    nb = c // blk
    hkv = pool.k.shape[2]
    dh = pool.k.shape[4]
    blocks = table[pos0 // blk + jnp.arange(nb)]        # [nb]
    valid = jnp.ones((nb, hkv, blk), bool)

    def quant_whole(pool_side, scale_side, new):
        shaped = new.reshape(nb, blk, hkv, dh).transpose(0, 2, 1, 3)
        q, scale = _quantize(shaped, valid)
        return (pool_side.at[layer, blocks].set(q),
                scale_side.at[layer, blocks].set(scale))

    with jax.named_scope("requant"):
        k, ks = quant_whole(pool.k, pool.k_scale, k_new)
        v, vs = quant_whole(pool.v, pool.v_scale, v_new)
    return PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)


def _int8_partial_chunk(pool: PagedKV, layer: int, phys, off: jax.Array,
                        k_new: jax.Array, v_new: jax.Array) -> PagedKV:
    """int8 chunk write confined to ONE block (``C < block``): read the
    block, dequantize, insert the ``C`` rows at ``off``, re-quantize
    over rows ``0..max(off)``."""
    blk = pool.block_size
    hkv, dh = pool.k.shape[2], pool.k.shape[4]
    rows = jnp.arange(blk)
    valid_hi = off[-1]                                  # fills in order
    valid = jnp.broadcast_to((rows <= valid_hi)[None, :], (hkv, blk))
    hit = jnp.zeros((blk,), bool).at[off].set(True)

    def requant(pool_side, scale_side, new):
        old = _dequantize(pool_side[layer, phys],       # [Hkv, blk, dh]
                          scale_side[layer, phys])
        # insert row c at offset off[c] (offsets are distinct)
        upd = jnp.zeros((blk, hkv, dh), new.dtype).at[off].set(new)
        cur = jnp.where(hit[None, :, None], upd.transpose(1, 0, 2), old)
        q, scale = _quantize(cur, valid)
        return (pool_side.at[layer, phys].set(q),
                scale_side.at[layer, phys].set(scale))

    with jax.named_scope("requant"):
        k, ks = requant(pool.k, pool.k_scale, k_new)
        v, vs = requant(pool.v, pool.v_scale, v_new)
    return PagedKV(k=k, v=v, k_scale=ks, v_scale=vs)


def scrub_blocks(pool: PagedKV, blocks) -> PagedKV:
    """Zero the named physical blocks (values AND int8 scales) —
    factory-fresh state, as if never written. The engine runs this when
    a QUARANTINED sequence releases its blocks: a poisoned cache may
    hold NaN/Inf, and a non-finite stale byte is the one thing the
    length/causal mask cannot neutralize (``0.0 * nan == nan`` inside
    the attention ``p @ v`` reduction — finite stale bytes contribute
    exact zeros, non-finite ones poison the whole row). Scrubbing also
    restores the int8 invariant that a block's bytes are a pure
    function of its own sequence's write history, so a retried request
    re-quantizes against the same zero state an uninterrupted run saw.
    Normal releases (finished/preempted sequences) skip the scrub —
    their stale bytes are finite and masked-exact — except for blocks
    the chaos layer marked corrupted, which the engine scrubs on ANY
    release (an eviction can precede the dispatch that would have
    flagged the NaN)."""
    blocks = jnp.asarray(blocks, jnp.int32)
    z = jnp.zeros((), pool.k.dtype)
    out = pool._replace(k=pool.k.at[:, blocks].set(z),
                        v=pool.v.at[:, blocks].set(z))
    if pool.k_scale is not None:
        out = out._replace(k_scale=pool.k_scale.at[:, blocks].set(0.0),
                          v_scale=pool.v_scale.at[:, blocks].set(0.0))
    return out


def copy_block(pool: PagedKV, src, dst) -> PagedKV:
    """Copy one physical block's bytes (values AND int8 scales) from
    ``src`` to ``dst`` across every layer — the device half of
    copy-on-write (``decode/engine.py``): a sequence about to write
    into a block it shares takes a private bit-identical copy first,
    so the write history every sharer observes stays exactly the
    unshared engine's. ``src``/``dst`` may be traced scalars (one
    compiled copy program serves every block pair)."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = pool._replace(k=pool.k.at[:, dst].set(pool.k[:, src]),
                        v=pool.v.at[:, dst].set(pool.v[:, src]))
    if pool.k_scale is not None:
        out = out._replace(
            k_scale=pool.k_scale.at[:, dst].set(pool.k_scale[:, src]),
            v_scale=pool.v_scale.at[:, dst].set(pool.v_scale[:, src]))
    return out


def copy_block_rows(pool: PagedKV, src, dst, n_rows) -> PagedKV:
    """Row-masked ``copy_block``: copy only the first ``n_rows`` token
    rows of ``src`` into ``dst`` (rows past the mask are zeroed, the
    scrubbed-free-block state a fresh prefill expects) — the device
    half of SUB-BLOCK prefix sharing. A partial radix hit clones just
    the shared prefix rows into a private block and the borrower's
    prefill resumes past them, so sharing no longer quantizes to whole
    blocks. The int8 per-block SCALES copy whole: they freeze at share
    time exactly as whole-block sharing froze them (a per-row slice of
    a per-block scale does not exist), which is why the borrowed rows
    stay bit-identical to the donor's bytes rather than to an unshared
    re-prefill. All three operands may be traced scalars — one
    compiled program serves every (src, dst, rows) triple."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    n = jnp.asarray(n_rows, jnp.int32)
    mask = (jnp.arange(pool.block_size) < n)[None, None, :, None]
    z = jnp.zeros((), pool.k.dtype)
    out = pool._replace(
        k=pool.k.at[:, dst].set(jnp.where(mask, pool.k[:, src], z)),
        v=pool.v.at[:, dst].set(jnp.where(mask, pool.v[:, src], z)))
    if pool.k_scale is not None:
        out = out._replace(
            k_scale=pool.k_scale.at[:, dst].set(pool.k_scale[:, src]),
            v_scale=pool.v_scale.at[:, dst].set(pool.v_scale[:, src]))
    return out


def extract_blocks(pool: PagedKV, blocks) -> dict:
    """Host-side copy of the named physical blocks' bytes — the export
    half of the single-sequence KV handoff (``decode/fleet.py``):
    ``k``/``v`` come back ``[L, n, H_kv, block, dh]`` numpy arrays AT
    THE STORAGE DTYPE (int8 codes stay int8 — the import must not
    round-trip through f32, or the bit-exactness contract dies at the
    requantization boundary), ``k_scale``/``v_scale`` ``[L, n, H_kv]``
    f32 (None unless int8). A plain eager gather + device->host
    readback: export rides the host, never the compiled program set."""
    import numpy as np
    idx = np.asarray(blocks, np.int32)
    out = {"k": np.asarray(pool.k[:, idx]),
           "v": np.asarray(pool.v[:, idx]),
           "k_scale": None, "v_scale": None}
    if pool.k_scale is not None:
        out["k_scale"] = np.asarray(pool.k_scale[:, idx])
        out["v_scale"] = np.asarray(pool.v_scale[:, idx])
    return out


def implant_block(pool: PagedKV, dst, k_blk, v_blk,
                  k_scale=None, v_scale=None) -> PagedKV:
    """Write one imported block's bytes (values AND int8 scales) at
    physical block ``dst`` across every layer — the import half of the
    KV handoff. ``k_blk``/``v_blk`` are ``[L, H_kv, block, dh]`` in the
    pool's storage dtype; ``dst`` may be a traced scalar, so ONE
    compiled implant program (donated, like the step programs) serves
    every destination block — importing never recompiles."""
    dst = jnp.asarray(dst, jnp.int32)
    out = pool._replace(k=pool.k.at[:, dst].set(k_blk),
                        v=pool.v.at[:, dst].set(v_blk))
    if pool.k_scale is not None:
        out = out._replace(k_scale=pool.k_scale.at[:, dst].set(k_scale),
                           v_scale=pool.v_scale.at[:, dst].set(v_scale))
    return out


def corrupt_block(pool: PagedKV, block: int) -> PagedKV:
    """Chaos injection (``corrupt_block@s:block``): poison one physical
    block the way a flipped HBM page would — NaN values for the float
    dtypes; NaN per-block SCALES under int8 (int8 codes have no NaN, so
    corruption surfaces through the dequantize multiply). Any sequence
    whose table names the block reads NaN through its gather and fails
    the per-row logits guardrail at its next step — masked positions
    offer no shelter (``0.0 * nan == nan`` in the attention ``p @ v``
    reduction, the same arithmetic ``scrub_blocks`` exists for). A
    corrupted FREE block is caught by the next request that reserves
    it: quarantined once, scrubbed on release, clean on retry."""
    if not 0 <= block < pool.n_blocks:
        raise ValueError(f"block {block} outside pool "
                         f"[0, {pool.n_blocks})")
    if pool.k_scale is not None:
        bad = jnp.asarray(jnp.nan, jnp.float32)
        return pool._replace(k_scale=pool.k_scale.at[:, block].set(bad),
                             v_scale=pool.v_scale.at[:, block].set(bad))
    bad = jnp.asarray(jnp.nan, pool.k.dtype)
    return pool._replace(k=pool.k.at[:, block].set(bad),
                         v=pool.v.at[:, block].set(bad))


def fused_decode_attn(pool: PagedKV, layer: int, q: jax.Array,
                      tables: jax.Array, lengths: jax.Array,
                      interpret: bool | None = None) -> jax.Array:
    """Single-query attention for one layer, fused over the block
    tables (``ops/pallas_paged_attention.py``): the Pallas kernel walks
    each slot's table directly and streams pool blocks through VMEM
    with the int8 per-block dequant folded in — no gathered
    ``[B, H_kv, T_cap, dh]`` layout ever reaches HBM. ``q [B, H, dh]``
    f32, ``tables [B, MB]`` int32, ``lengths [B]`` attendable positions
    (the engine passes ``lengths + 1``). Differential oracle:
    ``decode_attn(q, *vmap(gather_layer), lengths)`` — bit-identical at
    f32 under jit (tests/test_pallas_paged_attention.py)."""
    from ..ops.pallas_paged_attention import paged_decode_attn
    ks = None if pool.k_scale is None else pool.k_scale[layer]
    vs = None if pool.v_scale is None else pool.v_scale[layer]
    return paged_decode_attn(q, pool.k[layer], pool.v[layer], ks, vs,
                             tables, lengths, interpret=interpret)


def gather_layer(pool: PagedKV, layer: int, table: jax.Array):
    """One sequence's dequantized contiguous KV view for one layer:
    ``table [max_blocks]`` -> ``(k, v)`` each ``[H_kv, T_cap, dh]`` f32
    (``T_cap = max_blocks * block``). The gather itself is
    ``models.attention.gather_paged_kv`` — the attention read against a
    block table; this wrapper only adds the dtype story."""
    from ..models.attention import gather_paged_kv
    # "gather" tags the block-table read + dequant in traces/HLO
    # (utils/trace_analysis SCOPES: decode/gather, prefill/gather) —
    # the paged-KV traffic term the DECODE roofline prices
    with jax.named_scope("gather"):
        k, v = gather_paged_kv(pool.k[layer], pool.v[layer], table)
        if pool.k_scale is None:
            if k.dtype != jnp.float32:
                k = k.astype(jnp.float32)
                v = v.astype(jnp.float32)
            return k, v
        blk = pool.block_size
        # per-block scales -> per-position: [MB, Hkv] -> [Hkv, MB*blk]
        ks = jnp.repeat(pool.k_scale[layer][table].T, blk, axis=1)
        vs = jnp.repeat(pool.v_scale[layer][table].T, blk, axis=1)
        return (k.astype(jnp.float32) * ks[..., None],
                v.astype(jnp.float32) * vs[..., None])
