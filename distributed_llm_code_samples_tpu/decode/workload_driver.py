"""Trace replay: feed a workload trace into any serving target,
deterministically.

``runtime/workload.py`` defines what a workload IS (seeded arrivals,
heavy-tail lengths, sessions, tenants, the versioned trace file); this
module is the half that DRIVES one — into a single ``DecodeEngine``, an
in-process ``FleetRouter``, or a process-transport fleet (the router
API is transport-agnostic, so the driver never knows which). The CLI
surface is ``generate --trace FILE`` / ``--trace_gen SPEC``.

**Pacing.** Two clocks, one contract:

- ``pace="virtual"`` (the CPU tier-1 mode): trace time maps onto the
  target's scheduling rounds — an entry with offset ``t`` is submitted
  at the START of the first round ``r`` with ``r / steps_per_s >= t``.
  No wall clock anywhere in the loop, so the same ``(trace, seed)``
  yields byte-identical tokens, identical admission order, and
  identical ``workload`` records on every replay — **replay IS the
  determinism proof**, and chaos (``kill_worker`` mid-trace, deploys)
  composes on top because the router's round clock is the same clock.
- ``pace="wall"`` (chip runs): offsets are real seconds from replay
  start — the open-loop load a production fleet would see. Token
  identity still holds (sampling never reads the clock); admission
  order may legitimately vary with service speed, which is the point.

**Accounting** (schema v13): one ``workload`` record per ``log_every``
rounds plus a final one — trace identity, per-interval
offered/admitted, cumulative per-tenant {offered, completed, shed} —
through the target's existing ``TelemetryWriter`` (the emission rides
the writer thread; nothing here touches a compiled program, and the
zero-new-compiles-vs-hand-submission property is pinned by test).
Sheds (``AdmissionError``) are counted per tenant by the driver — the
router's shed record consumed the uid, but only the driver knows the
whole offered load.
"""

from __future__ import annotations

import time

from ..runtime.workload import materialize_prompt, tenant_key
from .engine import AdmissionError, DecodeEngine

# consecutive no-progress rounds with live work before the replay is
# declared stalled (mirrors DecodeEngine.run/FleetRouter.run's stall
# refusal; a few idle rounds are legitimate while the virtual clock
# walks toward the next arrival)
_STALL_ROUNDS = 64


class WorkloadDriver:
    """One trace replay against one target.

    ``target`` is a ``DecodeEngine`` or a ``FleetRouter`` (any
    transport). ``metrics`` is the writer the ``workload`` records ride
    (default: the router's own writer / the engine's) — per-request
    ``request``/``span`` records flow through the engines' writers as
    always; the driver adds only the workload plane."""

    def __init__(self, target, header: dict, entries: list[dict], *,
                 vocab: int, pace: str = "virtual",
                 steps_per_s: float = 8.0, log_every: int = 0,
                 metrics=None, autoscale=None, watch=None):
        if pace not in ("virtual", "wall"):
            raise ValueError(f"pace must be 'virtual' or 'wall', got "
                             f"{pace!r}")
        if steps_per_s <= 0:
            raise ValueError(f"steps_per_s must be > 0, got "
                             f"{steps_per_s}")
        self.target = target
        self.header = header
        self.entries = entries
        self.vocab = int(vocab)
        self.pace = pace
        self.steps_per_s = float(steps_per_s)
        self.log_every = int(log_every)
        self.is_fleet = not isinstance(target, DecodeEngine)
        self.metrics = metrics if metrics is not None else (
            target.metrics)
        # the trace identity every workload record pins
        self.trace = {"id": header["id"],
                      "version": header["trace_version"]}
        # driver-side books (the router/engine never see the whole
        # offered load — sheds consume nothing downstream)
        self.uid_tenant: dict[int, str] = {}
        self.offered: dict[str, int] = {}
        self.shed: dict[str, int] = {}
        # per-REASON shed book (round 20): the engine names why it
        # shed (queue_full / predicted_deadline_miss) and the raised
        # AdmissionError carries it — only the driver sees every shed
        self.shed_reasons: dict[str, int] = {}
        # closed-loop autoscaler (decode/autoscale.py), ticked between
        # rounds on the SAME round clock the chaos plan fires on — a
        # scale action counts as progress for the stall refusal (a
        # fleet mid-spawn is not stalled)
        if autoscale is not None and not self.is_fleet:
            raise ValueError("autoscale drives a fleet target only "
                             "(a single engine has no membership to "
                             "scale)")
        self.autoscale = autoscale
        # watchtower (runtime/watch.py), ticked between rounds AFTER
        # the autoscaler on the same round clock — detectors see the
        # round's post-scale truth, and the alert history inherits the
        # replay determinism the round clock gives every decision
        if watch is not None and not self.is_fleet:
            raise ValueError("watch drives a fleet target only (the "
                             "detectors read the router's digests)")
        self.watch = watch
        self.rounds = 0
        self._interval_offered = 0
        self._interval_admitted = 0
        self.total_offered = 0
        self.total_admitted = 0

    # -- target shims (engine vs router) -------------------------------

    def _has_work(self) -> bool:
        if self.is_fleet:
            return self.target.has_work
        return bool(self.target.waiting or self.target.active)

    def _step(self) -> bool:
        return self.target.step()

    def _pending_chaos(self) -> bool:
        if self.is_fleet:
            return self.target._pending_kills()
        return False

    def _submit(self, entry: dict) -> None:
        prompt = materialize_prompt(self.header, entry, self.vocab)
        tk = tenant_key(entry.get("tenant"))
        self.offered[tk] = self.offered.get(tk, 0) + 1
        self._interval_offered += 1
        self.total_offered += 1
        try:
            if self.is_fleet:
                uid = self.target.submit(prompt, int(entry["max_new"]),
                                         session=entry.get("session"),
                                         tenant=entry.get("tenant"))
            else:
                uid = self.target.submit(prompt, int(entry["max_new"]),
                                         tenant=entry.get("tenant"))
        except AdmissionError as e:
            self.shed[tk] = self.shed.get(tk, 0) + 1
            r = getattr(e, "reason", "queue_full")
            self.shed_reasons[r] = self.shed_reasons.get(r, 0) + 1
            return
        self.uid_tenant[uid] = tk
        self._interval_admitted += 1
        self.total_admitted += 1

    def _completed_by_tenant(self) -> dict[str, int]:
        """Cumulative per-tenant completions — engine-side a dict
        read; fleet-side one ``results`` round-trip per alive worker
        (cadence-only, the emit_decode stance)."""
        finished = (self.target.results() if self.is_fleet
                    else self.target.finished)
        done: dict[str, int] = {}
        for uid in finished:
            tk = self.uid_tenant.get(int(uid))
            if tk is not None:
                done[tk] = done.get(tk, 0) + 1
        return done

    def _tenants_block(self, completed: dict) -> dict:
        """The cumulative per-tenant book — ONE builder for the
        workload records and the run summary."""
        return {
            t: {"offered": self.offered.get(t, 0),
                "completed": completed.get(t, 0),
                "shed": self.shed.get(t, 0)}
            for t in sorted(set(self.offered) | set(completed)
                            | set(self.shed))
        }

    def _emit_workload(self, completed: dict | None = None) -> None:
        if self.metrics is None:
            return
        if completed is None:
            completed = self._completed_by_tenant()
        self.metrics.workload({
            "step": self.rounds,
            "trace": dict(self.trace),
            "offered": self._interval_offered,
            "admitted": self._interval_admitted,
            "tenants": self._tenants_block(completed),
        })
        self._interval_offered = 0
        self._interval_admitted = 0

    def _emit_decode_cadence(self) -> None:
        """Per-engine decode cadence records (the router/engine's
        ``run()`` owns this normally; the driver steps manually, so it
        owns the cadence here)."""
        if self.is_fleet:
            self.target._emit_decode_records()
        elif self.metrics is not None:
            now = time.perf_counter()
            delta = self.target.tokens_generated - self._last_tokens
            dt = max(now - self._last_t, 1e-9)
            tps = round(delta / dt, 2) if delta > 0 else None
            self.metrics.decode(self.target.telemetry_record(tps))
            self._last_t, self._last_tokens = \
                now, self.target.tokens_generated

    # -- the replay loop ----------------------------------------------

    def run(self) -> dict:
        """Drain the whole trace; returns the workload summary (the
        CLI payload's ``workload`` block)."""
        entries = self.entries
        i = 0
        stalled = 0
        t0 = time.monotonic()
        self._last_t = time.perf_counter()
        self._last_tokens = (0 if self.is_fleet
                             else self.target.tokens_generated)
        while i < len(entries) or self._has_work():
            now_s = (self.rounds / self.steps_per_s
                     if self.pace == "virtual"
                     else time.monotonic() - t0)
            while (i < len(entries)
                   and float(entries[i]["t_offset_s"]) <= now_s + 1e-9):
                self._submit(entries[i])
                i += 1
            did = self._step()
            if self.autoscale is not None:
                # between-rounds controller tick, on the round clock
                # (deterministic under virtual pacing); a scale action
                # is progress — the stall refusal must not fire while
                # a replacement worker is being spawned and warmed
                did = bool(self.autoscale.tick()) or did
            if self.watch is not None:
                self.watch.tick()
            self.rounds += 1
            if self.log_every > 0 and self.rounds % self.log_every == 0:
                self._emit_decode_cadence()
                self._emit_workload()
            if did or not self._has_work():
                stalled = 0
            elif i >= len(entries) and not self._pending_chaos():
                # live work, nothing left to arrive, no chaos pending,
                # and the target ran nothing — the run()-stall refusal
                stalled += 1
                if stalled >= _STALL_ROUNDS:
                    raise RuntimeError(
                        "trace replay stalled: live requests but the "
                        "target ran no work for "
                        f"{_STALL_ROUNDS} rounds")
            if (self.pace == "wall" and i < len(entries)
                    and not self._has_work()):
                # idle until the next arrival — don't busy-spin a real
                # clock (the virtual clock advances by round instead)
                wait = float(entries[i]["t_offset_s"]) \
                    - (time.monotonic() - t0)
                if wait > 0:
                    time.sleep(min(wait, 0.05))
        # ONE drain-end completions sweep feeds the final workload
        # record AND the summary (under the process transport each
        # sweep is a results round-trip per alive worker)
        completed = self._completed_by_tenant()
        self._emit_decode_cadence()
        self._emit_workload(completed)
        if self.is_fleet:
            # the drain-end ops-plane flush FleetRouter.run performs
            # (the driver replaced run(), so it owes the same epilogue)
            self.target.emit_transport_stats()
            self.target._publish_status(force=True)
        return {
            "trace": dict(self.trace),
            "pace": self.pace,
            "steps_per_s": (self.steps_per_s
                            if self.pace == "virtual" else None),
            "rounds": self.rounds,
            "entries": len(entries),
            "offered": self.total_offered,
            "admitted": self.total_admitted,
            "shed": self.total_offered - self.total_admitted,
            "shed_reasons": dict(sorted(self.shed_reasons.items())),
            "tenants": self._tenants_block(completed),
        }


def replay_trace(target, header: dict, entries: list[dict], *,
                 vocab: int, pace: str = "virtual",
                 steps_per_s: float = 8.0, log_every: int = 0,
                 metrics=None, autoscale=None, watch=None) -> dict:
    """One-call replay (see ``WorkloadDriver``): drive ``entries``
    into ``target`` and return the workload summary. ``autoscale`` is
    an ``AutoscaleController`` and ``watch`` a ``Watchtower``, each
    ticked between rounds (fleet targets only)."""
    return WorkloadDriver(target, header, entries, vocab=vocab,
                          pace=pace, steps_per_s=steps_per_s,
                          log_every=log_every, metrics=metrics,
                          autoscale=autoscale, watch=watch).run()
